"""Replicated log: the consensus layer under the FSM.

The reference uses hashicorp/raft with a boltdb log store and an in-memory
option for dev/tests (nomad/server.go:91-95 raftInmem, nomad/raft_rpc.go).
This module provides the same shape:

- ``RaftLog``        — the log interface the server applies through.
- ``InmemLog``       — in-memory log (tests / dev mode), like raftInmem.
- ``FileLog``        — single-voter durable WAL with length-prefixed pickled
                       entries, fsync batching, and snapshot+truncate —
                       filling boltdb's role.
- ``ReplicatedLog``  — leader-append + follower-replication over a
                       transport callable; majority commit.  Single-voter
                       by default; multi-server replication uses the RPC
                       layer's raft channel (server/rpc.py).

Leadership is modeled explicitly (leader_ch notifications) so the leader
loop (server/leader.py-equivalent logic inside server.py) can
enable/disable the broker exactly as the reference does
(nomad/leader.go:28-120).
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Callable, List, Optional, Tuple

from .fsm import FSM, MessageType

_LEN = struct.Struct("<Q")

# Number of FSM snapshots retained (reference: server.go:51
# snapshotsRetained = 2).
SNAPSHOTS_RETAINED = 2


class RaftLog:
    """Single-voter commit path: append → fsync (durable impls) → apply."""

    def __init__(self, fsm: FSM):
        self.fsm = fsm
        # RLock: fsm.apply runs under this lock and its hooks may consult
        # applied_index() on the same thread.
        self._l = threading.RLock()
        self._last_index = 0
        self._leader = True  # single-voter: always leader
        self._leader_listeners: List[Callable[[bool], None]] = []

    # -- leadership --------------------------------------------------------

    def is_leader(self) -> bool:
        return self._leader

    def notify_leadership(self, cb: Callable[[bool], None]) -> None:
        self._leader_listeners.append(cb)
        cb(self._leader)

    def _set_leader(self, leader: bool) -> None:
        if leader == self._leader:
            return
        self._leader = leader
        for cb in self._leader_listeners:
            cb(leader)

    # -- log ---------------------------------------------------------------

    def applied_index(self) -> int:
        with self._l:
            return self._last_index

    def apply(self, msg_type: MessageType, payload: dict):
        """Append + commit + apply one entry; returns (result, index)
        (the raftApply path, nomad/rpc.go raftApply → fsm.Apply).

        The FSM apply runs under the log lock so entries reach the state
        store in strict index order and applied_index() never reports an
        entry whose state is not yet visible."""
        with self._l:
            if not self._leader:
                raise NotLeaderError("not the leader")
            self._last_index += 1
            index = self._last_index
            self._persist(index, msg_type, payload)
            result = self.fsm.apply(index, msg_type, payload)
        return result, index

    def _persist(self, index: int, msg_type: MessageType, payload: dict) -> None:
        pass  # in-memory: nothing to do

    def snapshot(self) -> None:
        pass

    def close(self) -> None:
        pass


class NotLeaderError(Exception):
    pass


class InmemLog(RaftLog):
    """In-memory log for dev/tests (raftInmem analogue)."""


class FileLog(RaftLog):
    """Durable single-voter WAL + snapshots.

    Layout in ``data_dir``:
      wal.log         — length-prefixed pickled (index, type, payload)
      snapshot-<idx>  — FSM snapshot taken at <idx>
    Recovery: newest snapshot restore, then WAL replay of entries > idx.
    """

    def __init__(self, fsm: FSM, data_dir: str, fsync: bool = True):
        super().__init__(fsm)
        self.data_dir = data_dir
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)
        self.wal_path = os.path.join(data_dir, "wal.log")
        self._recover()
        self._fh = open(self.wal_path, "ab")

    # -- recovery ----------------------------------------------------------

    def _snapshot_files(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.data_dir):
            if name.startswith("snapshot-"):
                try:
                    idx = int(name.split("-", 1)[1])
                except ValueError:
                    continue
                out.append((idx, os.path.join(self.data_dir, name)))
        return sorted(out)

    def _recover(self) -> None:
        snap_idx = 0
        snaps = self._snapshot_files()
        if snaps:
            snap_idx, path = snaps[-1]
            with open(path, "rb") as fh:
                self.fsm.restore(fh.read())
            self._last_index = snap_idx

        if not os.path.exists(self.wal_path):
            return
        good_offset = 0
        torn = False
        wal_size = os.path.getsize(self.wal_path)
        with open(self.wal_path, "rb") as fh:
            while True:
                header = fh.read(_LEN.size)
                if len(header) < _LEN.size:
                    torn = len(header) > 0
                    break
                (length,) = _LEN.unpack(header)
                if length > wal_size - fh.tell():
                    # length prefix runs past EOF — torn tail (don't even
                    # attempt the read: a garbage prefix can claim GBs)
                    torn = True
                    break
                blob = fh.read(length)
                if len(blob) < length:
                    torn = True
                    break  # torn tail write — discard
                index, msg_type, payload = pickle.loads(blob)
                good_offset = fh.tell()
                if index <= snap_idx:
                    continue
                self.fsm.apply(index, MessageType(msg_type), payload)
                self._last_index = index
        # Truncate the torn tail so subsequent appends follow the last good
        # record — otherwise new fsynced entries land after garbage and are
        # unreachable on the next replay (silent loss).
        if torn:
            with open(self.wal_path, "r+b") as fh:
                fh.truncate(good_offset)

    # -- persistence -------------------------------------------------------

    def _persist(self, index: int, msg_type: MessageType, payload: dict) -> None:
        blob = pickle.dumps((index, int(msg_type), payload),
                            protocol=pickle.HIGHEST_PROTOCOL)
        self._fh.write(_LEN.pack(len(blob)))
        self._fh.write(blob)
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def snapshot(self) -> None:
        """Write an FSM snapshot and truncate the WAL (fsm.go:568 +
        snapshotsRetained=2)."""
        with self._l:
            index = self._last_index
            blob = self.fsm.snapshot()
            path = os.path.join(self.data_dir, f"snapshot-{index}")
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            # Truncate the WAL: all entries ≤ index are in the snapshot.
            self._fh.close()
            self._fh = open(self.wal_path, "wb")
            # Retain only the most recent snapshots.
            snaps = self._snapshot_files()
            for old_idx, old_path in snaps[:-SNAPSHOTS_RETAINED]:
                os.unlink(old_path)

    def close(self) -> None:
        self._fh.close()


# ---------------------------------------------------------------------------
# Multi-server replication (hashicorp/raft equivalent)
# ---------------------------------------------------------------------------


class MultiRaft(RaftLog):
    """Leader election + log replication across servers over the RPC raft
    channel (reference: hashicorp/raft beneath nomad/server.go setupRaft,
    transported via raft_rpc.go RaftLayer on the shared RPC port).

    The protocol is Raft's core: randomized election timeouts, term-voted
    RequestVote, AppendEntries with prev-entry consistency check and
    follower truncation, majority commit, ordered FSM apply.  Entries carry
    pickled payloads (trusted intra-cluster channel, as the reference
    trusts msgpack-encoded structs between its own servers).

    ``apply`` blocks until the entry is committed by a majority and applied
    locally, then returns (result, index) — identical semantics to the
    single-voter path so the Server code above it does not change.
    """

    HEARTBEAT_INTERVAL = 0.08
    ELECTION_TIMEOUT = (0.25, 0.5)

    def __init__(self, fsm: FSM, my_addr: str, pool,
                 logger=None):
        super().__init__(fsm)
        import logging as _logging
        import random

        self.logger = logger or _logging.getLogger("nomad_tpu.raft")
        self.my_addr = my_addr
        self.pool = pool
        self._rand = random.Random(hash(my_addr) & 0xFFFF)
        self._leader = False  # starts as follower, unlike single-voter

        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader_addr: Optional[str] = None
        # log[i] = (term, msg_type_value, payload_bytes); 1-indexed via offset
        self.log: List[Tuple[int, int, bytes]] = []
        self.commit_index = 0
        self.state = "follower"
        self.peers: List[str] = [my_addr]

        self._apply_cond = threading.Condition(self._l)
        self._last_contact = 0.0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._peer_match = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        import time as _time
        self._last_contact = _time.monotonic()
        t = threading.Thread(target=self._election_loop, name="raft-election",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        self._stop.set()

    def set_peers(self, peers: List[str]) -> None:
        with self._l:
            self.peers = sorted(set(peers) | {self.my_addr})

    def _quorum(self) -> int:
        return len(self.peers) // 2 + 1

    # -- RPC entry (RPCServer.raft_handler) --------------------------------

    def handle_message(self, msg: dict) -> dict:
        kind = msg.get("kind")
        if kind == "request_vote":
            return self._on_request_vote(msg)
        if kind == "append_entries":
            return self._on_append_entries(msg)
        raise ValueError(f"unknown raft message kind {kind!r}")

    # -- election ----------------------------------------------------------

    def _election_timeout(self) -> float:
        lo, hi = self.ELECTION_TIMEOUT
        return lo + self._rand.random() * (hi - lo)

    def _election_loop(self) -> None:
        import time as _time
        timeout = self._election_timeout()
        while not self._stop.is_set():
            _time.sleep(0.02)
            with self._l:
                is_leader = self.state == "leader"
                since = _time.monotonic() - self._last_contact
            if is_leader:
                self._send_heartbeats()
                _time.sleep(self.HEARTBEAT_INTERVAL)
                continue
            if since >= timeout:
                self._run_election()
                timeout = self._election_timeout()

    def _run_election(self) -> None:
        import time as _time
        with self._l:
            self.state = "candidate"
            self.term += 1
            term = self.term
            self.voted_for = self.my_addr
            self.leader_addr = None
            last_index = len(self.log)
            last_term = self.log[-1][0] if self.log else 0
            peers = [p for p in self.peers if p != self.my_addr]
            self._last_contact = _time.monotonic()
        votes = 1
        lock = threading.Lock()
        done = threading.Event()

        def ask(peer):
            nonlocal votes
            try:
                from .rpc import RPC_RAFT
                reply = self.pool.call(peer, "raft", {
                    "kind": "request_vote", "term": term,
                    "candidate": self.my_addr,
                    "last_log_index": last_index, "last_log_term": last_term,
                }, channel=RPC_RAFT, timeout=0.5)
            except Exception:
                return
            with lock:
                if reply.get("granted"):
                    votes += 1
                    if votes >= self._quorum():
                        done.set()
            with self._l:
                if reply.get("term", 0) > self.term:
                    self._step_down(reply["term"])
                    done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True)
                   for p in peers]
        for t in threads:
            t.start()
        if len(self.peers) == 1:
            done.set()
        done.wait(timeout=0.6)
        with self._l:
            if self.state == "candidate" and self.term == term \
                    and votes >= self._quorum():
                self.state = "leader"
                self.leader_addr = self.my_addr
                self.logger.info("raft: %s won election for term %d",
                                 self.my_addr, term)
        if self.is_raft_leader():
            self._send_heartbeats()
            self._set_leader(True)

    def is_raft_leader(self) -> bool:
        with self._l:
            return self.state == "leader"

    def _step_down(self, term: int) -> None:
        # caller holds self._l
        was_leader = self.state == "leader"
        self.term = max(self.term, term)
        self.state = "follower"
        self.voted_for = None
        if was_leader:
            threading.Thread(target=self._set_leader, args=(False,),
                             daemon=True).start()

    def _on_request_vote(self, msg: dict) -> dict:
        import time as _time
        with self._l:
            if msg["term"] < self.term:
                return {"granted": False, "term": self.term}
            if msg["term"] > self.term:
                self._step_down(msg["term"])
            up_to_date = (
                msg["last_log_term"], msg["last_log_index"]
            ) >= (self.log[-1][0] if self.log else 0, len(self.log))
            if up_to_date and self.voted_for in (None, msg["candidate"]):
                self.voted_for = msg["candidate"]
                self._last_contact = _time.monotonic()
                return {"granted": True, "term": self.term}
            return {"granted": False, "term": self.term}

    # -- replication -------------------------------------------------------

    def _send_heartbeats(self) -> None:
        self._replicate_round([])

    def _replicate_round(self, new_entries: List[Tuple[int, int, bytes]],
                         ) -> bool:
        """Send AppendEntries to every peer; True if majority acked.

        Simplification vs full Raft: each round ships the entries the
        leader believes the follower is missing based on the follower's
        acked index returned in the previous reply (stored per-peer)."""
        with self._l:
            term = self.term
            peers = [p for p in self.peers if p != self.my_addr]
            commit = self.commit_index
            log_snapshot = list(self.log)
        if not peers:
            return True
        acks = 1
        lock = threading.Lock()
        done = threading.Event()
        quorum = self._quorum()

        def send(peer):
            nonlocal acks
            match = self._peer_match.get(peer, 0)
            while True:
                entries = log_snapshot[match:]
                prev_index = match
                prev_term = log_snapshot[match - 1][0] if match > 0 else 0
                try:
                    from .rpc import RPC_RAFT
                    reply = self.pool.call(peer, "raft", {
                        "kind": "append_entries", "term": term,
                        "leader": self.my_addr,
                        "prev_log_index": prev_index,
                        "prev_log_term": prev_term,
                        "entries": entries,
                        "leader_commit": commit,
                    }, channel=RPC_RAFT, timeout=2.0)
                except Exception:
                    return
                if reply.get("term", 0) > term:
                    with self._l:
                        self._step_down(reply["term"])
                    done.set()
                    return
                if reply.get("success"):
                    self._peer_match[peer] = len(log_snapshot)
                    with lock:
                        acks += 1
                        if acks >= quorum:
                            done.set()
                    return
                # consistency check failed: back off and retry
                if match == 0:
                    return
                match = max(0, reply.get("match", match - 1))

        threads = [threading.Thread(target=send, args=(p,), daemon=True)
                   for p in peers]
        for t in threads:
            t.start()
        done.wait(timeout=3.0)
        with lock:
            return acks >= quorum

    def _on_append_entries(self, msg: dict) -> dict:
        import time as _time
        with self._l:
            if msg["term"] < self.term:
                return {"success": False, "term": self.term}
            if msg["term"] > self.term or self.state != "follower":
                self._step_down(msg["term"])
            self.term = msg["term"]
            self.leader_addr = msg["leader"]
            self._last_contact = _time.monotonic()

            prev_index = msg["prev_log_index"]
            prev_term = msg["prev_log_term"]
            if prev_index > len(self.log):
                return {"success": False, "term": self.term,
                        "match": len(self.log)}
            if prev_index > 0 and self.log[prev_index - 1][0] != prev_term:
                return {"success": False, "term": self.term,
                        "match": max(0, prev_index - 1)}
            # truncate conflicts, append new
            entries = [tuple(e) for e in msg["entries"]]
            self.log = self.log[:prev_index] + entries
            # advance commit + apply
            new_commit = min(msg["leader_commit"], len(self.log))
            self._apply_committed(new_commit)
            return {"success": True, "term": self.term,
                    "match": len(self.log)}

    def _apply_committed(self, new_commit: int) -> None:
        # caller holds self._l
        while self.commit_index < new_commit:
            self.commit_index += 1
            term, mt, blob = self.log[self.commit_index - 1]
            payload = pickle.loads(blob)
            self._last_index = self.commit_index
            try:
                self.fsm.apply(self.commit_index, MessageType(mt), payload)
            except Exception:
                self.logger.exception("raft: fsm apply failed at %d",
                                      self.commit_index)

    # -- the apply path ----------------------------------------------------

    def apply(self, msg_type: MessageType, payload: dict):
        with self._l:
            if self.state != "leader":
                raise NotLeaderError(self.leader_addr or "")
            blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            self.log.append((self.term, msg_type.value, blob))
            index = len(self.log)
        ok = self._replicate_round([])
        with self._l:
            if not ok or self.state != "leader":
                raise NotLeaderError(self.leader_addr or "")
            result = None
            if self.commit_index < index:
                # commit everything up to and including this entry
                target = index
                while self.commit_index < target:
                    self.commit_index += 1
                    t_, mt_, blob_ = self.log[self.commit_index - 1]
                    p_ = pickle.loads(blob_)
                    self._last_index = self.commit_index
                    r_ = self.fsm.apply(self.commit_index, MessageType(mt_), p_)
                    if self.commit_index == target:
                        result = r_
            return result, index
