"""Server runtime: composes the log/FSM, broker, plan pipeline, workers,
heartbeats, periodic dispatch, and GC into the control plane, and exposes
the RPC endpoint surface as methods
(reference: nomad/server.go:78-305, nomad/leader.go:28-641,
nomad/*_endpoint.go).
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs import structs as s
from .blocked_evals import BlockedEvals
from .core_sched import CoreScheduler
from .eval_broker import EvalBroker
from .fsm import FSM, MessageType, TimeTable
from .heartbeat import HeartbeatTimers
from .periodic import PeriodicDispatch, derive_job
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .raft import FileLog, InmemLog, RaftLog
from .worker import BatchWorker, Worker


@dataclass
class ServerConfig:
    """(reference: nomad/config.go)."""

    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = "server-1"
    rpc_advertise: str = "127.0.0.1:4647"
    data_dir: str = ""                  # empty → in-memory log (dev mode)
    # RPC / clustering (nomad/config.go RPCAddr, BootstrapExpect, serf join)
    enable_rpc: bool = False            # start the TCP RPC listener
    rpc_bind: str = "127.0.0.1"
    rpc_port: int = 0                   # 0 → ephemeral
    bootstrap_expect: int = 1
    start_join: List[str] = field(default_factory=list)
    num_schedulers: int = 1
    use_tpu_batch_worker: bool = False
    batch_size: int = 64
    eval_nack_timeout: float = 60.0
    eval_delivery_limit: int = 3
    min_heartbeat_ttl: float = 10.0
    max_heartbeats_per_second: float = 50.0
    failed_eval_unblock_interval: float = 60.0
    eval_gc_interval: float = 300.0
    enabled_schedulers: List[str] = field(default_factory=lambda: [
        s.JOB_TYPE_SERVICE, s.JOB_TYPE_BATCH, s.JOB_TYPE_SYSTEM, s.JOB_TYPE_CORE])


class Server:
    """A single control-plane server (nomad/server.go:78 Server)."""

    def __init__(self, config: Optional[ServerConfig] = None,
                 logger: Optional[logging.Logger] = None):
        self.config = config or ServerConfig()
        self.logger = logger or logging.getLogger("nomad_tpu.server")
        # Must precede raft construction: WAL replay fires FSM hooks that
        # consult leadership.
        self._leader = False
        self._shutdown = threading.Event()

        self.eval_broker = EvalBroker(
            nack_timeout=self.config.eval_nack_timeout,
            delivery_limit=self.config.eval_delivery_limit)
        self.blocked_evals = BlockedEvals(self.eval_broker)
        self.plan_queue = PlanQueue()
        self.time_table = TimeTable()

        self.fsm = FSM(
            logger=self.logger,
            on_eval_update=self._fsm_eval_updated,
            on_unblock=self._fsm_unblock,
            on_job_register=self._fsm_job_registered,
            on_job_deregister=self._fsm_job_deregistered,
        )
        if self.config.data_dir:
            self.raft: RaftLog = FileLog(self.fsm, self.config.data_dir)
        else:
            self.raft = InmemLog(self.fsm)

        self.plan_applier = PlanApplier(self.plan_queue, self.raft, self.logger)
        self.heartbeat = HeartbeatTimers(
            on_expire=self._heartbeat_expired,
            min_ttl=self.config.min_heartbeat_ttl,
            max_per_second=self.config.max_heartbeats_per_second,
            logger=self.logger)
        self.periodic = PeriodicDispatch(self._periodic_dispatch, self.logger)

        self.workers: List[Worker] = []
        self._reaper_threads: List[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Boot: start workers and acquire (single-voter) leadership
        (server.go:272 setupWorkers + leader.go:28 monitorLeadership)."""
        for i in range(self.config.num_schedulers):
            if self.config.use_tpu_batch_worker:
                worker: Worker = BatchWorker(
                    self.eval_broker, self.plan_queue, self.raft,
                    blocked_evals=self.blocked_evals, logger=self.logger,
                    time_table=self.time_table,
                    max_batch=self.config.batch_size)
            else:
                worker = Worker(
                    self.eval_broker, self.plan_queue, self.raft,
                    schedulers=self.config.enabled_schedulers,
                    blocked_evals=self.blocked_evals, logger=self.logger,
                    time_table=self.time_table)
            self.workers.append(worker)
        self.raft.notify_leadership(self._leadership_changed)
        for worker in self.workers:
            worker.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        for worker in self.workers:
            worker.stop()
        self.plan_applier.stop()
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.plan_queue.set_enabled(False)
        self.periodic.set_enabled(False)
        self.heartbeat.set_enabled(False)
        self.raft.close()

    def is_leader(self) -> bool:
        return self._leader

    @property
    def state(self):
        return self.fsm.state

    # -- leadership --------------------------------------------------------

    def _leadership_changed(self, leader: bool) -> None:
        if leader:
            self._establish_leadership()
        else:
            self._revoke_leadership()

    def _establish_leadership(self) -> None:
        """(leader.go:110 establishLeadership)."""
        self._leader = True
        self.eval_broker.set_enabled(True)
        self.plan_queue.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.periodic.set_enabled(True)
        self.heartbeat.set_enabled(True)
        self.plan_applier.start()
        self._restore_evals()
        self._restore_periodic_dispatcher()
        self._start_reapers()

    def _revoke_leadership(self) -> None:
        self._leader = False
        self.eval_broker.set_enabled(False)
        self.plan_queue.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.periodic.set_enabled(False)
        self.heartbeat.set_enabled(False)
        self.plan_applier.stop()

    def _restore_evals(self) -> None:
        """Re-enqueue pending and re-block blocked evals from state
        (leader.go:195 restoreEvals)."""
        for ev in self.state.evals(None):
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    def _restore_periodic_dispatcher(self) -> None:
        """Track periodic jobs + catch up missed launches (leader.go:150)."""
        now = time.time()
        for job in self.state.jobs_by_periodic(None, True):
            self.periodic.add(job)
            launch = self.state.periodic_launch_by_id(None, job.id)
            last = launch.launch if launch else 0.0
            nxt = job.periodic.next(last)
            if last and 0 < nxt <= now:
                self.periodic.force_run(job.id)

    def _start_reapers(self) -> None:
        """Duplicate-blocked-eval reaper, failed-eval unblock, periodic GC
        core evals (leader.go:157-193)."""

        def dup_reaper():
            while self._leader and not self._shutdown.is_set():
                dups = self.blocked_evals.get_duplicates(timeout=0.5)
                if not dups:
                    continue
                cancelled = []
                for dup in dups:
                    ev = dup.copy()
                    ev.status = s.EVAL_STATUS_CANCELLED
                    ev.status_description = (
                        f"existing blocked evaluation exists for job {ev.job_id!r}")
                    cancelled.append(ev)
                self.raft.apply(MessageType.EVAL_UPDATE, {"evals": cancelled})

        def failed_unblocker():
            while self._leader and not self._shutdown.is_set():
                self._shutdown.wait(self.config.failed_eval_unblock_interval)
                if self._leader and not self._shutdown.is_set():
                    self.blocked_evals.unblock_failed()

        def gc_scheduler():
            while self._leader and not self._shutdown.is_set():
                self._shutdown.wait(self.config.eval_gc_interval)
                if not (self._leader and not self._shutdown.is_set()):
                    return
                for core_job in (s.CORE_JOB_EVAL_GC, s.CORE_JOB_JOB_GC,
                                 s.CORE_JOB_NODE_GC):
                    self._create_core_eval(core_job)

        for target in (dup_reaper, failed_unblocker, gc_scheduler):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._reaper_threads.append(t)

    def _create_core_eval(self, core_job: str) -> None:
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=s.JOB_MAX_PRIORITY,
            type=s.JOB_TYPE_CORE, triggered_by=s.EVAL_TRIGGER_SCHEDULED,
            job_id=core_job, status=s.EVAL_STATUS_PENDING)
        self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})

    # -- FSM hooks (leader side) ------------------------------------------

    def _fsm_eval_updated(self, ev: s.Evaluation) -> None:
        if not self._leader:
            return
        self.time_table.witness(self.raft.applied_index())
        if ev.should_enqueue():
            self.eval_broker.enqueue(ev)
        elif ev.should_block():
            self.blocked_evals.block(ev)
        elif (ev.status == s.EVAL_STATUS_COMPLETE
              and not ev.failed_tg_allocs):
            # Successful eval → untrack any blocked eval for the job
            # (fsm.go applyUpdateEval).
            self.blocked_evals.untrack(ev.job_id)

    def _fsm_unblock(self, computed_class: str, index: int) -> None:
        if self._leader:
            self.blocked_evals.unblock(computed_class, index)

    def _fsm_job_registered(self, job: s.Job) -> None:
        if self._leader and job.is_periodic() and not job.stopped():
            self.periodic.add(job)

    def _fsm_job_deregistered(self, job_id: str) -> None:
        if self._leader:
            self.periodic.remove(job_id)

    # -- heartbeat / periodic callbacks ------------------------------------

    def _heartbeat_expired(self, node_id: str) -> None:
        """Missed heartbeat ⇒ node down ⇒ node evals (heartbeat.go:86)."""
        try:
            self.node_update_status(node_id, s.NODE_STATUS_DOWN)
        except KeyError:
            pass

    def _periodic_dispatch(self, parent: s.Job, derived: s.Job,
                           launch_time: float) -> None:
        """Register the derived child job + record the launch
        (periodic.go:435 createEval)."""
        if parent.periodic and parent.periodic.prohibit_overlap:
            # A previous launch is still active if any derived child job
            # (id prefix "<parent>/periodic-") has a live eval or alloc
            # (periodic.go shouldDispatch via RunningChildren).
            from .periodic import PERIODIC_LAUNCH_SUFFIX
            prefix = parent.id + PERIODIC_LAUNCH_SUFFIX
            for child in self.state.jobs_by_id_prefix(None, prefix):
                if any(not ev.terminal_status()
                       for ev in self.state.evals_by_job(None, child.id)):
                    return
                if any(not a.terminal_status()
                       for a in self.state.allocs_by_job(None, child.id)):
                    return
        self.job_register(derived)
        self.raft.apply(MessageType.PERIODIC_LAUNCH_UPSERT,
                        {"job_id": parent.id, "launch": launch_time})

    # ======================================================================
    # RPC endpoint surface (reference: nomad/*_endpoint.go)
    # ======================================================================

    # -- Job ---------------------------------------------------------------

    def job_register(self, job: s.Job) -> Tuple[int, str]:
        """(job_endpoint.go:47 Register): validate → log JobRegister → eval
        unless periodic/parameterized.  Returns (modify_index, eval_id)."""
        job = job.copy()
        job.canonicalize()
        problems = job.validate()
        if problems:
            raise ValueError("job validation failed: " + "; ".join(problems))

        _, index = self.raft.apply(MessageType.JOB_REGISTER, {"job": job})

        eval_id = ""
        if not job.is_periodic() and not job.is_parameterized():
            ev = s.Evaluation(
                id=s.generate_uuid(),
                priority=job.priority,
                type=job.type,
                triggered_by=s.EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id,
                job_modify_index=index,
                status=s.EVAL_STATUS_PENDING,
            )
            _, eval_index = self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
            eval_id = ev.id
        return index, eval_id

    def job_deregister(self, job_id: str, purge: bool = True) -> Tuple[int, str]:
        """(job_endpoint.go Deregister)."""
        job = self.state.job_by_id(None, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        _, index = self.raft.apply(MessageType.JOB_DEREGISTER,
                                   {"job_id": job_id, "purge": purge})
        eval_id = ""
        if not job.is_periodic() and not job.is_parameterized():
            ev = s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=s.EVAL_TRIGGER_JOB_DEREGISTER, job_id=job_id,
                job_modify_index=index, status=s.EVAL_STATUS_PENDING)
            self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
            eval_id = ev.id
        return index, eval_id

    def job_list(self) -> List[s.Job]:
        return self.state.jobs(None)

    def job_get(self, job_id: str) -> Optional[s.Job]:
        return self.state.job_by_id(None, job_id)

    def job_summary(self, job_id: str) -> Optional[s.JobSummary]:
        return self.state.job_summary_by_id(None, job_id)

    def job_allocations(self, job_id: str, all_allocs: bool = False) -> List[s.Allocation]:
        return self.state.allocs_by_job(None, job_id, all_allocs)

    def job_evaluations(self, job_id: str) -> List[s.Evaluation]:
        return self.state.evals_by_job(None, job_id)

    def job_plan(self, job: s.Job, diff: bool = True) -> s.JobPlanResponse:
        """Dry-run scheduling (job_endpoint.go:~490 Plan): run the scheduler
        synchronously against a snapshot with a no-op planner, returning the
        annotated job diff + placement forensics (nothing is committed)."""
        from ..scheduler import Harness, new_scheduler
        from ..scheduler.annotate import annotate
        from ..structs.diff import job_diff

        old_job = self.state.job_by_id(None, job.id)
        job = job.copy()
        job.canonicalize()
        snap = self.state.snapshot()
        index = self.raft.applied_index() + 1
        snap.upsert_job(index, job)

        harness = Harness(snap)
        harness._next_index = index + 1
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            job_modify_index=index, status=s.EVAL_STATUS_PENDING,
            annotate_plan=True)
        sched = new_scheduler(job.type, self.logger, snap.snapshot(), harness)
        sched.process(ev)
        plan = harness.plans[0] if harness.plans else ev.make_plan(job)

        # The scheduler records placement forensics on a *copy* of the eval
        # handed to Planner.UpdateEval (scheduler/util.go setStatus) — read
        # the updated eval from the harness, like job_endpoint.go Plan does.
        updated = next((e for e in reversed(harness.evals) if e.id == ev.id), ev)
        resp = s.JobPlanResponse(
            annotations=plan.annotations,
            failed_tg_allocs=dict(updated.failed_tg_allocs),
            job_modify_index=old_job.job_modify_index if old_job else 0,
            created_evals=list(harness.create_evals))
        if diff:
            resp.diff = job_diff(old_job, job)
            annotate(resp.diff, plan.annotations)
        if job.is_periodic():
            resp.next_periodic_launch = job.periodic.next(s.now())
        return resp

    def periodic_force(self, job_id: str) -> Optional[s.Job]:
        return self.periodic.force_run(job_id)

    def job_evaluate(self, job_id: str) -> Tuple[int, str]:
        """Force a new evaluation for an existing job
        (job_endpoint.go Evaluate)."""
        job = self.state.job_by_id(None, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        if job.is_periodic():
            raise ValueError("can't evaluate periodic job")
        if job.is_parameterized():
            raise ValueError("can't evaluate parameterized job")
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            job_modify_index=job.modify_index, status=s.EVAL_STATUS_PENDING)
        _, index = self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
        return index, ev.id

    def job_dispatch(self, job_id: str, payload: bytes,
                     meta: Dict[str, str]) -> Tuple[int, str, str]:
        """Dispatch an instance of a parameterized job
        (job_endpoint.go Dispatch): validate meta keys against the
        parameterized config, derive a child job carrying the payload,
        register it and create its eval.  Returns
        (index, dispatched_job_id, eval_id)."""
        parent = self.state.job_by_id(None, job_id)
        if parent is None:
            raise KeyError(f"job not found: {job_id}")
        if not parent.is_parameterized():
            raise ValueError(f"job {job_id!r} is not parameterized")
        cfg = parent.parameterized_job
        if cfg.payload == "required" and not payload:
            raise ValueError("payload is required by this parameterized job")
        if cfg.payload == "forbidden" and payload:
            raise ValueError("payload is forbidden by this parameterized job")
        if len(payload) > 16 * 1024:
            raise ValueError("payload exceeds maximum size of 16KiB")
        keys = set(meta)
        required = set(cfg.meta_required)
        allowed = required | set(cfg.meta_optional)
        if required - keys:
            raise ValueError(
                "missing required dispatch metadata: "
                + ", ".join(sorted(required - keys)))
        if keys - allowed:
            raise ValueError(
                "dispatch metadata not allowed: "
                + ", ".join(sorted(keys - allowed)))

        child = parent.copy()
        child.parent_id = parent.id
        child.id = f"{parent.id}/dispatch-{int(s.now())}-{s.generate_uuid()[:8]}"
        child.name = child.id
        child.parameterized_job = None
        child.payload = payload
        child.meta = dict(parent.meta)
        child.meta.update(meta)
        child.status = s.JOB_STATUS_PENDING
        _, index = self.raft.apply(MessageType.JOB_REGISTER, {"job": child})
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=child.priority, type=child.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=child.id,
            job_modify_index=index, status=s.EVAL_STATUS_PENDING)
        self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
        return index, child.id, ev.id

    def node_evaluate(self, node_id: str) -> List[str]:
        """Force re-evaluation of all jobs with allocs on a node
        (node_endpoint.go Evaluate)."""
        node = self.state.node_by_id(None, node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        return self._create_node_evals(node_id, node.modify_index)

    # -- status / operator -------------------------------------------------

    def leader_address(self) -> str:
        return self.config.rpc_advertise if self.is_leader() else ""

    def peer_addresses(self) -> List[str]:
        return [self.config.rpc_advertise]

    def raft_configuration(self) -> Dict:
        return {
            "Servers": [{
                "ID": self.config.node_name,
                "Node": self.config.node_name,
                "Address": self.config.rpc_advertise,
                "Leader": self.is_leader(),
                "Voter": True,
            }],
            "Index": self.raft.applied_index(),
        }

    # -- Node --------------------------------------------------------------

    def node_register(self, node: s.Node) -> Tuple[int, float]:
        """(node_endpoint.go Register): returns (index, heartbeat_ttl)."""
        node = node.copy()
        if not node.id:
            raise ValueError("missing node ID for client registration")
        existed = self.state.node_by_id(None, node.id)
        if not node.status:
            node.status = s.NODE_STATUS_INIT
        _, index = self.raft.apply(MessageType.NODE_REGISTER, {"node": node})
        ttl = self.heartbeat.reset_heartbeat_timer(node.id)
        # Transitions create node evals (node_endpoint.go:165).
        if existed is not None and existed.status != node.status:
            self._create_node_evals(node.id, index)
        return index, ttl

    def node_deregister(self, node_id: str) -> int:
        _, index = self.raft.apply(MessageType.NODE_DEREGISTER, {"node_id": node_id})
        self.heartbeat.clear_heartbeat_timer(node_id)
        self._create_node_evals(node_id, index)
        return index

    def node_update_status(self, node_id: str, status: str) -> Tuple[int, float]:
        """(node_endpoint.go:277 UpdateStatus) — heartbeat + transitions."""
        node = self.state.node_by_id(None, node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        index = self.raft.applied_index()
        if node.status != status:
            _, index = self.raft.apply(
                MessageType.NODE_UPDATE_STATUS,
                {"node_id": node_id, "status": status})
            if self._should_create_node_evals(node.status, status):
                self._create_node_evals(node_id, index)
        ttl = 0.0
        if status != s.NODE_STATUS_DOWN:
            ttl = self.heartbeat.reset_heartbeat_timer(node_id)
        else:
            self.heartbeat.clear_heartbeat_timer(node_id)
        return index, ttl

    @staticmethod
    def _should_create_node_evals(old: str, new: str) -> bool:
        """(structs.go ShouldDrainNode/transition table)."""
        if old == new:
            return False
        if new in (s.NODE_STATUS_DOWN,):
            return True
        if old == s.NODE_STATUS_DOWN and new == s.NODE_STATUS_READY:
            return True
        if old == s.NODE_STATUS_INIT and new == s.NODE_STATUS_READY:
            return True
        return False

    def node_update_drain(self, node_id: str, drain: bool) -> int:
        node = self.state.node_by_id(None, node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        _, index = self.raft.apply(
            MessageType.NODE_UPDATE_DRAIN, {"node_id": node_id, "drain": drain})
        if drain:
            self._create_node_evals(node_id, index)
        return index

    def _create_node_evals(self, node_id: str, node_index: int) -> List[str]:
        """One eval per job with allocs on the node, plus system jobs
        (node_endpoint.go:803 createNodeEvals)."""
        allocs = self.state.allocs_by_node(None, node_id)
        job_ids = {a.job_id for a in allocs}
        evals: List[s.Evaluation] = []
        for job_id in job_ids:
            job = self.state.job_by_id(None, job_id)
            if job is None:
                continue
            evals.append(s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=s.EVAL_TRIGGER_NODE_UPDATE, job_id=job_id,
                node_id=node_id, node_modify_index=node_index,
                status=s.EVAL_STATUS_PENDING))
        for job in self.state.jobs_by_scheduler(None, s.JOB_TYPE_SYSTEM):
            if job.id in job_ids or job.stopped():
                continue
            evals.append(s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=s.EVAL_TRIGGER_NODE_UPDATE, job_id=job.id,
                node_id=node_id, node_modify_index=node_index,
                status=s.EVAL_STATUS_PENDING))
        if evals:
            self.raft.apply(MessageType.EVAL_UPDATE, {"evals": evals})
        return [e.id for e in evals]

    def node_get(self, node_id: str) -> Optional[s.Node]:
        return self.state.node_by_id(None, node_id)

    def node_list(self) -> List[s.Node]:
        return self.state.nodes(None)

    def node_get_allocs(self, node_id: str) -> List[s.Allocation]:
        return self.state.allocs_by_node(None, node_id)

    def node_get_client_allocs(self, node_id: str, min_index: int = 0,
                               max_wait: float = 0.0) -> Tuple[List[s.Allocation], int]:
        """Blocking-query variant the client's watchAllocations long-polls
        (node_endpoint.go:585 GetClientAllocs + rpc.go:340 blockingRPC):
        waits until the allocs table passes min_index or max_wait elapses,
        then returns (allocs, index)."""
        from ..state.state_store import WatchSet
        deadline = time.time() + max_wait
        while True:
            ws = WatchSet()
            allocs = self.state.allocs_by_node(ws, node_id)
            index = max(self.state.table_index("allocs"),
                        self.state.table_index("nodes"))
            if index > min_index or max_wait <= 0:
                return allocs, index
            remaining = deadline - time.time()
            if remaining <= 0:
                return allocs, index
            ws.watch(timeout=min(remaining, 1.0))

    def node_update_allocs(self, allocs: List[s.Allocation]) -> int:
        """Client alloc status sync (node_endpoint.go:657 UpdateAlloc)."""
        _, index = self.raft.apply(MessageType.ALLOC_CLIENT_UPDATE,
                                   {"allocs": allocs})
        return index

    # -- Eval --------------------------------------------------------------

    def eval_dequeue(self, schedulers: List[str],
                     timeout: float = 0.0) -> Tuple[Optional[s.Evaluation], str]:
        return self.eval_broker.dequeue(schedulers, timeout)

    def eval_ack(self, eval_id: str, token: str) -> None:
        self.eval_broker.ack(eval_id, token)

    def eval_nack(self, eval_id: str, token: str) -> None:
        self.eval_broker.nack(eval_id, token)

    def eval_get(self, eval_id: str) -> Optional[s.Evaluation]:
        return self.state.eval_by_id(None, eval_id)

    def eval_list(self) -> List[s.Evaluation]:
        return self.state.evals(None)

    def eval_allocations(self, eval_id: str) -> List[s.Allocation]:
        return self.state.allocs_by_eval(None, eval_id)

    # -- Alloc -------------------------------------------------------------

    def alloc_get(self, alloc_id: str) -> Optional[s.Allocation]:
        return self.state.alloc_by_id(None, alloc_id)

    def alloc_list(self) -> List[s.Allocation]:
        return self.state.allocs(None)

    # -- Plan --------------------------------------------------------------

    def plan_submit(self, plan: s.Plan):
        """(Plan.Submit → PlanQueue, plan_endpoint.go)."""
        return self.plan_queue.enqueue(plan)

    # -- System ------------------------------------------------------------

    def system_gc(self) -> None:
        self._create_core_eval(s.CORE_JOB_FORCE_GC)

    def system_reconcile_summaries(self) -> None:
        self.raft.apply(MessageType.RECONCILE_JOB_SUMMARIES, {})

    def stats(self) -> Dict:
        return {
            "leader": self._leader,
            "applied_index": self.raft.applied_index(),
            "broker": self.eval_broker.stats(),
            "blocked": self.blocked_evals.stats(),
            "plan_queue_depth": self.plan_queue.depth(),
            "heartbeat_active": self.heartbeat.active(),
        }
