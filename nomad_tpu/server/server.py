"""Server runtime: composes the log/FSM, broker, plan pipeline, workers,
heartbeats, periodic dispatch, and GC into the control plane, and exposes
the RPC endpoint surface as methods
(reference: nomad/server.go:78-305, nomad/leader.go:28-641,
nomad/*_endpoint.go).
"""
from __future__ import annotations

import logging
import math
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import fault
from ..structs import structs as s
from ..tenancy import QuotaLedger, RateLimiter
from ..utils import blackbox, contprof, knobs, tracing
from ..utils.telemetry import Telemetry
from . import event_broker as event_stream
from .blocked_evals import BlockedEvals
from .core_sched import CoreScheduler
from .eval_broker import BrokerLimitError, EvalBroker
from .event_broker import EventBroker
from .fsm import FSM, MessageType, TimeTable
from .heartbeat import HeartbeatTimers
from .periodic import PeriodicDispatch, derive_job
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .raft import FileLog, InmemLog, MultiRaft, NotLeaderError, RaftLog
from ..utils.tlsutil import TLSConfig, client_context, server_context
from .vault import ServerVaultClient, VaultConfig, VaultError
from .worker import BatchWorker, Worker


@dataclass
class ServerConfig:
    """(reference: nomad/config.go)."""

    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = "server-1"
    rpc_advertise: str = "127.0.0.1:4647"
    data_dir: str = ""                  # empty → in-memory log (dev mode)
    # RPC / clustering (nomad/config.go RPCAddr, BootstrapExpect, serf join)
    enable_rpc: bool = False            # start the TCP RPC listener
    rpc_bind: str = "127.0.0.1"
    rpc_port: int = 0                   # 0 → ephemeral
    bootstrap_expect: int = 1
    start_join: List[str] = field(default_factory=list)
    # Cross-region federation joins (serf WAN, nomad/serf.go): membership
    # only — never part of this region's raft quorum.
    wan_join: List[str] = field(default_factory=list)
    num_schedulers: int = 1
    use_tpu_batch_worker: bool = False
    batch_size: int = 64
    # Optional jax.sharding.Mesh this region's batch scheduler shards its
    # node axis over — each federated region owns its device slice (the
    # multi-slice/DCN story, SURVEY §2.9 last row): requests forward
    # between regions host-side (rpc.go:263), and each region's placement
    # loop runs on its OWN mesh with ICI collectives inside the slice.
    device_mesh: object = None
    eval_nack_timeout: float = 60.0
    eval_delivery_limit: int = 3
    # Eval-broker admission control (ISSUE 7): bounded pending queue +
    # per-job coalescing.  0 = unbounded (historical behavior); the env
    # knobs let operators bound a running deployment without code.
    broker_max_pending: int = field(default_factory=lambda: knobs.get_int(
        "NOMAD_TPU_BROKER_MAX_PENDING"))
    broker_coalesce: bool = field(default_factory=lambda: knobs.get_bool(
        "NOMAD_TPU_BROKER_COALESCE"))
    broker_bypass_priority: int = field(default_factory=lambda: knobs.get_int(
        "NOMAD_TPU_BROKER_BYPASS_PRIO", s.JOB_MAX_PRIORITY))
    # Multi-tenant serving plane (ROADMAP item 3): cluster-wide default
    # fair-dequeue objective (drf | weighted-rr | fifo); a Namespace
    # row's objective field overrides per tenant.
    tenancy_objective: str = field(default_factory=lambda: knobs.get_str(
        "NOMAD_TPU_TENANCY_OBJECTIVE", s.TENANCY_OBJECTIVE_DRF))
    # Follower-read scheduling (ISSUE 10): on a multi-raft cluster every
    # server also runs FollowerWorkers that, while the server is a
    # follower, pull evals from the leader's broker over RPC, schedule
    # off the locally replicated FSM, and forward plans to the leader's
    # serialized plan-apply (server/follower_sched.py).  Default on —
    # they idle on single-voter servers and on the leader.
    follower_scheduling: bool = field(default_factory=lambda: knobs.get_bool(
        "NOMAD_TPU_FOLLOWER_SCHED"))
    # Follower workers per server; 0 → num_schedulers.
    follower_schedulers: int = 0
    # Join as a NON-VOTING member (the reference's non_voting_server):
    # replicated like a voter — so follower-read scheduling works — but
    # never counted toward quorum and never campaigning.  The shape for
    # scaling scheduler capacity without scaling commit latency.
    non_voting: bool = False
    # Force MultiRaft even for a cluster seed with bootstrap_expect=1 —
    # the shape a deterministic leader takes when follower-scheduler
    # servers will join it later (the loadgen multi-server scenario).
    force_multi_raft: bool = False
    # Heartbeat TTL jitter fraction (thundering-herd dispersal).
    heartbeat_ttl_jitter: float = field(default_factory=lambda: knobs.get_float(
        "NOMAD_TPU_HEARTBEAT_JITTER"))
    # Retry cadence for queued (failed) Vault revocations
    # (vault.go:1104 revokeDaemon — 5 minutes there; shorter default so
    # a failed revoke clears quickly and tests can observe it).
    vault_revoke_interval: float = 5.0
    min_heartbeat_ttl: float = 10.0
    max_heartbeats_per_second: float = 50.0
    failed_eval_unblock_interval: float = 60.0
    eval_gc_interval: float = 300.0
    enabled_schedulers: List[str] = field(default_factory=lambda: [
        s.JOB_TYPE_SERVICE, s.JOB_TYPE_BATCH, s.JOB_TYPE_SYSTEM, s.JOB_TYPE_CORE])
    vault: Optional[VaultConfig] = None
    tls: Optional[TLSConfig] = None


def _job_usage_vec(job: s.Job) -> Tuple[int, int, int, int]:
    """A job's total resource ask on the alloc_usage_vec basis
    (cpu, memory_mb, disk_mb, iops): per-taskgroup task sums × count.
    The node-units admission gate prices a submission with this before
    any alloc exists to fold into the per-ns usage."""
    cpu = mem = disk = iops = 0
    for tg in job.task_groups:
        c = m = d = i = 0
        for task in tg.tasks:
            r = task.resources
            if r is None:
                continue
            c += r.cpu
            m += r.memory_mb
            d += r.disk_mb
            i += r.iops
        cpu += c * tg.count
        mem += m * tg.count
        disk += d * tg.count
        iops += i * tg.count
    return (cpu, mem, disk, iops)


class Server:
    """A single control-plane server (nomad/server.go:78 Server)."""

    def __init__(self, config: Optional[ServerConfig] = None,
                 logger: Optional[logging.Logger] = None,
                 vault_api=None):
        self.config = config or ServerConfig()
        self.logger = logger or logging.getLogger("nomad_tpu.server")
        # Telemetry (go-metrics role): in-memory sink surfaced via
        # agent-info + /v1/metrics; hot paths measure through it
        # (server.go:292-305 periodic emitters + MeasureSince call sites).
        self.metrics = Telemetry()
        # Opt-in eval-lifecycle tracing (utils/tracing.py): process-wide,
        # off by default; NOMAD_TPU_TRACE=1 arms it at construction so
        # /v1/trace/* works without code changes.
        if not tracing.enabled() and knobs.get_bool("NOMAD_TPU_TRACE"):
            tracing.enable()
        # Same construction-time arming for the host-attribution
        # profiler and the incident flight recorder — both are
        # process-wide, None-when-disarmed planes like the tracer.
        contprof.maybe_arm_from_env()
        blackbox.maybe_arm_from_env()
        blackbox.register_server(self)
        # Vault client (nomad/vault.go:234); vault_api injects the fake
        # in tests (vault_testing.go role).
        self.vault = ServerVaultClient(self.config.vault or VaultConfig(),
                                       api=vault_api,
                                       logger=self.logger.getChild("vault"))
        # Must precede raft construction: WAL replay fires FSM hooks that
        # consult leadership.
        self._leader = False
        self._shutdown = threading.Event()

        self.eval_broker = EvalBroker(
            nack_timeout=self.config.eval_nack_timeout,
            delivery_limit=self.config.eval_delivery_limit,
            metrics=self.metrics,
            max_pending=self.config.broker_max_pending,
            coalesce=self.config.broker_coalesce,
            bypass_priority=self.config.broker_bypass_priority)
        self.eval_broker.set_objective(self.config.tenancy_objective)
        # Tenancy enforcement (ROADMAP item 3): leader-side alloc-quota
        # reservation book and the per-tenant API token buckets the HTTP
        # layer consults.  Both are policy mirrors of committed
        # Namespace rows, pushed through the FSM namespace hook.
        self.quota_ledger = QuotaLedger()
        # Node-units reservation book (the quota_node_units field):
        # same ledger mechanics with fractional counts — a tenant's
        # dominant-resource share of the cluster, scaled to nodes-worth.
        self.node_units_ledger = QuotaLedger()
        self.api_limiter = RateLimiter()
        # Cluster capacity mirror for DRF dominant shares and node-units
        # admission: recomputed only when the nodes table index moves.
        self._capacity_node_index = -1
        self._cluster_capacity: Tuple[int, int, int, int] = (0, 0, 0, 0)
        self._cluster_nodes = 0
        self.blocked_evals = BlockedEvals(self.eval_broker)
        self.plan_queue = PlanQueue()
        self.time_table = TimeTable()

        self.fsm = FSM(
            logger=self.logger,
            on_eval_update=self._fsm_eval_updated,
            on_unblock=self._fsm_unblock,
            on_job_register=self._fsm_job_registered,
            on_job_deregister=self._fsm_job_deregistered,
            on_alloc_terminal=self._fsm_alloc_terminal,
            on_namespace_update=self._fsm_namespace_updated,
        )

        # RPC listener + connection pool (nomad/server.go:250 setupRPC).
        # Bound in __init__ so the advertised address is known before raft
        # construction; served from start().
        self.rpc = None
        self.pool = None
        self._members: Dict[str, Dict] = {}
        self._members_lock = threading.Lock()
        # Incarnation for this server's own member record (serf's
        # refutation counter): bumped past any gossiped 'left' about us.
        self._status_time = 1
        # Per-thread marker set while serving a request that was already
        # forwarded once (endpoints.py); blocks a second hop.
        self._fwd_ctx = threading.local()
        if self.config.enable_rpc:
            from .rpc import ConnPool, RPCServer

            tls_cfg = self.config.tls or TLSConfig()
            self.pool = ConnPool(tls_context=client_context(tls_cfg))
            self.rpc = RPCServer(host=self.config.rpc_bind,
                                 port=self.config.rpc_port,
                                 logger=self.logger.getChild("rpc"),
                                 tls_context=server_context(tls_cfg),
                                 metrics=self.metrics)
            # Advertise the configured host (never a wildcard bind) with
            # the actually-bound port (config.go AdvertiseAddrs).
            adv_host = ""
            if self.config.rpc_advertise:
                adv_host = self.config.rpc_advertise.rsplit(":", 1)[0]
            if not adv_host or adv_host == "0.0.0.0":
                adv_host = (self.config.rpc_bind
                            if self.config.rpc_bind != "0.0.0.0"
                            else "127.0.0.1")
            self.config.rpc_advertise = f"{adv_host}:{self.rpc.port}"
            # Chaos identity (ISSUE 12): the pool carries this server's
            # advertised address so named partition groups and
            # asymmetric net rules can tell its traffic apart — one
            # process hosting several servers enforces a partition on
            # every side it owns.
            self.pool.local_addr = self.config.rpc_advertise
        # Subprocess chaos arming: a follower child spawned into a
        # partition/flap scenario arms its own net plane from the env
        # (the parent can also drive it live over Chaos.SetNet).
        chaos_spec = (knobs.get_str("NOMAD_TPU_CHAOS_NET") or "").strip()
        if chaos_spec and not fault.net_armed():
            import json as _json

            try:
                fault.net_arm(_json.loads(chaos_spec))
            except (ValueError, KeyError) as e:
                self.logger.warning(
                    "ignoring malformed NOMAD_TPU_CHAOS_NET: %s", e)

        # Consensus (server.go:257 setupRaft): multi-server raft when
        # clustering is configured, else the single-voter WAL / in-memory
        # log (raftInmem dev path).
        multi = self.config.enable_rpc and (
            self.config.bootstrap_expect > 1 or bool(self.config.start_join)
            or self.config.force_multi_raft)
        if multi:
            raft_dir = (os.path.join(self.config.data_dir, "raft")
                        if self.config.data_dir else None)
            self.raft: RaftLog = MultiRaft(
                self.fsm, self.config.rpc_advertise, self.pool,
                data_dir=raft_dir, logger=self.logger.getChild("raft"))
        elif self.config.data_dir:
            self.raft = FileLog(self.fsm, self.config.data_dir)
        else:
            self.raft = InmemLog(self.fsm)

        self.raft.metrics = self.metrics

        if self.rpc is not None:
            from .endpoints import register_endpoints

            register_endpoints(self, self.rpc)
            if isinstance(self.raft, MultiRaft):
                self.rpc.raft_handler = self.raft.handle_message

        # Cluster event stream (event_broker.py): constructed always,
        # armed (attached to the state store + global registry) only via
        # NOMAD_TPU_EVENTS=1 or the first /v1/event/stream subscriber —
        # disarmed, every state write pays one attribute load + branch.
        # Relaxed index source: external events are clamped monotonic by
        # the broker anyway, and heartbeat-expiry publishes must not
        # queue on the raft lock behind the apply stream.
        self.event_broker = EventBroker(
            metrics=self.metrics,
            index_source=self.raft.applied_index_relaxed)
        self._events_enabled = False
        self._events_lock = threading.Lock()
        if knobs.get_bool("NOMAD_TPU_EVENTS"):
            self.enable_event_stream()

        self.plan_applier = PlanApplier(self.plan_queue, self.raft, self.logger,
                                        metrics=self.metrics,
                                        blocked_evals=self.blocked_evals)
        self.heartbeat = HeartbeatTimers(
            on_expire=self._heartbeat_expired,
            min_ttl=self.config.min_heartbeat_ttl,
            max_per_second=self.config.max_heartbeats_per_second,
            logger=self.logger,
            metrics=self.metrics,
            ttl_jitter=self.config.heartbeat_ttl_jitter)
        if self._events_enabled:
            self.heartbeat.event_broker = self.event_broker
        self.periodic = PeriodicDispatch(self._periodic_dispatch, self.logger)

        self.workers: List[Worker] = []
        self.follower_workers: List[Worker] = []
        self.leader_channel = None
        self._reaper_threads: List[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Boot: serve RPC, start raft + membership, start workers, and
        monitor leadership (server.go:250-284 setupRPC/setupRaft/setupSerf/
        setupWorkers + leader.go:28 monitorLeadership)."""
        if self.rpc is not None:
            self.rpc.start()
            self._merge_members([self._self_member()])
        # Every server keeps its own Vault token alive regardless of
        # leadership (vault.go:467 renewalLoop starts at construction).
        if self.vault.enabled and (self.config.vault or VaultConfig()).token:
            self.vault.start_renewal()
        if isinstance(self.raft, MultiRaft):
            self.raft.start()
            self._maybe_bootstrap()
        if self.rpc is not None and (self.config.start_join
                                     or self.config.wan_join):
            t = threading.Thread(target=self._join_loop, daemon=True,
                                 name="serf-join")
            t.start()
        t = threading.Thread(target=self._emit_metrics_loop, daemon=True,
                             name="metrics-emitter")
        t.start()
        for i in range(self.config.num_schedulers):
            if self.config.use_tpu_batch_worker:
                worker: Worker = BatchWorker(
                    self.eval_broker, self.plan_queue, self.raft,
                    blocked_evals=self.blocked_evals, logger=self.logger,
                    time_table=self.time_table,
                    metrics=self.metrics,
                    max_batch=self.config.batch_size,
                    mesh=self.config.device_mesh)
            else:
                worker = Worker(
                    self.eval_broker, self.plan_queue, self.raft,
                    schedulers=self.config.enabled_schedulers,
                    blocked_evals=self.blocked_evals, logger=self.logger,
                    time_table=self.time_table,
                    metrics=self.metrics)
            self.workers.append(worker)
        # Follower-read scheduling (ISSUE 10): one FollowerWorker pool
        # per multi-raft server.  They park while this server leads
        # (the local pool above owns the broker) and pull from the
        # leader over RPC otherwise, so no leadership-transition
        # choreography is needed — both pools exist, exactly one is
        # active.
        if (self.config.follower_scheduling and self.pool is not None
                and isinstance(self.raft, MultiRaft)
                and (self.config.follower_schedulers
                     or self.config.num_schedulers) > 0):
            from .follower_sched import FollowerWorker, LeaderChannel

            self.leader_channel = LeaderChannel(
                self.pool, self.leader_address,
                my_addr=self.config.rpc_advertise, metrics=self.metrics)
            n = self.config.follower_schedulers or self.config.num_schedulers
            for _ in range(n):
                self.follower_workers.append(FollowerWorker(
                    self.raft, self.leader_channel, self.is_leader,
                    logger=self.logger, metrics=self.metrics))
        self.raft.notify_leadership(self._leadership_changed)
        for worker in self.workers:
            worker.start()
        for worker in self.follower_workers:
            worker.start()

    # -- cluster event stream ----------------------------------------------

    def enable_event_stream(self) -> None:
        """Arm the event broker: attach it to the state store write path
        and the process-wide external-publisher registry.  Idempotent;
        stays armed for the server's lifetime so a subscriber that
        disconnects can resume against a ring that kept buffering."""
        with self._events_lock:
            if self._events_enabled:
                return
            self._events_enabled = True
            self.fsm.event_broker = self.event_broker
            self.fsm.state.event_broker = self.event_broker
            # Writes applied before arming were never buffered: raise
            # the broker's gap horizon so a stale resume errors with the
            # oldest index instead of silently replaying nothing.  Attach
            # BEFORE reading the horizon — applied_index() serializes on
            # the raft lock the FSM applies under, so any apply that
            # missed the just-attached broker is ≤ the index read here
            # (an apply that both published and landed ≤ horizon only
            # costs a false resume error, never a silent gap).
            self.event_broker.mark_armed(self.raft.applied_index())
            # Per-server publishers get this server's broker directly
            # (note_external is only for genuinely process-wide sources:
            # the breaker and the fault plane).  heartbeat may not exist
            # yet on the NOMAD_TPU_EVENTS=1 construction path; __init__
            # re-attaches it right after construction.
            self.eval_broker.event_broker = self.event_broker
            hb = getattr(self, "heartbeat", None)
            if hb is not None:
                hb.event_broker = self.event_broker
            event_stream.register(self.event_broker)

    def event_stream_subscribe(self, topics=None, from_index: int = 0,
                               replay_all: bool = False):
        """Subscribe to the cluster event stream (Event.Stream /
        /v1/event/stream).  Arms the broker on first use.  Raises
        event_broker.EventIndexError when ``from_index`` is below the
        ring's buffered horizon; ``replay_all`` is the no-gap-check
        backlog dump (whatever the ring still holds)."""
        self.enable_event_stream()
        return self.event_broker.subscribe(topics=topics,
                                           from_index=from_index,
                                           replay_all=replay_all)

    def shutdown(self) -> None:
        self._shutdown.set()
        self._leader = False
        blackbox.unregister_server(self)
        event_stream.unregister(self.event_broker)
        self.event_broker.close()
        for worker in self.workers:
            worker.stop()
        for worker in self.follower_workers:
            worker.stop()
        self.plan_applier.stop()
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.plan_queue.set_enabled(False)
        self.periodic.set_enabled(False)
        self.heartbeat.set_enabled(False)
        self.vault.stop()
        self.raft.close()
        if self.rpc is not None:
            self.rpc.shutdown()
        if self.pool is not None:
            self.pool.close()

    # -- membership (serf-lite: nomad/serf.go over the RPC port) -----------

    def _self_member(self) -> Dict:
        return {"Name": self.config.node_name,
                "Addr": self.config.rpc_advertise,
                "Region": self.config.region,
                "Status": "alive",
                "StatusTime": self._status_time,
                "NonVoter": self.config.non_voting}

    def members(self) -> List[Dict]:
        """(serf.Members / nomad/serf.go peer table)."""
        with self._members_lock:
            return sorted(self._members.values(),
                          key=lambda m: (m.get("Region", ""), m["Name"]))

    def join(self, addresses: List[str]) -> int:
        """Operator-initiated join (agent_endpoint.go Join → serf.Join):
        dial each address's Serf.Join, merge the replies; returns how many
        answered.  Each dial gets two backed-off retries — Serf.Join is an
        idempotent membership merge, and `nomad server-join` should
        survive one transient dial failure."""
        from ..utils.backoff import Backoff, retry

        if self.pool is None:
            raise ValueError("RPC is not enabled")
        me = self._self_member()
        joined = 0
        for addr in addresses:
            try:
                reply = retry(
                    lambda a=addr: self.pool.call(a, "Serf.Join",
                                                  {"Member": me},
                                                  timeout=2.0),
                    retries=2, backoff=Backoff(base=0.1, max_delay=0.5))
                self._merge_members(reply.get("Members") or [])
                joined += 1
            except Exception as e:
                self.logger.warning("server: join %s failed: %s", addr, e)
        return joined

    def force_leave(self, name: str) -> bool:
        """Mark a member as left (serf.RemoveFailedNode /
        agent_endpoint.go ForceLeave) and gossip it: the record carries a
        bumped StatusTime so peers' merges keep 'left' over stale 'alive'
        views.  A same-region raft peer set is untouched (voter removal is
        a config change, not a gossip eviction)."""
        changed = False
        with self._members_lock:
            for key, m in list(self._members.items()):
                if m["Name"] == name:
                    m["Status"] = "left"
                    m["StatusTime"] = int(m.get("StatusTime", 1)) + 1
                    changed = True
            view = list(self._members.values())
        if changed and self.pool is not None:
            threading.Thread(target=self._push_members, args=(view,),
                             daemon=True).start()
        return changed

    def membership_join(self, member: Dict) -> Dict:
        """Handle a Serf.Join from a peer: merge, gossip the change, and
        return the full member list (serf.go:51 nodeJoin)."""
        self._merge_members([member])
        return {"Members": self.members()}

    def _merge_members(self, incoming: List[Dict]) -> None:
        """Merge member records; on change, push our view to peers (the
        gossip dissemination step) and re-check bootstrap
        (serf.go:91 maybeBootstrap)."""
        added = []
        with self._members_lock:
            for m in incoming:
                name = m.get("Name")
                if not name or not m.get("Addr"):
                    continue
                # Names are only unique within a region (serf WAN names
                # members "name.region"); key by both so two regions'
                # default-named servers cannot overwrite each other.
                key = (name, m.get("Region", ""))
                old = self._members.get(key)
                if old is None:
                    added.append(m)
                    self._members[key] = dict(m)
                    continue
                # Refutation (serf alive/suspect semantics): a 'left'
                # about OURSELVES while we are alive gets out-bid by
                # bumping our incarnation past it and re-gossiping.
                if (name == self.config.node_name
                        and m.get("Region", "") == self.config.region
                        and m.get("Status") != "alive"
                        and int(m.get("StatusTime", 1)) >= self._status_time):
                    self._status_time = int(m.get("StatusTime", 1)) + 1
                    refreshed = self._self_member()
                    self._members[key] = refreshed
                    added.append(refreshed)  # gossip the refutation
                    continue
                # Conflict resolution: the record with the newer
                # StatusTime wins, so a gossiped 'left' is not
                # resurrected by a peer's stale 'alive' view.
                if int(m.get("StatusTime", 1)) >= \
                        int(old.get("StatusTime", 1)):
                    if m.get("Status") != old.get("Status"):
                        added.append(m)  # status change gossips onward
                    self._members[key] = dict(m)
            view = list(self._members.values())
        if not added:
            return
        self.logger.info("server: membership now %d members (+%s)",
                         len(view), ",".join(m["Name"] for m in added))
        self._maybe_bootstrap()
        if self.pool is not None:
            threading.Thread(target=self._push_members, args=(view,),
                             daemon=True).start()

    def _push_members(self, view: List[Dict]) -> None:
        """Anti-entropy push: send every member we know to every peer.
        Receivers that learn nothing new do not re-push, so this
        terminates."""
        me = self.config.rpc_advertise
        for m in view:
            addr = m["Addr"]
            if addr == me:
                continue
            for peer in view:
                try:
                    self.pool.call(addr, "Serf.Join", {"Member": peer},
                                   timeout=1.0)
                except Exception:
                    break  # peer unreachable; heartbeat/rejoin recovers

    def _maybe_bootstrap(self) -> None:
        """Initial cluster formation + config growth (serf.go:91
        maybeBootstrap).

        Only a *seed* server (no start_join) may adopt the initial voter
        set from its gossip view, and only once bootstrap_expect members
        are alive.  A joining server waits to be added by the leader via a
        replicated CONFIG entry — self-assembling a quorum from a private
        member view could create a second, disjoint quorum (split-brain).
        After bootstrap, the leader proposes a config change whenever
        gossip surfaces members that are not yet voters (raft AddVoter)."""
        if not isinstance(self.raft, MultiRaft):
            return
        with self._members_lock:
            # WAN members of other regions are never raft voters
            # (serf.go: per-region raft, WAN gossip for federation only).
            # Non-voting members (non_voting_server) replicate but never
            # join the quorum configuration.
            addrs = [m["Addr"] for m in self._members.values()
                     if m.get("Region", self.config.region)
                     == self.config.region and not m.get("NonVoter")]
            learner_addrs = [m["Addr"] for m in self._members.values()
                             if m.get("Region", self.config.region)
                             == self.config.region and m.get("NonVoter")]
        if not self.raft._bootstrapped:
            if self.config.start_join or self.config.non_voting:
                return
            if len(addrs) >= self.config.bootstrap_expect:
                self.raft.bootstrap(addrs)
            return
        if self.raft.is_raft_leader():
            for addr in learner_addrs:
                self.raft.add_learner(addr)
            new = sorted(set(self.raft.peers) | set(addrs))
            if new != sorted(self.raft.peers):
                def _propose():
                    try:
                        self.raft.propose_config(new)
                    except Exception as e:
                        self.logger.warning(
                            "server: config change failed: %s", e)
                threading.Thread(target=_propose, daemon=True).start()

    def _join_loop(self) -> None:
        """Retry start_join addresses until each answers — indefinitely,
        with capped backoff, like the agent's retry_join: a cluster whose
        members boot far apart must still converge."""
        pending = list(self.config.start_join) + list(self.config.wan_join)
        me = self._self_member()
        delay = 0.25
        attempts = 0
        while not self._shutdown.is_set() and pending:
            still = []
            for addr in pending:
                try:
                    reply = self.pool.call(addr, "Serf.Join", {"Member": me},
                                           timeout=1.0)
                    self._merge_members(reply.get("Members") or [])
                except Exception:
                    still.append(addr)
            pending = still
            if pending:
                attempts += 1
                if attempts % 20 == 0:
                    self.logger.warning(
                        "server: still unable to join %s after %d attempts",
                        ",".join(pending), attempts)
                self._shutdown.wait(delay)
                delay = min(delay * 1.5, 5.0)

    def is_leader(self) -> bool:
        return self._leader

    @property
    def state(self):
        return self.fsm.state

    # -- chaos/audit surface (ISSUE 12) ------------------------------------

    def consistent_snapshot(self):
        """A copy-on-write state snapshot taken at a raft ENTRY
        boundary: the raft lock serializes with the applier (MultiRaft
        applies committed chunks under it), so a multi-write apply like
        APPLY_PLAN_RESULTS can never be observed half-landed.  The
        snapshot itself is O(1); everything expensive happens on the
        immutable copy afterwards."""
        lock = getattr(self.raft, "_l", None)
        if lock is not None:
            with lock:
                return self.state.snapshot()
        return self.state.snapshot()

    def fsm_fingerprint(self) -> Tuple[int, str]:
        """(committed-prefix index, state digest) for the safety
        auditor's cross-server check.  The index label is the
        snapshot's own latest write index — internally consistent with
        the hashed content by construction, and equal across servers
        that applied the same prefix (entries that never touch the
        store don't bump it on any server)."""
        snap = self.consistent_snapshot()
        return snap.latest_index(), snap.fingerprint()

    # -- leadership --------------------------------------------------------

    def _leadership_changed(self, leader: bool) -> None:
        if leader:
            self._establish_leadership()
        else:
            self._revoke_leadership()

    def _establish_leadership(self) -> None:
        """(leader.go:110 establishLeadership)."""
        self._leader = True
        self.eval_broker.set_enabled(True)
        self.plan_queue.set_enabled(True)
        # Follower-read fence floor (ISSUE 10): the previous leader's
        # per-job plan fences died with its PlanQueue, but election
        # safety guarantees every COMMITTED plan is ≤ our LOG's last
        # index right now (fence_index — NOT the applied index, which
        # the async FSM applier may still be draining toward).  Raising
        # the global floor makes every remote dequeue carry a fence ≥
        # this index, so a lagging follower replicates past all
        # pre-failover plans before scheduling — without it, a follower
        # could schedule a job off a snapshot missing that job's own
        # committed placements (the one staleness the applier's
        # capacity re-check cannot catch).
        self.plan_queue.note_applied("", self.raft.fence_index())
        self.blocked_evals.set_enabled(True)
        self.periodic.set_enabled(True)
        self.heartbeat.set_enabled(True)
        self.plan_applier.start()
        self._restore_tenancy()
        self._restore_evals()
        self._restore_periodic_dispatcher()
        self._start_reapers()
        # Vault activates with leadership (vault.go:290 SetActive): the
        # revocation queue is ours to drain now; on loss it clears.
        self.vault.set_active(True)
        self._restore_revoking_accessors()
        # Reconcile voters with members discovered while we were a
        # follower (leader.go establishes raft config on leadership).
        self._maybe_bootstrap()

    def _restore_revoking_accessors(self) -> None:
        """Revoke accessors whose allocation OR node is already terminal
        or gone — the previous leader may have died mid-revocation
        (leader.go:221-260 restoreRevokingAccessors checks both)."""
        if not self.vault.enabled:
            return
        stale = []
        for acc in self.state.vault_accessors(None):
            alloc = self.state.alloc_by_id(None, acc.alloc_id)
            if alloc is None or alloc.terminal_status():
                stale.append(acc)
                continue
            node = self.state.node_by_id(None, acc.node_id)
            if node is None or node.terminal_status():
                stale.append(acc)
        if stale:
            threading.Thread(target=self._revoke_accessors,
                             args=(stale,), daemon=True).start()

    def _revoke_leadership(self) -> None:
        self._leader = False
        self.vault.set_active(False)
        self.eval_broker.set_enabled(False)
        self.plan_queue.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.periodic.set_enabled(False)
        self.heartbeat.set_enabled(False)
        self.plan_applier.stop()

    def _restore_tenancy(self) -> None:
        """Reseed the tenancy plane from restored state on leadership:
        fairness/rate policy from committed Namespace rows, and a
        conservative quota-ledger rebuild from every non-terminal
        eval's job (over-reserving is safe — extra 429s near the limit;
        under-reserving could let a failover breach quota)."""
        for ns in self.state.namespaces(None):
            self._fsm_namespace_updated(ns.name, ns)
        entries = []
        unit_entries = []
        seen = set()
        self._refresh_capacity()
        cap, nodes = self._cluster_capacity, self._cluster_nodes
        for ev in self.state.evals(None):
            if ev.terminal_status() or ev.job_id in seen:
                continue
            seen.add(ev.job_id)
            job = self.state.job_by_id(None, ev.job_id)
            if job is None:
                continue
            ns = job.namespace or "default"
            count = sum(tg.count for tg in job.task_groups)
            entries.append((job.id, ns, count))
            if nodes > 0:
                unit_entries.append(
                    (job.id, ns,
                     self._node_units(_job_usage_vec(job), cap, nodes)))
        self.quota_ledger.rebuild(entries)
        self.node_units_ledger.rebuild(unit_entries)
        self.eval_broker.note_usage_changed(self.state.namespace_usage())

    def _restore_evals(self) -> None:
        """Re-enqueue pending and re-block blocked evals from state
        (leader.go:195 restoreEvals)."""
        for ev in self.state.evals(None):
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    def _restore_periodic_dispatcher(self) -> None:
        """Track periodic jobs + catch up missed launches (leader.go:150)."""
        now = time.time()
        for job in self.state.jobs_by_periodic(None, True):
            self.periodic.add(job)
            launch = self.state.periodic_launch_by_id(None, job.id)
            last = launch.launch if launch else 0.0
            nxt = job.periodic.next(last)
            if last and 0 < nxt <= now:
                self.periodic.force_run(job.id)

    def _start_reapers(self) -> None:
        """Duplicate-blocked-eval reaper, failed-eval unblock, periodic GC
        core evals (leader.go:157-193)."""

        def dup_reaper():
            while self._leader and not self._shutdown.is_set():
                dups = self.blocked_evals.get_duplicates(timeout=0.5)
                if not dups:
                    continue
                cancelled = []
                for dup in dups:
                    ev = dup.copy()
                    ev.status = s.EVAL_STATUS_CANCELLED
                    ev.status_description = (
                        f"existing blocked evaluation exists for job {ev.job_id!r}")
                    cancelled.append(ev)
                self.raft.apply(MessageType.EVAL_UPDATE, {"evals": cancelled})

        def shed_reaper():
            # Broker-coalesced duplicates: the broker absorbed their
            # trigger into the kept eval; cancel them through the log so
            # eval-status tells the story (and they never look pending).
            while self._leader and not self._shutdown.is_set():
                shed = self.eval_broker.get_shed(timeout=0.5)
                if not shed:
                    continue
                cancelled = []
                for dup in shed:
                    ev = dup.copy()
                    ev.status = s.EVAL_STATUS_CANCELLED
                    ev.status_description = (
                        f"coalesced with a pending evaluation for job "
                        f"{ev.job_id!r} (broker admission control)")
                    cancelled.append(ev)
                try:
                    self.raft.apply(MessageType.EVAL_UPDATE,
                                    {"evals": cancelled})
                except NotLeaderError:
                    return

        def failed_unblocker():
            while self._leader and not self._shutdown.is_set():
                self._shutdown.wait(self.config.failed_eval_unblock_interval)
                if self._leader and not self._shutdown.is_set():
                    self.blocked_evals.unblock_failed()

        def gc_scheduler():
            while self._leader and not self._shutdown.is_set():
                self._shutdown.wait(self.config.eval_gc_interval)
                if not (self._leader and not self._shutdown.is_set()):
                    return
                for core_job in (s.CORE_JOB_EVAL_GC, s.CORE_JOB_JOB_GC,
                                 s.CORE_JOB_NODE_GC):
                    self._create_core_eval(core_job)

        def vault_revoke_daemon():
            # Retry failed revocations until the token TTLs out
            # (vault.go:1104 revokeDaemon; 5-min cadence there, shorter
            # here so tests observe it).
            while self._leader and not self._shutdown.is_set():
                self._shutdown.wait(self.config.vault_revoke_interval)
                if not (self._leader and not self._shutdown.is_set()):
                    return
                try:
                    done = self.vault.tick_revocations()
                except Exception:
                    self.logger.exception("vault revoke daemon")
                    continue
                if done:
                    self._deregister_accessor_rows(done)

        for target in (dup_reaper, shed_reaper, failed_unblocker,
                       gc_scheduler, vault_revoke_daemon):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._reaper_threads.append(t)

    def _emit_metrics_loop(self, interval: float = 1.0) -> None:
        """Periodic gauge emission (server.go:292-305 EmitStats of the
        broker, plan queue, blocked evals, and heartbeat timers; metric
        names per the reference telemetry doc)."""
        tenant_top = knobs.get_int("NOMAD_TPU_TENANCY_METRICS_TOP", 10)
        while not self._shutdown.is_set():
            try:
                self._feed_tenancy(tenant_top)
                b = self.eval_broker.stats()
                self.metrics.set_gauge("broker.total_ready",
                                       b.get("total_ready", 0))
                self.metrics.set_gauge("broker.total_unacked",
                                       b.get("total_unacked", 0))
                self.metrics.set_gauge("broker.total_waiting",
                                       b.get("total_waiting", 0))
                self.metrics.set_gauge("broker.pending",
                                       self.eval_broker.pending_count())
                bl = self.blocked_evals.stats()
                self.metrics.set_gauge("blocked_evals.total_blocked",
                                       bl.get("total_blocked", 0))
                self.metrics.set_gauge("blocked_evals.total_escaped",
                                       bl.get("total_escaped", 0))
                self.metrics.set_gauge("plan.queue_depth",
                                       self.plan_queue.depth())
                self.metrics.set_gauge("heartbeat.active",
                                       self.heartbeat.active())
                self.metrics.set_gauge("raft.applied_index",
                                       self.raft.applied_index())
                if self.leader_channel is not None:
                    self.metrics.set_gauge(
                        "plan.forward.inflight",
                        self.leader_channel.inflight())
                if isinstance(self.raft, MultiRaft) and not self._leader:
                    # Replication debt of this follower's FSM vs the
                    # commit horizon the leader has shown it (a lower
                    # bound on true leader lag; the per-dequeue
                    # follower.snapshot_lag samples carry the exact
                    # leader-applied delta).
                    self.metrics.set_gauge(
                        "follower.snapshot_lag",
                        max(0, self.raft.commit_index
                            - self.raft.applied_index_relaxed()))
                if self._events_enabled:
                    es = self.event_broker.stats()
                    self.metrics.set_gauge("events.ring_depth",
                                           es["depth"])
                    self.metrics.set_gauge("events.subscribers",
                                           es["subscribers"])
                    self.metrics.set_gauge("events.dropped",
                                           es["evicted"])
                    self.metrics.set_gauge("events.max_subscriber_lag",
                                           es["max_subscriber_lag"])
                # Breaker state must survive interval rolls while evals
                # are quiet — the open-and-idle window is exactly the
                # one worth observing.  sys.modules, not an import: the
                # ops package drags in jax, which an oracle-only server
                # never needs.
                brk_mod = sys.modules.get("nomad_tpu.ops.breaker")
                if brk_mod is not None:
                    self.metrics.set_gauge(
                        "breaker.state",
                        brk_mod.STATE_CODE.get(brk_mod.BREAKER.state, 0))
                    self.metrics.set_gauge("breaker.trips",
                                           brk_mod.BREAKER.trips)
                prof = contprof.PROFILER
                if prof is not None:
                    for sub, share in prof.shares(30.0).items():
                        self.metrics.set_gauge(f"cpu.{sub}", share)
                    gil = prof.gil_pressure_ms()
                    self.metrics.set_gauge("runtime.gil_delay_p50_ms",
                                           gil["p50"])
                    self.metrics.set_gauge("runtime.gil_delay_p99_ms",
                                           gil["p99"])
                self._watch_plan_slo()
            except Exception:  # never kill the emitter
                self.logger.exception("metrics emit failed")
            self._shutdown.wait(interval)

    def _watch_plan_slo(self) -> None:
        """Plan-apply p99 SLO watch: when NOMAD_TPU_BLACKBOX_SLO_PLAN_P99_MS
        is set (>0) and the current interval's plan.apply p99 breaches
        it, auto-capture a flight-recorder bundle.  note_trigger's
        per-reason rate limit keeps a sustained breach from flooding."""
        slo_ms = knobs.get_float("NOMAD_TPU_BLACKBOX_SLO_PLAN_P99_MS", 0.0)
        if not slo_ms or slo_ms <= 0 or not blackbox.enabled():
            return
        latest = self.metrics.sink.latest()
        summ = latest.get("Samples", {}).get("nomad.plan.apply")
        if not summ or not summ.get("count"):
            return
        p99 = summ.get("p99", 0.0)
        if p99 > slo_ms:
            blackbox.note_trigger(
                "slo.plan_apply_p99",
                {"P99Ms": round(p99, 3), "SloMs": slo_ms,
                 "Count": summ.get("count", 0),
                 "Node": self.config.node_name})

    def _feed_tenancy(self, tenant_top: int) -> None:
        """Per-tick tenancy upkeep, piggybacked on the metrics cadence:
        drain the state store's dirty per-ns usage fold into the DRF
        scorer (O(changed tenants)), refresh the cluster-capacity
        mirror when the nodes table moved, and emit the busiest
        tenants' ``tenant.*`` gauges (knob-capped — a 1k-tenant fleet
        must not mint 4k gauge keys)."""
        dirty = self.state.drain_ns_dirty()
        if dirty:
            usage = self.state.namespace_usage()
            self.eval_broker.note_usage_changed(
                {ns: usage.get(ns, (0, 0, 0, 0, 0)) for ns in dirty})
        self._refresh_capacity()
        if tenant_top <= 0:
            return
        counters = self.eval_broker.tenant_counters()
        busiest = sorted(counters.items(),
                         key=lambda kv: (-kv[1][0], kv[0]))[:tenant_top]
        cap, nodes = self._cluster_capacity, self._cluster_nodes
        for ns, (pending, dequeued, shed, rejects) in busiest:
            self.metrics.set_gauge(f"tenant.pending.{ns}", pending)
            self.metrics.set_gauge(f"tenant.dequeued.{ns}", dequeued)
            self.metrics.set_gauge(f"tenant.shed.{ns}", shed)
            self.metrics.set_gauge(f"tenant.rejects.{ns}", rejects)
            if nodes > 0:
                self.metrics.set_gauge(
                    f"tenant.node_units.{ns}",
                    self._node_units(
                        self.state.namespace_usage_one(ns)[:4], cap, nodes))

    def _refresh_capacity(self) -> None:
        """Keep the cluster-capacity mirror current: recompute the
        4-vector total + non-terminal node count only when the nodes
        table index moved (O(1) otherwise), and push it into the
        broker's DRF scorer.  Shared by the metrics tick and the
        node-units admission gate."""
        node_index = self.state.table_index("nodes")
        if node_index == self._capacity_node_index:
            return
        self._capacity_node_index = node_index
        cap = [0, 0, 0, 0]
        nodes = 0
        for node in self.state.nodes(None):
            if node.terminal_status():
                continue
            nodes += 1
            res = node.resources
            if res is None:
                continue
            cap[0] += res.cpu
            cap[1] += res.memory_mb
            cap[2] += res.disk_mb
            cap[3] += res.iops
        self._cluster_capacity = tuple(cap)
        self._cluster_nodes = nodes
        self.eval_broker.set_cluster_capacity(self._cluster_capacity)

    @staticmethod
    def _node_units(usage: Tuple[int, int, int, int],
                    cap: Tuple[int, int, int, int], nodes: int) -> float:
        """Nodes-worth of dominant-resource usage (the quota_node_units
        basis, structs.Namespace): max over dimensions of usage/capacity,
        scaled by the node count — 'this tenant occupies X nodes' even
        when its footprint is spread thin across many."""
        share = max((u / c) for u, c in zip(usage, cap) if c > 0) \
            if any(cap) else 0.0
        return share * nodes

    def _create_core_eval(self, core_job: str) -> None:
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=s.JOB_MAX_PRIORITY,
            type=s.JOB_TYPE_CORE, triggered_by=s.EVAL_TRIGGER_SCHEDULED,
            job_id=core_job, status=s.EVAL_STATUS_PENDING)
        self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})

    # -- FSM hooks (leader side) ------------------------------------------

    def _fsm_eval_updated(self, ev: s.Evaluation) -> None:
        if not self._leader:
            return
        self.time_table.witness(self.raft.applied_index())
        if ev.terminal_status():
            # The job's driving eval is done: its placements are live in
            # the per-ns usage fold (or never will be), so the admission
            # reservations made for it have served their purpose.
            self.quota_ledger.release(ev.job_id)
            self.node_units_ledger.release(ev.job_id)
        if ev.should_enqueue():
            self.eval_broker.enqueue(ev)
        elif ev.should_block():
            self.blocked_evals.block(ev)
        elif (ev.status == s.EVAL_STATUS_COMPLETE
              and not ev.failed_tg_allocs):
            # Successful eval → untrack any blocked eval for the job
            # (fsm.go applyUpdateEval).
            self.blocked_evals.untrack(ev.job_id)

    def _fsm_unblock(self, computed_class: str, index: int) -> None:
        if self._leader:
            self.blocked_evals.unblock(computed_class, index)

    def _fsm_job_registered(self, job: s.Job) -> None:
        if self._leader and job.is_periodic() and not job.stopped():
            self.periodic.add(job)

    def _fsm_job_deregistered(self, job_id: str) -> None:
        if self._leader:
            self.periodic.remove(job_id)
            self.quota_ledger.release(job_id)
            self.node_units_ledger.release(job_id)

    def _fsm_namespace_updated(self, name: str,
                               ns: Optional[s.Namespace]) -> None:
        """Committed Namespace row changed: refresh the policy mirrors.
        Runs on every server (the rate limiter guards each HTTP front
        door; fairness weights matter only while leading but are cheap
        to keep warm)."""
        if ns is None:
            self.eval_broker.drop_namespace_policy(name)
            self.api_limiter.drop(name)
            return
        self.eval_broker.set_namespace_policy(
            name, ns.dequeue_weight, ns.objective)
        self.api_limiter.configure(name, ns.api_rate, float(ns.api_burst))

    def _fsm_alloc_terminal(self, alloc_id: str) -> None:
        """Terminal alloc ⇒ revoke its derived Vault tokens
        (vault.go RevokeTokens on alloc terminal)."""
        if not self._leader or not self.vault.enabled:
            return
        accessors = self.state.vault_accessors_by_alloc(None, alloc_id)
        if accessors:
            threading.Thread(target=self._revoke_accessors,
                             args=(accessors,), daemon=True).start()

    def _revoke_accessors(self, accessors) -> None:
        done = self.vault.revoke_accessors([a.accessor for a in accessors])
        # Failed revocations queue for retry until the token TTLs out
        # (vault.go storeForRevocation; drained by vault_revoke_daemon).
        failed = [a for a in accessors if a.accessor not in done]
        if failed:
            self.vault.store_for_revocation([a.accessor for a in failed])
        if not done:
            return
        to_remove = [a for a in accessors if a.accessor in done]
        try:
            self.raft.apply(MessageType.VAULT_ACCESSOR_DEREGISTER,
                            {"accessors": to_remove})
        except NotLeaderError:
            pass  # new leader's restore pass re-revokes (idempotent)

    def _deregister_accessor_rows(self, accessor_ids) -> None:
        """Drop accessor rows for ids revoked by the retry daemon."""
        wanted = set(accessor_ids)
        rows = [a for a in self.state.vault_accessors(None)
                if a.accessor in wanted]
        if not rows:
            return
        try:
            self.raft.apply(MessageType.VAULT_ACCESSOR_DEREGISTER,
                            {"accessors": rows})
        except NotLeaderError:
            pass

    # -- heartbeat / periodic callbacks ------------------------------------

    def _heartbeat_expired(self, node_id: str) -> None:
        """Missed heartbeat ⇒ node down ⇒ node evals (heartbeat.go:86)."""
        try:
            self.node_update_status(node_id, s.NODE_STATUS_DOWN)
        except KeyError:
            pass

    def _periodic_dispatch(self, parent: s.Job, derived: s.Job,
                           launch_time: float) -> None:
        """Register the derived child job + record the launch
        (periodic.go:435 createEval)."""
        if parent.periodic and parent.periodic.prohibit_overlap:
            # A previous launch is still active if any derived child job
            # (id prefix "<parent>/periodic-") has a live eval or alloc
            # (periodic.go shouldDispatch via RunningChildren).
            from .periodic import PERIODIC_LAUNCH_SUFFIX
            prefix = parent.id + PERIODIC_LAUNCH_SUFFIX
            for child in self.state.jobs_by_id_prefix(None, prefix):
                if any(not ev.terminal_status()
                       for ev in self.state.evals_by_job(None, child.id)):
                    return
                if any(not a.terminal_status()
                       for a in self.state.allocs_by_job(None, child.id)):
                    return
        # Explicit own region: a derived child must never region-route
        # away from its parent (periodic.go children are region-local).
        self.job_register(derived, region=self.config.region)
        self.raft.apply(MessageType.PERIODIC_LAUNCH_UPSERT,
                        {"job_id": parent.id, "launch": launch_time})

    # ======================================================================
    # RPC endpoint surface (reference: nomad/*_endpoint.go)
    # ======================================================================

    def regions(self) -> List[str]:
        """Distinct regions known through membership (region_endpoint.go
        List over serf WAN members)."""
        out = {self.config.region}
        for m in self.members():
            r = m.get("Region")
            if r:
                out.add(r)
        return sorted(out)

    def region_info(self) -> List[Dict]:
        """Per-region detail rows for the /v1/regions?detail surface:
        name, alive server count, and best-known leader address.  The
        home region answers from local raft state; remote leaders are a
        best-effort bounded Status.Leader probe against one alive member
        ("" when the region is unreachable — this endpoint must never
        hang on a dark region)."""
        by_region: Dict[str, List[Dict]] = {}
        for m in list(self.members()) + [self._self_member()]:
            r = m.get("Region", "")
            if r and m.get("Status", "alive") == "alive":
                rows = by_region.setdefault(r, [])
                if not any(x.get("Name") == m.get("Name") for x in rows):
                    rows.append(m)
        out = []
        probe_timeout = knobs.get_float("NOMAD_TPU_REGION_PROBE_TIMEOUT")
        for region in sorted(by_region):
            members = by_region[region]
            leader = ""
            if region == self.config.region:
                leader = self.leader_address()
            elif self.pool is not None:
                for m in members:
                    try:
                        reply = self.pool.call(
                            m["Addr"], "Status.Leader", {},
                            timeout=probe_timeout)
                        # Status.Leader replies with the bare address
                        # string (status_endpoint.go), not a dict.
                        leader = (reply if isinstance(reply, str)
                                  else (reply or {}).get("Leader", ""))
                        break
                    except Exception:
                        continue
            out.append({"Name": region, "Servers": len(members),
                        "Leader": leader})
        return out

    def _forward_region(self, region: str, wire_method: str, body: Dict):
        """Route a request to any alive server of another region
        (nomad/rpc.go:263 forwardRegion over the WAN member table).  Does
        NOT consume the one leader-forward hop: the remote server may
        still forward to its own region's leader.

        Partition tolerance contract: a down region degrades to a typed
        ``NoPathToRegion`` carrying a retry_after hint — never a hang and
        never a silent generic error.  The walk makes a bounded number of
        rounds over the region's known servers with the shared jittered
        Backoff between rounds; within a round only DIAL failures rotate
        (the request was never sent, so trying the next server cannot
        double-apply).  The dials ride ``self.pool``, so the per-address
        dial-backoff gate armed by raft replication and leader forwarding
        is shared with the federation path: a region that just went dark
        fails fast locally instead of re-paying connect timeouts."""
        from .rpc import DialError, NoPathToRegion
        from ..utils.backoff import Backoff

        if getattr(self._fwd_ctx, "region_hop", False):
            # This request already took its region hop; stale member
            # records must not bounce it between regions.
            raise ValueError(
                f"request for region {region!r} arrived at "
                f"{self.config.region!r} after a region forward")
        candidates = [m for m in self.members()
                      if m.get("Region") == region
                      and m.get("Status", "alive") == "alive"]
        if not candidates or self.pool is None:
            raise ValueError(f"no servers known in region {region!r}")
        body = dict(body)
        body["Region"] = region
        body["__region_hop__"] = True
        rounds = max(1, knobs.get_int("NOMAD_TPU_REGION_DIAL_ROUNDS"))
        bo = Backoff(base=0.05, max_delay=2.0)
        last: Optional[Exception] = None
        for round_no in range(rounds):
            if round_no and self._shutdown.wait(bo.next_delay()):
                break
            for m in candidates:
                try:
                    return self.pool.call(m["Addr"], wire_method, body)
                except DialError as e:
                    # Only DIAL failures rotate — the request was never
                    # sent.  A post-send transport error may have applied
                    # remotely; retrying could double-apply a write, and
                    # application errors must propagate as-is.
                    last = e
        retry_after = min(knobs.get_float("NOMAD_TPU_REGION_RETRY_AFTER_CAP"),
                          0.5 + 0.5 * rounds)
        raise NoPathToRegion(region, retry_after, rounds=rounds,
                             detail=str(last) if last else "")

    def _forward(self, wire_method: str, body: Dict):
        """Re-issue a write that hit NotLeaderError as a wire RPC to the
        leader (nomad/rpc.go:178 forward) — this is what lets the HTTP API
        of a follower serve writes.  Raises NotLeaderError when there is no
        known leader, no wire transport, or the request already took its
        one forwarding hop (the reference's Forwarded flag: a request must
        not chain through a trail of stale leader pointers)."""
        leader = self.leader_address()
        if (self.pool is None or not leader
                or leader == self.config.rpc_advertise
                or getattr(self._fwd_ctx, "active", False)):
            raise NotLeaderError(leader)
        body = dict(body)
        body["__forwarded__"] = True
        return self.pool.call(leader, wire_method, body)

    # -- Job ---------------------------------------------------------------

    def _check_tenant_admission(self, job: s.Job) -> None:
        """Per-tenant front-door gate, leader-side, BEFORE the raft
        write (composes with the global broker cap inside
        check_admission): the namespace's pending-eval quota, then an
        atomic check+reserve of its live-alloc quota in the ledger.
        Rejections raise BrokerLimitError → 429 + Retry-After; a
        bypass-priority submission (core GC, repair) skips both."""
        ns = job.namespace or "default"
        row = self.state.namespace_by_name(None, ns)
        self.eval_broker.check_admission(
            job.priority, namespace=ns,
            ns_max_pending=row.max_pending_evals if row is not None else 0)
        if row is None or job.priority >= self.eval_broker.bypass_priority:
            return
        count = sum(tg.count for tg in job.task_groups)
        quota = row.max_live_allocs
        if quota > 0:
            live = self.state.namespace_usage_one(ns)[4]
            if not self.quota_ledger.check_and_reserve(
                    ns, job.id, count, live, quota):
                self.eval_broker.note_quota_reject(ns)
                asked = live + self.quota_ledger.reserved(ns) + count
                retry_after = min(5.0, 0.2 + 0.3 * (asked / quota))
                raise BrokerLimitError(retry_after, asked, quota,
                                       namespace=ns)
        units_quota = row.quota_node_units
        if units_quota > 0:
            # Node-units gate (ROADMAP item 3's open item): the tenant's
            # dominant-resource share of the cluster, in nodes-worth,
            # must stay under quota_node_units counting this job's ask.
            self._refresh_capacity()
            cap, nodes = self._cluster_capacity, self._cluster_nodes
            if nodes > 0:
                used = self._node_units(
                    self.state.namespace_usage_one(ns)[:4], cap, nodes)
                ask = self._node_units(_job_usage_vec(job), cap, nodes)
                if not self.node_units_ledger.check_and_reserve(
                        ns, job.id, ask, used, units_quota):
                    # Roll back the alloc-count reservation made above:
                    # this registration is rejected, so nothing will
                    # ever release it otherwise.
                    self.quota_ledger.release(job.id)
                    self.eval_broker.note_quota_reject(ns)
                    asked = used + self.node_units_ledger.reserved(ns) + ask
                    retry_after = min(
                        5.0, 0.2 + 0.3 * (asked / units_quota))
                    raise BrokerLimitError(
                        retry_after, math.ceil(asked),
                        math.ceil(units_quota), namespace=ns)

    def job_register(self, job: s.Job, region: str = "") -> Tuple[int, str]:
        """(job_endpoint.go:47 Register): validate → log JobRegister → eval
        unless periodic/parameterized.  Returns (modify_index, eval_id).

        A request whose effective region (explicit arg, else Job.Region)
        differs from this server's routes to that region
        (rpc.go:263 forwardRegion).  An EXPLICIT region always routes (and
        errors if unknown); a job-file region only routes when that region
        is actually federated — otherwise it registers locally, so a
        default-region job file still works on a renamed cluster."""
        target = region or job.region
        if target and target != self.config.region and (
                region or target in self.regions()):
            reply = self._forward_region(target, "Job.Register",
                                         {"Job": job})
            return reply["Index"], reply["EvalID"]
        job = job.copy()
        job.canonicalize()
        problems = job.validate()
        if problems:
            raise ValueError("job validation failed: " + "; ".join(problems))

        # Admission control at the front door (429-style NACK): reject
        # BEFORE the raft write while the broker is saturated — once the
        # job + eval are persisted there is nothing left to shed.  Only
        # evals-to-be are gated (periodic/parameterized registrations
        # enqueue nothing).
        if self._leader and not job.is_periodic() \
                and not job.is_parameterized():
            self._check_tenant_admission(job)

        try:
            _, index = self.raft.apply(MessageType.JOB_REGISTER, {"job": job})
        except NotLeaderError:
            reply = self._forward("Job.Register", {"Job": job})
            return reply["Index"], reply["EvalID"]

        eval_id = ""
        if not job.is_periodic() and not job.is_parameterized():
            ev = s.Evaluation(
                id=s.generate_uuid(),
                priority=job.priority,
                type=job.type,
                namespace=job.namespace,
                triggered_by=s.EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id,
                job_modify_index=index,
                status=s.EVAL_STATUS_PENDING,
            )
            # Open the eval.e2e umbrella (submit → broker ack) before
            # the eval write so the span covers enqueue + queue wait.
            tr = tracing.TRACER
            if tr is not None:
                tr.mark(ev.id, job_id=job.id, submit="job_register",
                        priority=job.priority, namespace=job.namespace)
            _, eval_index = self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
            eval_id = ev.id
        return index, eval_id

    def job_deregister(self, job_id: str, purge: bool = True,
                       region: str = "") -> Tuple[int, str]:
        """(job_endpoint.go Deregister)."""
        if region and region != self.config.region:
            reply = self._forward_region(region, "Job.Deregister",
                                         {"JobID": job_id, "Purge": purge})
            return reply["Index"], reply["EvalID"]
        job = self.state.job_by_id(None, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        try:
            _, index = self.raft.apply(MessageType.JOB_DEREGISTER,
                                       {"job_id": job_id, "purge": purge})
        except NotLeaderError:
            reply = self._forward("Job.Deregister",
                                  {"JobID": job_id, "Purge": purge})
            return reply["Index"], reply["EvalID"]
        eval_id = ""
        if not job.is_periodic() and not job.is_parameterized():
            ev = s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                namespace=job.namespace,
                triggered_by=s.EVAL_TRIGGER_JOB_DEREGISTER, job_id=job_id,
                job_modify_index=index, status=s.EVAL_STATUS_PENDING)
            self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
            eval_id = ev.id
        return index, eval_id

    def job_list(self, prefix: str = "", region: str = "",
                 min_index: int = 0,
                 max_wait: float = 0.0) -> Tuple[List[s.Job], int]:
        """Region-routed job listing (reads forward like writes —
        rpc.go:178 forwards every RPC, reads included).  Blocking-query
        semantics run at the OWNING region (min_index/max_wait travel
        with the forward, rpc.go:340 blockingRPC).  Returns (jobs, index)."""
        if region and region != self.config.region:
            from ..api.codec import ensure
            reply = self._forward_region(
                region, "Job.List",
                {"Prefix": prefix, "MinQueryIndex": min_index,
                 "MaxQueryTime": max_wait})
            return ([ensure(s.Job, j) for j in reply["Jobs"] or []],
                    int(reply.get("Index", 0)))
        self._block_on_table("jobs", min_index, max_wait)
        jobs = (self.state.jobs_by_id_prefix(None, prefix) if prefix
                else self.state.jobs(None))
        return jobs, self.state.table_index("jobs")

    def _block_on_table(self, table: str, min_index: int,
                        max_wait: float) -> None:
        """Server-side long-poll on a state table (rpc.go:340
        blockingRPC)."""
        if min_index <= 0 or max_wait <= 0:
            return
        from ..state.state_store import WatchSet
        deadline = time.time() + min(max_wait, 300.0)
        while self.state.table_index(table) <= min_index:
            remaining = deadline - time.time()
            if remaining <= 0:
                return
            ws = WatchSet()
            # register interest, then wait for the next write
            getattr(self.state, "jobs")(ws)
            ws.watch(timeout=min(remaining, 1.0))

    def job_get(self, job_id: str, region: str = "",
                min_index: int = 0,
                max_wait: float = 0.0) -> Optional[s.Job]:
        if region and region != self.config.region:
            from ..api.codec import ensure
            reply = self._forward_region(
                region, "Job.Get",
                {"JobID": job_id, "MinQueryIndex": min_index,
                 "MaxQueryTime": max_wait})
            data = reply.get("Job")
            return ensure(s.Job, data) if data else None
        self._block_on_table("jobs", min_index, max_wait)
        return self.state.job_by_id(None, job_id)

    def job_summary(self, job_id: str) -> Optional[s.JobSummary]:
        return self.state.job_summary_by_id(None, job_id)

    def job_allocations(self, job_id: str, all_allocs: bool = False) -> List[s.Allocation]:
        return self.state.allocs_by_job(None, job_id, all_allocs)

    def job_evaluations(self, job_id: str) -> List[s.Evaluation]:
        return self.state.evals_by_job(None, job_id)

    def job_plan(self, job: s.Job, diff: bool = True) -> s.JobPlanResponse:
        """Dry-run scheduling (job_endpoint.go:~490 Plan): run the scheduler
        synchronously against a snapshot with a no-op planner, returning the
        annotated job diff + placement forensics (nothing is committed)."""
        from ..scheduler import Harness, new_scheduler
        from ..scheduler.annotate import annotate
        from ..structs.diff import job_diff

        old_job = self.state.job_by_id(None, job.id)
        job = job.copy()
        job.canonicalize()
        snap = self.state.snapshot()
        index = self.raft.applied_index() + 1
        snap.upsert_job(index, job)

        harness = Harness(snap)
        harness._next_index = index + 1
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            job_modify_index=index, status=s.EVAL_STATUS_PENDING,
            annotate_plan=True)
        sched = new_scheduler(job.type, self.logger, snap.snapshot(), harness)
        sched.process(ev)
        plan = harness.plans[0] if harness.plans else ev.make_plan(job)

        # The scheduler records placement forensics on a *copy* of the eval
        # handed to Planner.UpdateEval (scheduler/util.go setStatus) — read
        # the updated eval from the harness, like job_endpoint.go Plan does.
        updated = next((e for e in reversed(harness.evals) if e.id == ev.id), ev)
        resp = s.JobPlanResponse(
            annotations=plan.annotations,
            failed_tg_allocs=dict(updated.failed_tg_allocs),
            job_modify_index=old_job.job_modify_index if old_job else 0,
            created_evals=list(harness.create_evals))
        if diff:
            resp.diff = job_diff(old_job, job)
            annotate(resp.diff, plan.annotations)
        if job.is_periodic():
            resp.next_periodic_launch = job.periodic.next(s.now())
        return resp

    def periodic_force(self, job_id: str) -> Optional[s.Job]:
        if not self._leader:
            reply = self._forward("Periodic.Force", {"JobID": job_id})
            child_id = reply.get("ChildJobID", "")
            if not child_id:
                return None
            child = self.state.job_by_id(None, child_id)
            return child or s.Job(id=child_id, name=child_id)
        return self.periodic.force_run(job_id)

    def job_evaluate(self, job_id: str) -> Tuple[int, str]:
        """Force a new evaluation for an existing job
        (job_endpoint.go Evaluate)."""
        job = self.state.job_by_id(None, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        if job.is_periodic():
            raise ValueError("can't evaluate periodic job")
        if job.is_parameterized():
            raise ValueError("can't evaluate parameterized job")
        if self._leader:
            self._check_tenant_admission(job)
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=job.priority, type=job.type,
            namespace=job.namespace,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            job_modify_index=job.modify_index, status=s.EVAL_STATUS_PENDING)
        tr = tracing.TRACER
        if tr is not None:
            tr.mark(ev.id, job_id=job.id, submit="job_evaluate",
                    priority=job.priority, namespace=job.namespace)
        try:
            _, index = self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
        except NotLeaderError:
            reply = self._forward("Job.Evaluate", {"JobID": job_id})
            return reply["Index"], reply["EvalID"]
        return index, ev.id

    def job_dispatch(self, job_id: str, payload: bytes,
                     meta: Dict[str, str]) -> Tuple[int, str, str]:
        """Dispatch an instance of a parameterized job
        (job_endpoint.go Dispatch): validate meta keys against the
        parameterized config, derive a child job carrying the payload,
        register it and create its eval.  Returns
        (index, dispatched_job_id, eval_id)."""
        parent = self.state.job_by_id(None, job_id)
        if parent is None:
            raise KeyError(f"job not found: {job_id}")
        if not parent.is_parameterized():
            raise ValueError(f"job {job_id!r} is not parameterized")
        cfg = parent.parameterized_job
        if cfg.payload == "required" and not payload:
            raise ValueError("payload is required by this parameterized job")
        if cfg.payload == "forbidden" and payload:
            raise ValueError("payload is forbidden by this parameterized job")
        if len(payload) > 16 * 1024:
            raise ValueError("payload exceeds maximum size of 16KiB")
        keys = set(meta)
        required = set(cfg.meta_required)
        allowed = required | set(cfg.meta_optional)
        if required - keys:
            raise ValueError(
                "missing required dispatch metadata: "
                + ", ".join(sorted(required - keys)))
        if keys - allowed:
            raise ValueError(
                "dispatch metadata not allowed: "
                + ", ".join(sorted(keys - allowed)))

        child = parent.copy()
        child.parent_id = parent.id
        child.id = f"{parent.id}/dispatch-{int(s.now())}-{s.generate_uuid()[:8]}"
        child.name = child.id
        child.parameterized_job = None
        child.payload = payload
        child.meta = dict(parent.meta)
        child.meta.update(meta)
        child.status = s.JOB_STATUS_PENDING
        if self._leader:
            self._check_tenant_admission(child)
        try:
            _, index = self.raft.apply(MessageType.JOB_REGISTER, {"job": child})
        except NotLeaderError:
            reply = self._forward("Job.Dispatch",
                                  {"JobID": job_id, "Payload": payload,
                                   "Meta": meta})
            return (reply["Index"], reply["DispatchedJobID"],
                    reply["EvalID"])
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=child.priority, type=child.type,
            namespace=child.namespace,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=child.id,
            job_modify_index=index, status=s.EVAL_STATUS_PENDING)
        self.raft.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})
        return index, child.id, ev.id

    def node_evaluate(self, node_id: str) -> List[str]:
        """Force re-evaluation of all jobs with allocs on a node
        (node_endpoint.go Evaluate)."""
        node = self.state.node_by_id(None, node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        try:
            return self._create_node_evals(node_id, node.modify_index)
        except NotLeaderError:
            return self._forward("Node.Evaluate",
                                 {"NodeID": node_id})["EvalIDs"]

    # -- status / operator -------------------------------------------------

    def leader_address(self) -> str:
        """Best-known leader RPC address (Status.Leader,
        status_endpoint.go)."""
        if isinstance(self.raft, MultiRaft):
            return self.raft.leader_addr or ""
        return self.config.rpc_advertise if self.is_leader() else ""

    def peer_addresses(self) -> List[str]:
        if isinstance(self.raft, MultiRaft):
            return list(self.raft.peers)
        return [self.config.rpc_advertise]

    def trace_for_eval_fanout(self, eval_id: str,
                              timeout: float = 1.0) -> Tuple[List, str]:
        """Spans for an eval, checking the local tracer first and then
        fanning out to peer servers over Status.TraceEval (the tracer is
        per-process: a follower-scheduled eval's spans live only on the
        scheduling follower, which 404'd leader-side trace links before
        this).  Best-effort and bounded: a dark follower is skipped, the
        first peer with spans wins.  Returns (spans, source_addr) — an
        empty list with source "" when nobody has the trace."""
        spans = tracing.trace_for_eval(eval_id)
        if spans:
            return spans, self.config.rpc_advertise
        if self.pool is None:
            return [], ""
        me = self.config.rpc_advertise
        for addr in self.peer_addresses():
            if addr == me:
                continue
            try:
                reply = self.pool.call(addr, "Status.TraceEval",
                                       {"EvalID": eval_id},
                                       timeout=timeout)
            except Exception:
                continue  # dark follower: skip, keep fanning out
            got = (reply or {}).get("Spans") or []
            if got:
                return got, addr
        return [], ""

    def operator_raft_remove_peer(self, address: str) -> None:
        """Remove a (possibly dead) server from the raft voter set
        (operator_endpoint.go RaftRemovePeerByAddress →
        api/operator.go:69): forwards to the leader, which replicates a
        new configuration without the peer."""
        if not address:
            raise ValueError("missing peer address")
        if self._leader:
            try:
                self._remove_peer_as_leader(address)
                return
            except NotLeaderError:
                pass  # stepped down mid-flight: forward like everyone else
        try:
            self._forward("Operator.RaftRemovePeerByAddress",
                          {"Address": address})
        except Exception as e:
            # The wire encodes errors as "<TypeName>: <message>"
            # (rpc.py): re-raise the leader's typed errors by TYPE so
            # the HTTP layer maps them to 404/400 regardless of which
            # server served the request (message wording may change;
            # the type prefix is the contract).
            msg = str(e)
            if msg.startswith("KeyError"):
                # Preserve the leader's message (it may be a different
                # KeyError than the peer-membership check).
                raise KeyError(msg.split(": ", 1)[-1].strip("'")) from e
            if msg.startswith("ValueError"):
                raise ValueError(msg.split(": ", 1)[-1]) from e
            raise
        return

    def _remove_peer_as_leader(self, address: str) -> None:
        if address == self.config.rpc_advertise:
            raise ValueError(
                "refusing to remove the current leader; remove it from "
                "another server after leadership moves")
        peers = [p for p in self.raft.peers if p != address]
        if len(peers) == len(self.raft.peers):
            raise KeyError(f"peer not found: {address}")
        self.raft.propose_config(peers)

    def raft_configuration(self) -> Dict:
        leader = self.leader_address()
        servers = []
        members = self.members() or [self._self_member()]
        for m in members:
            servers.append({
                "ID": m["Name"],
                "Node": m["Name"],
                "Address": m["Addr"],
                "Leader": m["Addr"] == leader if leader else (
                    m["Name"] == self.config.node_name and self.is_leader()),
                "Voter": True,
            })
        return {"Servers": servers, "Index": self.raft.applied_index()}

    # -- Node --------------------------------------------------------------

    def node_register(self, node: s.Node) -> Tuple[int, float]:
        """(node_endpoint.go Register): returns (index, heartbeat_ttl)."""
        node = node.copy()
        if not node.id:
            raise ValueError("missing node ID for client registration")
        existed = self.state.node_by_id(None, node.id)
        if not node.status:
            node.status = s.NODE_STATUS_INIT
        try:
            _, index = self.raft.apply(MessageType.NODE_REGISTER,
                                       {"node": node})
        except NotLeaderError:
            reply = self._forward("Node.Register", {"Node": node})
            return reply["Index"], reply["HeartbeatTTL"]
        ttl = self.heartbeat.reset_heartbeat_timer(node.id)
        # Transitions create node evals (node_endpoint.go:165).
        if existed is not None and existed.status != node.status:
            self._create_node_evals(node.id, index)
        return index, ttl

    def node_deregister(self, node_id: str) -> int:
        try:
            _, index = self.raft.apply(MessageType.NODE_DEREGISTER,
                                       {"node_id": node_id})
        except NotLeaderError:
            return self._forward("Node.Deregister", {"NodeID": node_id})["Index"]
        self.heartbeat.clear_heartbeat_timer(node_id)
        self._create_node_evals(node_id, index)
        # Deregistered node: same revocation sweep as the down
        # transition (node_endpoint.go:254-264).
        self._revoke_node_accessors(node_id)
        return index

    def node_update_status(self, node_id: str, status: str) -> Tuple[int, float]:
        """(node_endpoint.go:277 UpdateStatus) — heartbeat + transitions."""
        node = self.state.node_by_id(None, node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        if not self._leader:
            # Forward even when the status is unchanged: the heartbeat TTL
            # timer lives on the leader, and a follower acking a heartbeat
            # without resetting it would let the leader mark a healthy
            # node down (node_endpoint.go:277 forwards before anything).
            reply = self._forward("Node.UpdateStatus",
                                  {"NodeID": node_id, "Status": status})
            return reply["Index"], reply["HeartbeatTTL"]
        # Relaxed: the common no-transition heartbeat must not queue on
        # the raft lock behind the apply stream (at harness scale that
        # convoy starved renewals into expiry).
        index = self.raft.applied_index_relaxed()
        if node.status != status:
            _, index = self.raft.apply(
                MessageType.NODE_UPDATE_STATUS,
                {"node_id": node_id, "status": status})
            if self._should_create_node_evals(node.status, status):
                self._create_node_evals(node_id, index)
        ttl = 0.0
        if status != s.NODE_STATUS_DOWN:
            ttl = self.heartbeat.reset_heartbeat_timer(node_id)
        else:
            self.heartbeat.clear_heartbeat_timer(node_id)
            # A down node's tasks can no longer guard their secrets:
            # revoke every accessor derived for allocs on it
            # (node_endpoint.go:339-351).
            self._revoke_node_accessors(node_id)
        return index, ttl

    def _revoke_node_accessors(self, node_id: str) -> None:
        if not self.vault.enabled:
            return
        accessors = self.state.vault_accessors_by_node(None, node_id)
        if accessors:
            threading.Thread(target=self._revoke_accessors,
                             args=(accessors,), daemon=True).start()

    @staticmethod
    def _should_create_node_evals(old: str, new: str) -> bool:
        """(structs.go ShouldDrainNode/transition table)."""
        if old == new:
            return False
        if new in (s.NODE_STATUS_DOWN,):
            return True
        if old == s.NODE_STATUS_DOWN and new == s.NODE_STATUS_READY:
            return True
        if old == s.NODE_STATUS_INIT and new == s.NODE_STATUS_READY:
            return True
        return False

    def node_update_drain(self, node_id: str, drain: bool) -> int:
        node = self.state.node_by_id(None, node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        try:
            _, index = self.raft.apply(
                MessageType.NODE_UPDATE_DRAIN,
                {"node_id": node_id, "drain": drain})
        except NotLeaderError:
            return self._forward("Node.UpdateDrain",
                                 {"NodeID": node_id, "Drain": drain})["Index"]
        if drain:
            self._create_node_evals(node_id, index)
        return index

    def _create_node_evals(self, node_id: str, node_index: int) -> List[str]:
        """One eval per job with allocs on the node, plus system jobs
        (node_endpoint.go:803 createNodeEvals)."""
        allocs = self.state.allocs_by_node(None, node_id)
        job_ids = {a.job_id for a in allocs}
        evals: List[s.Evaluation] = []
        for job_id in job_ids:
            job = self.state.job_by_id(None, job_id)
            if job is None:
                continue
            evals.append(s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                namespace=job.namespace,
                triggered_by=s.EVAL_TRIGGER_NODE_UPDATE, job_id=job_id,
                node_id=node_id, node_modify_index=node_index,
                status=s.EVAL_STATUS_PENDING))
        for job in self.state.jobs_by_scheduler(None, s.JOB_TYPE_SYSTEM):
            if job.id in job_ids or job.stopped():
                continue
            evals.append(s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                namespace=job.namespace,
                triggered_by=s.EVAL_TRIGGER_NODE_UPDATE, job_id=job.id,
                node_id=node_id, node_modify_index=node_index,
                status=s.EVAL_STATUS_PENDING))
        if evals:
            self.raft.apply(MessageType.EVAL_UPDATE, {"evals": evals})
        return [e.id for e in evals]

    def node_get(self, node_id: str) -> Optional[s.Node]:
        return self.state.node_by_id(None, node_id)

    def node_list(self) -> List[s.Node]:
        return self.state.nodes(None)

    def node_get_allocs(self, node_id: str) -> List[s.Allocation]:
        return self.state.allocs_by_node(None, node_id)

    def derive_vault_token(self, alloc_id: str, task_names: List[str]
                           ) -> Dict[str, Dict]:
        """Derive per-task Vault tokens for a client
        (node_endpoint.go DeriveVaultToken → vault.go DeriveToken):
        validates the alloc, mints tokens, and registers the accessors
        through the log so a leader failover can still revoke them."""
        from ..state.state_store import VaultAccessor

        if not self._leader:
            # Forward before minting: a follower must not create tokens it
            # cannot register for revocation.
            reply = self._forward(
                "Node.DeriveVaultToken",
                {"AllocID": alloc_id, "Tasks": list(task_names)})
            return reply["Tasks"]
        alloc = self.state.alloc_by_id(None, alloc_id)
        if alloc is None:
            raise KeyError(f"allocation {alloc_id!r} not found")
        if alloc.terminal_status():
            raise VaultError("cannot derive token for terminal allocation")
        if alloc.job is None:
            alloc = alloc.copy()
            alloc.job = self.state.job_by_id(None, alloc.job_id)
        # Response-wrapped by default (vault.go getWrappingFn): the client
        # receives a single-use wrapping token, never the raw secret on
        # the wire; the accessor still registers server-side BEFORE
        # distribution so failover revocation works even if the client
        # never unwraps.  VaultConfig.wrap_derived_tokens=False restores
        # plain tokens for non-embedded clients that have no vault_addr
        # to unwrap against (ADVICE r5).
        wrapped = getattr(self.vault.config, "wrap_derived_tokens", True)
        tokens = self.vault.derive_token(alloc, task_names, wrapped=wrapped)
        accessors = [VaultAccessor(
            accessor=info["accessor"], alloc_id=alloc_id,
            node_id=alloc.node_id, task=task,
            creation_ttl=int(info.get("ttl", 0)),
        ) for task, info in tokens.items()]
        try:
            self.raft.apply(MessageType.VAULT_ACCESSOR_REGISTER,
                            {"accessors": accessors})
        except NotLeaderError:
            # Leadership lost between mint and registration: the tokens
            # exist in Vault but no replica knows about them — revoke
            # immediately rather than leak live credentials for their
            # full TTL (vault.go revokes on registration failure).
            self.vault.revoke_accessors([a.accessor for a in accessors])
            raise
        return tokens

    def node_get_client_allocs(self, node_id: str, min_index: int = 0,
                               max_wait: float = 0.0) -> Tuple[List[s.Allocation], int]:
        """Blocking-query variant the client's watchAllocations long-polls
        (node_endpoint.go:585 GetClientAllocs + rpc.go:340 blockingRPC):
        waits until the allocs table passes min_index or max_wait elapses,
        then returns (allocs, index)."""
        from ..state.state_store import WatchSet
        deadline = time.time() + max_wait
        while True:
            ws = WatchSet()
            allocs = self.state.allocs_by_node(ws, node_id)
            index = max(self.state.table_index("allocs"),
                        self.state.table_index("nodes"))
            if index > min_index or max_wait <= 0:
                return allocs, index
            remaining = deadline - time.time()
            if remaining <= 0:
                return allocs, index
            ws.watch(timeout=min(remaining, 1.0))

    def node_update_allocs(self, allocs: List[s.Allocation]) -> int:
        """Client alloc status sync (node_endpoint.go:657 UpdateAlloc)."""
        try:
            _, index = self.raft.apply(MessageType.ALLOC_CLIENT_UPDATE,
                                       {"allocs": allocs})
        except NotLeaderError:
            return self._forward(
                "Node.UpdateAlloc", {"Allocs": list(allocs)})["Index"]
        return index

    # -- Eval --------------------------------------------------------------

    def _require_leader(self) -> None:
        """Leader-only subsystems (broker/plan queue) live on the leader;
        callers on a follower get NotLeaderError (these calls are not
        forwarded — the in-process worker/plan pipeline only runs on the
        leader, matching nomad/worker.go's leader-local dequeue)."""
        if not self._leader:
            raise NotLeaderError(self.leader_address())

    def eval_dequeue(self, schedulers: List[str],
                     timeout: float = 0.0) -> Tuple[Optional[s.Evaluation], str]:
        self._require_leader()
        return self.eval_broker.dequeue(schedulers, timeout)

    def eval_dequeue_batch(self, schedulers: List[str], max_batch: int,
                           timeout: float = 0.0) -> Dict:
        """Remote-worker dequeue (Eval.DequeueBatch): up to ``max_batch``
        ready evals plus, per eval, the delivery-attempt count and the
        job's PLAN FENCE — the raft index of its newest committed plan
        (PlanQueue.applied_index_for).  A follower scheduler must cover
        ``max(eval.trigger_index(), fence)`` with its local log before
        scheduling (the follower-read staleness guard,
        server/follower_sched.py).  ``AppliedIndex`` carries the
        leader's applied index for the follower snapshot-lag gauge."""
        self._require_leader()
        batch = self.eval_broker.dequeue_batch(
            schedulers, max(1, min(int(max_batch), 32)), timeout)
        items = []
        for ev, token in batch:
            items.append({
                "eval": ev, "token": token,
                "attempts": self.eval_broker.delivery_attempts(ev.id),
                "fence": self.plan_queue.applied_index_for(ev.job_id),
            })
        return {"items": items,
                "applied_index": self.raft.applied_index_relaxed()}

    def eval_update(self, evals: List[s.Evaluation]) -> int:
        """Apply an EVAL_UPDATE on behalf of a remote worker
        (Eval.Update — the wire twin of WorkerPlanner.update_eval /
        create_eval / record_eval_failures)."""
        _, index = self.raft.apply(MessageType.EVAL_UPDATE,
                                   {"evals": evals})
        return index

    def eval_reblock(self, ev: s.Evaluation, token: str) -> int:
        """Apply + reblock on behalf of a remote worker (Eval.Reblock):
        the blocked-eval tracker is leader-local state, so the update
        and the reblock must land on the same server."""
        self._require_leader()
        _, index = self.raft.apply(MessageType.EVAL_UPDATE,
                                   {"evals": [ev]})
        self.blocked_evals.reblock(ev, token)
        return index

    def eval_pause_nack(self, eval_id: str, token: str) -> None:
        self._require_leader()
        self.eval_broker.pause_nack_timeout(eval_id, token)

    def eval_resume_nack(self, eval_id: str, token: str) -> None:
        self._require_leader()
        self.eval_broker.resume_nack_timeout(eval_id, token)

    def eval_ack(self, eval_id: str, token: str) -> None:
        if not self._leader:
            self._forward("Eval.Ack", {"EvalID": eval_id, "Token": token})
            return
        self.eval_broker.ack(eval_id, token)

    def eval_nack(self, eval_id: str, token: str) -> None:
        if not self._leader:
            self._forward("Eval.Nack", {"EvalID": eval_id, "Token": token})
            return
        self.eval_broker.nack(eval_id, token)

    def eval_get(self, eval_id: str) -> Optional[s.Evaluation]:
        return self.state.eval_by_id(None, eval_id)

    def eval_list(self) -> List[s.Evaluation]:
        return self.state.evals(None)

    def eval_allocations(self, eval_id: str) -> List[s.Allocation]:
        return self.state.allocs_by_eval(None, eval_id)

    # -- Alloc -------------------------------------------------------------

    def alloc_get(self, alloc_id: str) -> Optional[s.Allocation]:
        return self.state.alloc_by_id(None, alloc_id)

    def alloc_list(self) -> List[s.Allocation]:
        return self.state.allocs(None)

    # -- Plan --------------------------------------------------------------

    def plan_submit(self, plan: s.Plan):
        """(Plan.Submit → PlanQueue, plan_endpoint.go).

        Token fence: a plan whose eval token no longer matches the
        broker's OUTSTANDING delivery is a stale worker's submission —
        the nack deadline fired and the eval was redelivered (possibly
        to another server; follower-read deliveries run against the
        full deadline with no mid-flight pause).  Rejecting it here is
        what makes redelivery safe: same-job double placement is the
        one staleness the applier's capacity re-check cannot catch.
        Plans without a token (tests, direct operators) pass."""
        self._require_leader()
        if plan.eval_id and plan.eval_token:
            token, outstanding = self.eval_broker.outstanding(plan.eval_id)
            if outstanding and token != plan.eval_token:
                raise RuntimeError(
                    f"plan token fence: eval {plan.eval_id} was "
                    "redelivered; stale delivery's plan rejected")
        return self.plan_queue.enqueue(plan)

    # -- System ------------------------------------------------------------

    def system_gc(self) -> None:
        try:
            self._create_core_eval(s.CORE_JOB_FORCE_GC)
        except NotLeaderError:
            self._forward("System.GarbageCollect", {})

    def system_reconcile_summaries(self) -> None:
        try:
            self.raft.apply(MessageType.RECONCILE_JOB_SUMMARIES, {})
        except NotLeaderError:
            self._forward("System.ReconcileJobSummaries", {})

    # -- Namespace (tenancy plane) -----------------------------------------

    def namespace_upsert(self, ns: s.Namespace, region: str = "") -> int:
        """Register/update a tenant through raft (like jobs): validate →
        log NAMESPACE_UPSERT; policy mirrors refresh via the FSM hook.
        Namespaces are REGION-SCOPED (each region's raft owns its tenant
        rows and enforces their quotas locally): an explicit ``region``
        routes over the federation, like jobs."""
        if region and region != self.config.region:
            reply = self._forward_region(region, "Namespace.Upsert",
                                         {"Namespace": ns})
            return reply["Index"]
        ns = ns.copy()
        problems = ns.validate()
        if problems:
            raise ValueError(
                "namespace validation failed: " + "; ".join(problems))
        try:
            _, index = self.raft.apply(MessageType.NAMESPACE_UPSERT,
                                       {"namespace": ns})
        except NotLeaderError:
            reply = self._forward("Namespace.Upsert", {"Namespace": ns})
            return reply["Index"]
        return index

    def namespace_delete(self, name: str, region: str = "") -> int:
        if region and region != self.config.region:
            reply = self._forward_region(region, "Namespace.Delete",
                                         {"Name": name})
            return reply["Index"]
        if name == s.DEFAULT_NAMESPACE:
            raise ValueError("cannot delete the default namespace")
        if self.state.namespace_by_name(None, name) is None:
            raise KeyError(f"namespace not found: {name}")
        try:
            _, index = self.raft.apply(MessageType.NAMESPACE_DELETE,
                                       {"name": name})
        except NotLeaderError:
            reply = self._forward("Namespace.Delete", {"Name": name})
            return reply["Index"]
        return index

    def namespace_list(self, region: str = "") -> List[s.Namespace]:
        if region and region != self.config.region:
            reply = self._forward_region(region, "Namespace.List", {})
            return reply["Namespaces"]
        return self.state.namespaces(None)

    def namespace_status(self, name: str, region: str = "") -> Dict:
        """One tenant's row + live usage + broker counters — the
        namespace-status CLI/HTTP read."""
        if region and region != self.config.region:
            return self._forward_region(region, "Namespace.Status",
                                        {"Name": name})
        row = self.state.namespace_by_name(None, name)
        if row is None:
            raise KeyError(f"namespace not found: {name}")
        cpu, mem, disk, iops, live = self.state.namespace_usage_one(name)
        self._refresh_capacity()
        cap, nodes = self._cluster_capacity, self._cluster_nodes
        return {
            "Namespace": row,
            "Usage": {"CPU": cpu, "MemoryMB": mem, "DiskMB": disk,
                      "IOPS": iops, "LiveAllocs": live,
                      "NodeUnits": self._node_units(
                          (cpu, mem, disk, iops), cap, nodes)},
            "ReservedAllocs": self.quota_ledger.reserved(name),
            "ReservedNodeUnits": self.node_units_ledger.reserved(name),
            "PendingEvals": self.eval_broker.ns_pending_count(name),
        }

    def broker_stats(self) -> Dict:
        """The /v1/broker/stats saturation surface: broker admission /
        coalesce state plus the plan-queue depth (the two stages whose
        backlogs say whether the control plane is keeping up)."""
        out = self.eval_broker.extended_stats()
        out["PlanQueueDepth"] = self.plan_queue.depth()
        out["BlockedEvals"] = self.blocked_evals.stats()
        # Follower-read scheduling surface (ISSUE 10): what THIS server
        # is forwarding to the leader, and how far its replicated FSM
        # lags the commit horizon it knows about.
        fs: Dict = {"Enabled": bool(self.follower_workers),
                    "IsLeader": self._leader}
        if self.leader_channel is not None:
            fs.update(self.leader_channel.stats())
        if isinstance(self.raft, MultiRaft):
            fs["SnapshotLag"] = max(
                0, self.raft.commit_index
                - self.raft.applied_index_relaxed())
        out["FollowerSched"] = fs
        return out

    def stats(self) -> Dict:
        out = {
            "leader": self._leader,
            "applied_index": self.raft.applied_index(),
            "broker": self.eval_broker.stats(),
            "blocked": self.blocked_evals.stats(),
            "plan_queue_depth": self.plan_queue.depth(),
            "heartbeat_active": self.heartbeat.active(),
        }
        if self._events_enabled:
            out["events"] = self.event_broker.stats()
        sink = self.metrics.sink
        if hasattr(sink, "latest"):
            latest = sink.latest()
            out["metrics_gauges"] = latest["Gauges"]
            out["metrics_samples"] = {
                k: f"count={v['count']} mean={v['mean']}ms"
                for k, v in latest["Samples"].items()}
        return out
