"""CoreScheduler: internal GC jobs run through the normal eval pipeline
(reference: nomad/core_sched.go:24-439).

Eval type is '_core' and the eval's JobID selects the GC pass:
eval-gc, job-gc, node-gc, or force-gc (structs.go CoreJob* constants).
Thresholds are index-based via the TimeTable."""
from __future__ import annotations

import logging
import time
from typing import List, Optional

from ..structs import structs as s
from .fsm import MessageType

# GC thresholds (reference: nomad/config.go defaults).
EVAL_GC_THRESHOLD = 3600.0        # 1h
JOB_GC_THRESHOLD = 4 * 3600.0     # 4h
NODE_GC_THRESHOLD = 24 * 3600.0   # 24h


class CoreScheduler:
    def __init__(self, logger: logging.Logger, snap, planner, raft,
                 time_table=None):
        self.logger = logger
        self.snap = snap
        self.planner = planner
        self.raft = raft
        self.time_table = time_table

    def process(self, ev: s.Evaluation) -> None:
        """(core_sched.go:43 Process)."""
        job_id = ev.job_id
        force = job_id == s.CORE_JOB_FORCE_GC
        if job_id in (s.CORE_JOB_EVAL_GC,) or force:
            self._eval_gc(ev, force)
        if job_id in (s.CORE_JOB_JOB_GC,) or force:
            self._job_gc(ev, force)
        if job_id in (s.CORE_JOB_NODE_GC,) or force:
            self._node_gc(ev, force)
        ev2 = ev.copy()
        ev2.status = s.EVAL_STATUS_COMPLETE
        self.planner.update_eval(ev2)

    # -- helpers -----------------------------------------------------------

    def _threshold_index(self, threshold: float, force: bool) -> int:
        if force:
            return self.raft.applied_index()
        if self.time_table is None:
            return 0
        return self.time_table.nearest_index(time.time() - threshold)

    # -- passes ------------------------------------------------------------

    def _eval_gc(self, ev: s.Evaluation, force: bool) -> None:
        """Terminal evals older than the threshold, plus their allocs if
        every alloc is terminal (core_sched.go:64 evalGC)."""
        threshold = self._threshold_index(EVAL_GC_THRESHOLD, force)
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for evaluation in self.snap.evals(None):
            if evaluation.modify_index >= threshold:
                continue
            if not evaluation.terminal_status():
                continue
            allocs = self.snap.allocs_by_eval(None, evaluation.id)
            if any(not a.terminal_status() or a.modify_index >= threshold
                   for a in allocs):
                continue
            gc_evals.append(evaluation.id)
            gc_allocs.extend(a.id for a in allocs)
        if gc_evals or gc_allocs:
            self.logger.info("eval GC: %d evals, %d allocs",
                             len(gc_evals), len(gc_allocs))
            self.raft.apply(MessageType.EVAL_DELETE,
                            {"evals": gc_evals, "allocs": gc_allocs})

    def _job_gc(self, ev: s.Evaluation, force: bool) -> None:
        """Dead GC-able jobs with only terminal allocs/evals
        (core_sched.go:170 jobGC)."""
        threshold = self._threshold_index(JOB_GC_THRESHOLD, force)
        for job in self.snap.jobs_by_gc(None, True):
            if job.modify_index >= threshold or job.status != s.JOB_STATUS_DEAD:
                continue
            if job.is_periodic():
                continue
            evals = self.snap.evals_by_job(None, job.id)
            if any(not e.terminal_status() for e in evals):
                continue
            allocs = self.snap.allocs_by_job(None, job.id, True)
            if any(not a.terminal_status() for a in allocs):
                continue
            self.logger.info("job GC: %s", job.id)
            self.raft.apply(MessageType.EVAL_DELETE, {
                "evals": [e.id for e in evals],
                "allocs": [a.id for a in allocs]})
            self.raft.apply(MessageType.JOB_DEREGISTER,
                            {"job_id": job.id, "purge": True})

    def _node_gc(self, ev: s.Evaluation, force: bool) -> None:
        """Down nodes with no allocs (core_sched.go:300 nodeGC)."""
        threshold = self._threshold_index(NODE_GC_THRESHOLD, force)
        for node in self.snap.nodes(None):
            if node.modify_index >= threshold:
                continue
            if node.status != s.NODE_STATUS_DOWN:
                continue
            if self.snap.allocs_by_node(None, node.id):
                continue
            self.logger.info("node GC: %s", node.id)
            self.raft.apply(MessageType.NODE_DEREGISTER, {"node_id": node.id})
