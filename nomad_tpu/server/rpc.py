"""RPC layer: msgpack-RPC over TCP with byte-prefix protocol demux.

Reference behavior: nomad/rpc.go — a single TCP port serves every protocol,
demuxed by the first byte (rpc.go:23-30: rpcNomad=0x01, rpcRaft=0x02,
rpcMultiplex=0x03, rpcTLS=0x04); net/rpc with a msgpack codec
(rpc.go:59-67); ``forward`` routes calls to the cluster leader or a remote
region (rpc.go:178-283); ConnPool reuses connections (nomad/pool.go).

Frame format on the Nomad channel: length-prefixed msgpack arrays
``[seq, method, body]`` for requests and ``[seq, error, body]`` for
responses — the moral of net/rpc's request/response header pairs.  The Raft
channel carries the same framing but is dispatched to the consensus layer
(raft_rpc.go RaftLayer).
"""

from __future__ import annotations

import dataclasses
import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

from .. import codec, fault
from ..utils import tracing
from ..utils.telemetry import NULL_TELEMETRY

# Protocol bytes (rpc.go:23-30)
RPC_NOMAD = 0x01
RPC_RAFT = 0x02
# Struct-codec channel (ISSUE 11): same [seq, method, body] envelopes,
# but frames may carry the generated flat binary layout (codec.MAGIC
# per-frame tag) instead of reflection msgpack.  Dialers handshake —
# the server acks with its codec version + schema fingerprint — and
# negotiate DOWN per connection: an old peer closes on the unknown
# protocol byte and the dialer redials the legacy channel; a peer on a
# different schema keeps the connection but sends msgpack frames (every
# receiver sniffs per frame).
RPC_NOMAD_CODEC = 0x05

_LEN = struct.Struct("<I")


class RPCError(Exception):
    pass


class TransportError(RPCError):
    """Connection-level failure (dial/read/write) — unlike an application
    error reply from the remote."""


class DialError(TransportError):
    """The connection could not even be established: the request was never
    sent, so retrying elsewhere cannot double-apply it."""


class NoLeaderError(RPCError):
    pass


class NoPathToRegion(RPCError):
    """Cross-region forwarding exhausted its bounded dial rounds: every
    known server of the target region was unreachable at DIAL time (so
    nothing was ever sent and nothing can have double-applied).  Typed
    so callers can tell "region unreachable" from "no leader": it
    carries the target ``region`` and a ``retry_after`` hint, the HTTP
    layer maps it to 429 + Retry-After, and the RPC layer re-types it
    from the wire error string — a down region degrades to a retryable
    error, never a hang."""

    def __init__(self, region: str, retry_after: float, rounds: int = 0,
                 detail: str = ""):
        self.region = region
        self.retry_after = retry_after
        self.rounds = rounds
        super().__init__(
            f"no path to region '{region}' after {rounds} dial rounds"
            + (f" ({detail})" if detail else "")
            + f"; retry_after={retry_after:.2f}")

    @staticmethod
    def from_message(msg: str) -> "NoPathToRegion":
        """Rebuild from the wire error string (the server encodes
        errors as '<TypeName>: <message>')."""
        import re

        m = re.search(r"region '([^']*)'", msg)
        region = m.group(1) if m else ""
        m = re.search(r"retry_after=([0-9.]+)", msg)
        retry = float(m.group(1)) if m else 1.0
        m = re.search(r"after (\d+) dial rounds", msg)
        rounds = int(m.group(1)) if m else 0
        return NoPathToRegion(region, retry, rounds=rounds)


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _wire_default(v: Any) -> Any:
    """msgpack ``default`` hook: hot endpoints hand the frame layer RAW
    dataclasses; on a legacy (msgpack) connection they serialize to the
    exact CamelCase wire trees old peers already speak."""
    from ..api.codec import to_wire

    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return to_wire(v)
    if getattr(v, "__lazy_strs__", False):
        return list(v)
    raise TypeError(f"unserializable rpc value {type(v).__name__}")


def _pack_frame(obj: Any, binary: bool) -> bytes:
    """One frame payload: the generated struct codec when the
    connection negotiated it (falling back per frame on schema drift),
    reflection msgpack otherwise.  Both sides of every connection sniff
    the per-frame tag, so mixed frames on one stream are fine."""
    if binary and codec.enabled():
        try:
            return codec.encode(obj, "rpc")
        except codec.CodecError:
            pass  # fallback counted by codec.encode
    t0 = time.monotonic()
    data = msgpack.packb(obj, use_bin_type=True, default=_wire_default)
    codec.note_msgpack("rpc", "encode", t0, len(data))
    return data


def _unpack_frame(data: bytes) -> Tuple[Any, bool]:
    """Decode one frame payload, sniffing the per-frame codec tag.
    Returns (obj, was_binary).  A malformed codec frame surfaces as
    TransportError like any other desynchronized stream."""
    if codec.is_frame(data):
        try:
            return codec.decode(data, "rpc"), True
        except codec.CodecError as e:
            raise TransportError(f"bad codec frame: {e}") from e
    t0 = time.monotonic()
    obj = msgpack.unpackb(data, raw=False)
    codec.note_msgpack("rpc", "decode", t0, len(data))
    return obj, False


def _send_frame(sock: socket.socket, obj: Any,
                binary: bool = False) -> None:
    data = _pack_frame(obj, binary)
    act = fault.faultpoint("rpc.send")
    if act is not None:
        if act.kind == "drop":
            return  # frame lost on the wire; the peer's read times out
        if act.kind == "delay":
            time.sleep(act.delay)
        elif act.kind == "dup":
            sock.sendall(_LEN.pack(len(data)) + data)
        elif act.kind == "truncate":
            # Ship the length prefix + a partial payload, then sever the
            # connection: the peer reads EOF mid-frame (the torn-write
            # shape _recv_exact must surface as TransportError).
            cut = max(1, len(data) // 2)
            sock.sendall(_LEN.pack(len(data)) + data[:cut])
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
            raise ConnectionError(act.message)
        elif act.kind in ("error", "crash"):
            # Surface as the transport failure a real broken wire raises,
            # so the fault exercises the SAME classify/discard/retry
            # machinery production errors take (ConnPool wraps this into
            # TransportError; RemoteServerRPC demotes and retries).
            raise ConnectionError(act.message)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            # EOF mid-frame is a transport failure, not a decode problem:
            # surfacing it as TransportError (with how much arrived) keeps
            # a truncated frame from propagating as a confusing
            # struct/msgpack error further up.
            if buf:
                raise TransportError(
                    f"connection closed mid-frame ({len(buf)}/{n} bytes)")
            raise TransportError("connection closed")
        buf += chunk
    return buf


def _recv_frame_tagged(sock: socket.socket) -> Tuple[Any, bool]:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > 64 << 20:
        # A ludicrous length prefix means the stream is desynchronized
        # (or hostile): transport-level, the connection must be discarded.
        raise TransportError(f"frame too large: {n}")
    return _unpack_frame(_recv_exact(sock, n))


def _recv_frame(sock: socket.socket) -> Any:
    return _recv_frame_tagged(sock)[0]


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


class RPCServer:
    """TCP listener demuxing Nomad-RPC and Raft channels onto handlers.

    ``register(method, fn)`` exposes ``fn(body) -> reply`` on the Nomad
    channel; ``raft_handler`` receives raft messages (election/replication)
    from peers.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 logger: Optional[logging.Logger] = None,
                 tls_context=None, metrics=None):
        self.logger = logger or logging.getLogger("nomad_tpu.rpc")
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        self.methods: Dict[str, Callable[[Any], Any]] = {}
        self.raft_handler: Optional[Callable[[Any], Any]] = None
        self.tls_context = tls_context
        outer = self

        self._active: set = set()
        self._active_lock = threading.Lock()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                # Track the RAW socket first so shutdown() can sever a
                # connection stuck mid-handshake; bound the handshake so a
                # silent peer cannot pin this thread forever.
                with outer._active_lock:
                    outer._active.add(sock)
                if outer.tls_context is not None:
                    # mTLS: every connection handshakes before the
                    # protocol byte (helper/tlsutil wraps the whole
                    # stream; rpcTLS demux byte in the reference).
                    try:
                        sock.settimeout(10.0)
                        tls_sock = outer.tls_context.wrap_socket(
                            sock, server_side=True)
                        tls_sock.settimeout(None)
                    except OSError as e:
                        outer.logger.warning("rpc: TLS handshake failed: %s",
                                             e)
                        with outer._active_lock:
                            outer._active.discard(sock)
                        return
                    with outer._active_lock:
                        outer._active.discard(sock)
                        outer._active.add(tls_sock)
                    sock = tls_sock
                try:
                    try:
                        prefix = _recv_exact(sock, 1)[0]
                    except (TransportError, ConnectionError, OSError):
                        return
                    if prefix == RPC_NOMAD:
                        outer._serve_nomad(sock)
                    elif prefix == RPC_RAFT:
                        outer._serve_raft(sock)
                    elif prefix == RPC_NOMAD_CODEC and codec.enabled():
                        # Handshake ack: magic + version + schema
                        # fingerprint.  The dialer compares fingerprints
                        # and falls back to msgpack FRAMES on mismatch
                        # (the channel still serves: every frame is
                        # sniffed).
                        try:
                            sock.sendall(bytes((codec.MAGIC,
                                                codec.VERSION))
                                         + codec.FINGERPRINT)
                        except OSError:
                            return
                        outer._serve_nomad(sock)
                    else:
                        # Unknown byte — including the codec channel
                        # under NOMAD_TPU_CODEC=0 (an old msgpack-only
                        # build behaves identically): close, and the
                        # dialer negotiates down to the legacy channel.
                        outer.logger.warning(
                            "rpc: unrecognized protocol byte %#x", prefix)
                finally:
                    with outer._active_lock:
                        outer._active.discard(sock)
                        outer._active.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.tcp = Server((host, port), Handler)
        self.host = host
        self.port = self.tcp.server_address[1]
        self._thread = threading.Thread(target=self.tcp.serve_forever,
                                        name="rpc", daemon=True)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        self.tcp.shutdown()
        self.tcp.server_close()
        # Established connections must die with the server: a peer's pooled
        # connection left open would keep talking to this dead instance's
        # in-memory state instead of reconnecting to its successor.
        with self._active_lock:
            conns = list(self._active)
            self._active.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def register(self, method: str, fn: Callable[[Any], Any]) -> None:
        self.methods[method] = fn

    def _serve_nomad(self, sock: socket.socket) -> None:
        """One connection, many sequential requests (like a net/rpc codec
        session over a pooled yamux stream)."""
        while True:
            try:
                (seq, method, body), req_binary = _recv_frame_tagged(sock)
            except (TransportError, ConnectionError, OSError, ValueError):
                return
            self.metrics.incr_counter("rpc.request")
            if not req_binary:
                # Per-method msgpack-frame accounting (ISSUE 12
                # satellite): the residual reflection traffic must be
                # provably Status/Serf control chatter, never a hot
                # scheduling method — codec.msgpack_methods() is the
                # profile `bench --check` and the soak report read.
                codec.note_msgpack_method(method)
            fn = self.methods.get(method)
            if fn is None:
                # Unknown methods are rejected traffic, not silence.
                self.metrics.incr_counter("rpc.request_error")
                reply = [seq, f"rpc: can't find method {method}", None]
            else:
                t0 = time.perf_counter()
                # Branch before building the span attrs: the disarmed
                # per-request path pays one load + comparison only.
                tr = tracing.TRACER
                req_span = tracing.NOOP if tr is None else tr.span(
                    "rpc.request", method=method)
                try:
                    with req_span:
                        reply = [seq, None, fn(body)]
                except NoLeaderError as e:
                    reply = [seq, f"__no_leader__:{e}", None]
                except Exception as e:  # error string back to caller
                    self.metrics.incr_counter("rpc.request_error")
                    reply = [seq, f"{type(e).__name__}: {e}", None]
                self.metrics.measure_since(f"rpc.request.{method}", t0)
            try:
                # Reply in the codec the request arrived in: the peer
                # chose it at handshake (or per frame on schema drift).
                _send_frame(sock, reply, binary=req_binary)
            except (ConnectionError, OSError):
                return

    def _serve_raft(self, sock: socket.socket) -> None:
        while True:
            try:
                seq, _method, body = _recv_frame(sock)
            except (TransportError, ConnectionError, OSError, ValueError):
                return
            handler = self.raft_handler
            if handler is None:
                reply = [seq, "raft: not ready", None]
            else:
                try:
                    reply = [seq, None, handler(body)]
                except Exception as e:
                    reply = [seq, f"{type(e).__name__}: {e}", None]
            try:
                _send_frame(sock, reply)
            except (ConnectionError, OSError):
                return


# ---------------------------------------------------------------------------
# client side / conn pool (nomad/pool.go)
# ---------------------------------------------------------------------------


class _HandshakeRefused(Exception):
    """The peer closed on the codec protocol byte: an old msgpack-only
    build (or NOMAD_TPU_CODEC=0).  The pool negotiates the ADDRESS down
    to the legacy channel and redials."""


class _Conn:
    def __init__(self, addr: str, channel: int, timeout: float,
                 tls_context=None):
        host, port = addr.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=timeout)
        if tls_context is not None:
            self.sock = tls_context.wrap_socket(self.sock,
                                                server_hostname=host)
        self.sock.sendall(bytes([channel]))
        self.binary = False
        if channel == RPC_NOMAD_CODEC:
            # Codec handshake: ack = magic + version + 8-byte schema
            # fingerprint.  A clean EOF here is the old-peer signature
            # (it reads the unknown protocol byte and orderly-closes) →
            # _HandshakeRefused, and the pool pins the ADDRESS to the
            # legacy channel.  Timeouts and resets are NOT refusals — a
            # restarting or GIL-stalled codec peer must not get
            # demoted to msgpack for the process lifetime — they
            # surface as dial failures and the next dial re-probes.  A
            # fingerprint/version mismatch keeps the connection but
            # pins it to msgpack frames: flat layouts are only spoken
            # between peers PROVEN to share the schema.
            try:
                self.sock.settimeout(timeout)
                ack = _recv_exact(self.sock, 2 + len(codec.FINGERPRINT))
            except TransportError as e:
                try:
                    self.sock.close()
                except OSError:
                    pass
                if "mid-frame" in str(e):
                    # Partial ack then EOF: the peer was mid-crash, not
                    # refusing the protocol — don't mark legacy.
                    raise ConnectionError(
                        f"codec handshake torn: {e}") from e
                raise _HandshakeRefused(str(e)) from e
            except (ConnectionError, OSError) as e:
                # Reset / timeout: transient transport failure.
                try:
                    self.sock.close()
                except OSError:
                    pass
                raise
            self.binary = (ack[0] == codec.MAGIC
                           and ack[1] == codec.VERSION
                           and ack[2:] == codec.FINGERPRINT)
        self.seq = 0
        self.lock = threading.Lock()

    def call(self, method: str, body: Any, timeout: float) -> Any:
        with self.lock:
            self.seq += 1
            seq = self.seq
            self.sock.settimeout(timeout)
            _send_frame(self.sock, [seq, method, body],
                        binary=self.binary)
            rseq, err, reply = _recv_frame(self.sock)
        if rseq != seq:
            # Desynchronized stream — the connection is unusable.
            raise ConnectionError(f"rpc: sequence mismatch ({rseq} != {seq})")
        if err:
            if isinstance(err, str) and err.startswith("__no_leader__:"):
                raise NoLeaderError(err.split(":", 1)[1])
            if isinstance(err, str) and err.startswith("BrokerLimitError"):
                # Re-type the admission NACK so wire callers get the
                # retry_after hint instead of a generic RPCError (the
                # client's jittered-backoff retry plumbing keys on it).
                from .eval_broker import BrokerLimitError

                raise BrokerLimitError.from_message(err)
            if isinstance(err, str) and err.startswith("NoPathToRegion"):
                # A remote server's cross-region forward exhausted its
                # dial rounds — re-type so the caller sees the target
                # region and retry_after hint rather than a bare string.
                raise NoPathToRegion.from_message(err)
            raise RPCError(err)
        return reply

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class ConnPool:
    """Connection reuse per (addr, channel) (pool.go:144).

    Hands out *parallel* connections: a call checks out an idle connection
    (or dials a new one) and returns it afterwards, so a long-poll holding
    one connection cannot starve short calls — the role yamux stream
    multiplexing plays in the reference (pool.go getClient + yamux
    Session.Open).

    Chaos surface (ISSUE 12): every call passes the ``net.send`` fault
    point and every fresh dial the ``net.dial`` point, carrying
    ``(local_addr, addr)`` so named partition groups and asymmetric
    src/dst rules apply — this single seam covers the Nomad channel AND
    the MultiRaft replication transport (raft replication rides
    ``pool.call`` too).  ``local_addr`` is stamped by the owning Server
    with its advertised address; pools without an identity (clients)
    match only ``*`` patterns.  ``chaos_exempt`` pools bypass the plane
    entirely — the harness's control/audit channel, which must reach a
    "partitioned" server the way an out-of-band console would.
    """

    MAX_IDLE_PER_KEY = 4
    # Per-address dial backoff (redial-storm fix): a dead peer's
    # dials fail instantly (connection refused), so every retry round —
    # replicators, elections, RemoteServerRPC walks — used to hammer it
    # with a fresh socket.  Failures now arm a shared jittered Backoff
    # per address; while it holds, dials fail fast LOCALLY (DialError,
    # no socket) and the cap bounds how stale the gate can get.
    DIAL_BACKOFF_BASE = 0.05
    DIAL_BACKOFF_MAX = 2.0

    def __init__(self, timeout: float = 10.0, tls_context=None):
        self.timeout = timeout
        self.tls_context = tls_context
        self.local_addr = ""       # stamped by the owning Server
        self.chaos_exempt = False  # control/audit pools bypass the plane
        self._idle: Dict[Tuple[str, int], List[_Conn]] = {}
        self._lock = threading.Lock()
        # Addresses that refused the codec handshake (old builds /
        # kill-switched peers): remembered so every later dial goes
        # straight to the legacy channel — per-connection negotiation,
        # paid once per address.
        self._legacy_addrs: set = set()
        # addr -> (Backoff, not_before_monotonic)
        self._dial_gate: Dict[str, list] = {}

    def _net_check(self, kind: str, addr: str) -> None:
        """Partition/rule verdict for one dial or call.  Blocked traffic
        surfaces as DialError: the request was never sent, so every
        retry path may safely go elsewhere (the same guarantee a real
        unreachable peer gives)."""
        if self.chaos_exempt:
            return
        act = fault.netpoint(kind, self.local_addr, addr)
        if act is None:
            return
        action, delay = act
        if action == "drop":
            raise DialError(
                f"rpc to {addr} failed: network partitioned (injected)")
        if delay > 0:
            time.sleep(delay)

    def _dial(self, addr: str, channel: int, timeout: float) -> _Conn:
        self._net_check("dial", addr)
        now = time.monotonic()
        with self._lock:
            gate = self._dial_gate.get(addr)
            if gate is not None and now < gate[1]:
                raise DialError(
                    f"rpc to {addr} failed: in dial backoff for another "
                    f"{gate[1] - now:.2f}s after {gate[0].attempt} "
                    "consecutive dial failures")
        try:
            conn = self._dial_raw(addr, channel, timeout)
        except OSError:
            from ..utils.backoff import Backoff
            with self._lock:
                gate = self._dial_gate.get(addr)
                if gate is None:
                    gate = [Backoff(base=self.DIAL_BACKOFF_BASE,
                                    max_delay=self.DIAL_BACKOFF_MAX), 0.0]
                    self._dial_gate[addr] = gate
                gate[1] = time.monotonic() + gate[0].next_delay()
            raise
        with self._lock:
            self._dial_gate.pop(addr, None)
        return conn

    def _dial_raw(self, addr: str, channel: int, timeout: float) -> _Conn:
        if (channel == RPC_NOMAD and codec.enabled()
                and addr not in self._legacy_addrs):
            try:
                return _Conn(addr, RPC_NOMAD_CODEC, timeout,
                             tls_context=self.tls_context)
            except _HandshakeRefused as e:
                # Orderly refusal = old build / kill-switched peer.
                # Visible: operators should be able to tell a
                # negotiated-down fleet from a codec one.
                logging.getLogger("nomad_tpu.rpc").info(
                    "rpc: %s refused the codec channel (%s); pinning "
                    "legacy msgpack for this address", addr, e)
                codec.TELEMETRY.incr_counter("codec.negotiate_down")
                with self._lock:
                    self._legacy_addrs.add(addr)
        return _Conn(addr, channel, timeout,
                     tls_context=self.tls_context)

    def call(self, addr: str, method: str, body: Any,
             channel: int = RPC_NOMAD, timeout: Optional[float] = None) -> Any:
        timeout = timeout if timeout is not None else self.timeout
        self._net_check("send", addr)
        key = (addr, channel)
        with self._lock:
            bucket = self._idle.get(key)
            conn = bucket.pop() if bucket else None
        if conn is None:
            try:
                conn = self._dial(addr, channel, timeout)
            except OSError as e:  # includes ssl.SSLError
                raise DialError(f"rpc to {addr} failed: {e}") from e
        try:
            reply = conn.call(method, body, timeout)
        except TransportError:
            # Already classified (EOF mid-frame, oversized/desynced
            # frame): the socket is poisoned — discard, never re-pool.
            conn.close()
            raise
        except (ConnectionError, OSError) as e:
            # Includes socket.timeout: a reply may still be in flight, so
            # releasing this connection would hand the NEXT caller a stale
            # response (sequence mismatch at best).  Discard.
            conn.close()
            raise TransportError(f"rpc to {addr} failed: {e}") from e
        except RPCError:
            # Application-level error reply: the transport is still healthy,
            # keep the connection pooled.
            self._release(key, conn)
            raise
        self._release(key, conn)
        return reply

    def _release(self, key: Tuple[str, int], conn: _Conn) -> None:
        with self._lock:
            bucket = self._idle.setdefault(key, [])
            if len(bucket) < self.MAX_IDLE_PER_KEY:
                bucket.append(conn)
                return
        conn.close()

    def invalidate(self, addr: str) -> None:
        """Drop every idle connection to ``addr`` (all channels), clear
        its dial gate, and un-pin any legacy-msgpack demotion: a peer
        KNOWN to have restarted leaves only dead sockets in the pool
        (draining them one TransportError at a time wastes a failed
        call per conn), and a zero-byte EOF its death raced into the
        codec handshake must not demote its codec-capable successor to
        msgpack for the pool's lifetime — the next dial re-probes."""
        with self._lock:
            dead = [conn for key, bucket in self._idle.items()
                    if key[0] == addr for conn in bucket]
            for key in [k for k in self._idle if k[0] == addr]:
                del self._idle[key]
            self._dial_gate.pop(addr, None)
            self._legacy_addrs.discard(addr)
        for conn in dead:
            conn.close()

    def close(self) -> None:
        with self._lock:
            for bucket in self._idle.values():
                for conn in bucket:
                    conn.close()
            self._idle.clear()


# ---------------------------------------------------------------------------
# client agent -> server RPC adapter
# ---------------------------------------------------------------------------


class RemoteServerRPC:
    """The duck-typed RPC surface nomad_tpu.client.Client expects
    (node_register / node_update_status / node_get_client_allocs /
    node_update_allocs), carried over the wire to a server — what the
    reference client does via msgpack-RPC (client/rpc via
    client.go:465 Client.RPC).

    Retries across the server list with bounded rounds and jittered
    exponential backoff between them (a fleet of clients whose leader
    died must not re-dial in lockstep).  A ``NoLeaderError`` reply
    carries the responding follower's best-known leader address — that
    server is promoted to the front of the list so the next attempt goes
    straight at the leader instead of re-walking stale entries.
    """

    MAX_ROUNDS = 3

    def __init__(self, servers: List[str], pool: Optional[ConnPool] = None,
                 max_rounds: Optional[int] = None, sleep=time.sleep):
        from ..api.codec import ensure
        from ..utils.backoff import Backoff
        self._ensure = ensure
        self.servers = list(servers)
        self.pool = pool or ConnPool()
        self.max_rounds = max_rounds or self.MAX_ROUNDS
        self._sleep = sleep
        self._backoff_factory = lambda: Backoff(base=0.05, max_delay=2.0)

    @staticmethod
    def _looks_like_addr(hint: str) -> bool:
        """A NoLeaderError message is only a usable leader hint when it is
        an actual host:port — during elections servers reply with prose
        ('no cluster leader', 'not the leader'), and promoting that into
        the server list would poison every later dial."""
        host, sep, port = hint.rpartition(":")
        return bool(sep) and bool(host) and port.isdigit()

    def _promote(self, addr: str) -> None:
        if addr in self.servers:
            self.servers.remove(addr)
        self.servers.insert(0, addr)

    def _demote(self, addr: str) -> None:
        if addr in self.servers:
            self.servers.remove(addr)
            self.servers.append(addr)

    def _call(self, method: str, body: Any) -> Any:
        last: Optional[Exception] = None
        bo = self._backoff_factory()
        for round_no in range(self.max_rounds):
            if round_no:
                self._sleep(bo.next_delay())
            for addr in list(self.servers):
                try:
                    return self.pool.call(addr, method, body)
                except NoLeaderError as e:
                    # The server answered but isn't leader: re-resolve.
                    # Its reply names the leader when it knows one — aim
                    # the next attempt there rather than round-robining.
                    last = e
                    leader = str(e).strip()
                    if (leader != addr and self._looks_like_addr(leader)):
                        self._promote(leader)
                        break  # restart the scan at the leader
                    self._demote(addr)
                except (RPCError, OSError) as e:
                    last = e
                    self._demote(addr)
        raise RPCError(
            f"no servers reachable after {self.max_rounds} rounds: {last}")

    def node_register(self, node):
        # Bodies carry RAW dataclasses: the frame layer encodes them
        # with the struct codec on negotiated connections and converts
        # to the CamelCase wire trees for legacy msgpack peers.
        reply = self._call("Node.Register", {"Node": node})
        return reply["Index"], reply["HeartbeatTTL"]

    def node_update_status(self, node_id: str, status: str):
        reply = self._call("Node.UpdateStatus",
                           {"NodeID": node_id, "Status": status})
        return reply["Index"], reply["HeartbeatTTL"]

    def node_get_client_allocs(self, node_id: str, min_index: int = 0,
                               max_wait: float = 30.0):
        from ..structs import structs as s
        reply = self._call("Node.GetClientAllocs",
                           {"NodeID": node_id, "MinQueryIndex": min_index,
                            "MaxQueryTime": max_wait})
        allocs = [self._ensure(s.Allocation, a)
                  for a in reply["Allocs"] or []]
        return allocs, reply["Index"]

    def node_update_allocs(self, allocs):
        reply = self._call("Node.UpdateAlloc", {"Allocs": list(allocs)})
        return reply["Index"]

    def node_get(self, node_id: str):
        from ..structs import structs as s
        reply = self._call("Node.Get", {"NodeID": node_id})
        data = reply.get("Node")
        return self._ensure(s.Node, data) if data else None

    def alloc_get(self, alloc_id: str):
        from ..structs import structs as s
        reply = self._call("Alloc.Get", {"AllocID": alloc_id})
        data = reply.get("Alloc")
        return self._ensure(s.Allocation, data) if data else None

    def derive_vault_token(self, alloc_id: str, task_names):
        reply = self._call("Node.DeriveVaultToken",
                           {"AllocID": alloc_id, "Tasks": list(task_names)})
        return reply["Tasks"]
