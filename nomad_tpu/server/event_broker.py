"""Cluster event stream broker (reference: nomad/stream/event_broker.go,
the 1.0 ``/v1/event/stream`` plane).

The state store's WatchSet machinery wakes blocking queries per table and
throws the change away; this broker keeps it: every write-path mutation
publishes a structured, raft-index-stamped :class:`structs.Event` into a
bounded ring, and subscribers consume an ordered feed with topic filters
and ``index=`` resume semantics:

- events arrive in monotonic raft-index order (publishes happen on the
  apply path, which is serialized by the log lock);
- a subscriber that reconnects with ``index=N`` replays every buffered
  event with ``index >= N`` before going live — no gaps while the ring
  still buffers ``N`` (the boundary index may redeliver; consumers key
  on (index, topic, key));
- when ``N`` has already been evicted from the ring the subscribe fails
  with :class:`EventIndexError` carrying the oldest buffered index, so
  the consumer knows to resnapshot instead of silently missing changes.

Cost discipline (the fault.py / tracing.py contract): the broker exists
per server but is **disarmed by default** — nothing is attached to the
state store, so every write pays exactly one attribute load + ``None``
branch.  Arming happens via ``NOMAD_TPU_EVENTS=1`` at server
construction or lazily on the first ``/v1/event/stream`` subscriber
(Server.enable_event_stream).  Ring size: ``NOMAD_TPU_EVENTS_RING``
(default 4096).

Cross-cutting publishers that hold no server handle (the process-wide
breaker, the fault plane, heartbeat expiry) go through the module-level
:func:`note_external` hook, which is one truthiness check while no
broker is armed and stamps events with the server's latest applied
index.  Armed brokers also mirror every event into a process-global
recency ring so the chaos conftest can dump "what happened" next to the
trace timeline on failure (:func:`recent`).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import structs as s
from ..utils import tracing
from ..utils.telemetry import NULL_TELEMETRY

DEFAULT_RING_SIZE = 4096
DEFAULT_MAX_PENDING = 8192
# Process-global forensic tail (chaos conftest dump), independent of any
# one broker's lifetime — servers shut down inside the test body, before
# the failure report hook runs.
RECENT_CAPACITY = 2048


class EventIndexError(Exception):
    """``index=`` resume pointing below the ring's buffered horizon: the
    requested events were already evicted, so a resumed stream would
    have a silent gap.  Carries the oldest index still buffered so the
    consumer can resnapshot and resubscribe."""

    def __init__(self, requested: int, oldest: int):
        self.requested = requested
        self.oldest = oldest
        super().__init__(
            f"requested index {requested} is no longer buffered; "
            f"oldest buffered index is {oldest}")


class Subscription:
    """One consumer's ordered event queue.  Filled by the broker under
    its publish path; drained by the HTTP/CLI stream generator.  A
    consumer that stops draining past ``max_pending`` is closed with a
    lag error rather than wedging publishers or growing unboundedly
    (stream/subscription.go closes slow subscribers the same way)."""

    def __init__(self, broker: "EventBroker",
                 topics: Optional[Dict[str, set]],
                 max_pending: int = DEFAULT_MAX_PENDING):
        self._broker = broker
        # topic -> set of keys ("" / empty set = every key); None = all.
        self.topics = topics
        self.max_pending = max_pending
        self._q: deque = deque()
        self._cond = threading.Condition()
        self.closed = False
        self.close_error: Optional[str] = None

    def matches(self, ev: s.Event) -> bool:
        if self.topics is None:
            return True
        keys = self.topics.get(ev.topic)
        if keys is None:
            keys = self.topics.get("*")
            if keys is None:
                return False
        return not keys or ev.key in keys

    def offer(self, ev: s.Event, replay: bool = False) -> None:
        """``replay=True`` is the subscribe-time ring replay: it bypasses
        the lag shed (the backlog is bounded by the ring size the
        operator chose — shedding a brand-new subscriber for reading the
        buffer it asked for would make resume impossible on large
        rings)."""
        with self._cond:
            if self.closed:
                return
            if not replay and len(self._q) >= self.max_pending:
                self.closed = True
                self.close_error = (
                    f"subscriber lagging: {len(self._q)} undelivered "
                    "events; reconnect with index= to resume")
                self._cond.notify_all()
                return
            self._q.append(ev)
            self._cond.notify_all()

    def next(self, timeout: Optional[float] = None) -> Optional[s.Event]:
        """Next event, or None on timeout / after close once drained."""
        with self._cond:
            if not self._q and not self.closed:
                self._cond.wait(timeout)
            if self._q:
                return self._q.popleft()
            return None

    def pending(self) -> int:
        with self._cond:
            return len(self._q)

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()
        self._broker._remove(self)


class EventBroker:
    """Bounded ring + fan-out.  All ring mutation happens under one lock;
    subscriber queues have their own locks, always acquired after the
    broker's (publish: broker → sub; subscribe replay: broker → sub) so
    ordering is consistent and deadlock-free."""

    def __init__(self, ring_size: Optional[int] = None, metrics=None,
                 index_source: Optional[Callable[[], int]] = None):
        if ring_size is None:
            from ..utils import knobs

            ring_size = knobs.get_int("NOMAD_TPU_EVENTS_RING",
                                      DEFAULT_RING_SIZE)
        self.ring_size = max(8, ring_size)
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        # Applied-index source for externally-originated events (breaker,
        # fault plane, heartbeat expiry) that carry no raft entry.
        self.index_source = index_source
        self._l = threading.Lock()
        self._ring: deque = deque()
        self._subs: List[Subscription] = []
        # Subscriber index (the 10k-filtered-subscriber fan-out fix):
        # publish used to walk EVERY subscription's filter per event
        # under the ring lock — O(K) per write with K alloc-watchers
        # attached.  Bucketing by topic and (topic, key) makes delivery
        # O(matching): an event touches the follow-all list, its topic's
        # every-key list, and its exact (topic, key) list.  Exotic
        # filters ("*" with key sets) fall back to a per-event match in
        # _subs_unindexed.
        self._subs_all: List[Subscription] = []
        self._subs_unindexed: List[Subscription] = []
        self._subs_topic_all: Dict[str, List[Subscription]] = {}
        self._subs_topic_key: Dict[Tuple[str, str],
                                   List[Subscription]] = {}
        # Highest index ever evicted from the ring: a resume at or below
        # it has a gap and must error instead of silently skipping.
        self._evicted_through = 0
        self.published = 0
        self.evicted = 0

    def _index_sub(self, sub: Subscription) -> None:
        if sub.topics is None:
            self._subs_all.append(sub)
        elif "*" in sub.topics:
            self._subs_unindexed.append(sub)
        else:
            for topic, keys in sub.topics.items():
                if not keys:
                    self._subs_topic_all.setdefault(topic, []).append(sub)
                else:
                    for key in keys:
                        self._subs_topic_key.setdefault(
                            (topic, key), []).append(sub)

    def _deindex_sub(self, sub: Subscription) -> None:
        """Mirror of _index_sub.  Emptied buckets are POPPED — churning
        per-alloc watchers mint unique (topic, key) entries, and leaving
        empty lists behind would grow the index without bound."""

        def drop(table, key):
            bucket = table.get(key)
            if bucket is None:
                return
            try:
                bucket.remove(sub)
            except ValueError:
                pass
            if not bucket:
                del table[key]

        if sub.topics is None:
            try:
                self._subs_all.remove(sub)
            except ValueError:
                pass
        elif "*" in sub.topics:
            try:
                self._subs_unindexed.remove(sub)
            except ValueError:
                pass
        else:
            for topic, keys in sub.topics.items():
                if not keys:
                    drop(self._subs_topic_all, topic)
                else:
                    for key in keys:
                        drop(self._subs_topic_key, (topic, key))

    # -- publish -----------------------------------------------------------

    def make_event(self, topic: str, etype: str, key: str, index: int,
                   payload: Optional[Dict] = None,
                   eval_id: str = "") -> s.Event:
        """Build an event, inheriting eval/span correlation from the
        current tracing span when one is active (PR 3 plane)."""
        span_id = 0
        tr = tracing.TRACER
        if tr is not None:
            sp = tr.current()
            if sp is not None:
                span_id = sp.span_id
                if not eval_id:
                    eval_id = sp.attrs.get("eval_id", "") or ""
        return s.Event(topic=topic, type=etype, key=key, index=index,
                       payload=payload or {}, eval_id=eval_id,
                       span_id=span_id, wall=time.time())

    def publish(self, events: List[s.Event], clamp: bool = False) -> None:
        """Append + fan out.  ``clamp=True`` (externally-originated
        events) raises each event's index to the ring tail's if it would
        otherwise step backwards: raft-index-stamped state events are
        serialized by the log lock, but an external stamp read from
        applied_index races with an in-flight apply, and the stream's
        monotonic-order contract must hold for resume dedupe."""
        if not events:
            return
        with self._l:
            ring = self._ring
            for ev in events:
                if clamp and ring and ev.index < ring[-1].index:
                    ev.index = ring[-1].index
                if len(ring) >= self.ring_size:
                    old = ring.popleft()
                    if old.index > self._evicted_through:
                        self._evicted_through = old.index
                    self.evicted += 1
                ring.append(ev)
            self.published += len(events)
            # Fan out while still holding the ring lock: two concurrent
            # publishers (raft apply vs. an external stamp) append in
            # order, but offering outside the lock could deliver those
            # events to a live subscriber inverted, breaking the
            # monotonic-order contract resume dedupe relies on.  offer()
            # is a deque append under the sub's own lock (broker → sub,
            # the documented order).  Delivery walks the subscriber
            # INDEX, not every subscription — O(matching) per event.
            for ev in events:
                for sub in self._subs_all:
                    sub.offer(ev)
                for sub in self._subs_topic_all.get(ev.topic, ()):
                    sub.offer(ev)
                for sub in self._subs_topic_key.get((ev.topic, ev.key),
                                                    ()):
                    sub.offer(ev)
                for sub in self._subs_unindexed:
                    if sub.matches(ev):
                        sub.offer(ev)
        _note_recent(events)

    def publish_one(self, topic: str, etype: str, key: str, index: int,
                    payload: Optional[Dict] = None,
                    eval_id: str = "", clamp: bool = False) -> None:
        self.publish([self.make_event(topic, etype, key, index, payload,
                                      eval_id)], clamp=clamp)

    def publish_external(self, topic: str, etype: str, key: str,
                         payload: Optional[Dict] = None,
                         eval_id: str = "") -> None:
        """An event with no raft entry of its own (breaker transition,
        fault fire, heartbeat expiry): stamped with the latest applied
        index (clamped to the ring tail so the stream stays monotonic —
        the stamp races with in-flight applies)."""
        index = self.index_source() if self.index_source is not None else 0
        self.publish([self.make_event(topic, etype, key, index, payload,
                                      eval_id)], clamp=True)

    # -- subscribe ---------------------------------------------------------

    def subscribe(self, topics: Optional[Dict[str, set]] = None,
                  from_index: int = 0,
                  max_pending: int = DEFAULT_MAX_PENDING,
                  replay_all: bool = False) -> Subscription:
        """New subscription.  ``from_index > 0`` replays every buffered
        event with ``index >= from_index`` (in order, before any live
        event), raising EventIndexError when that range has already
        been partially evicted.  ``replay_all`` replays whatever the
        ring currently holds with no gap check — the backlog-dump mode,
        which must work on a ring that has already evicted (the consumer
        asked for "what you still have", not "everything since N")."""
        sub = Subscription(self, topics, max_pending=max_pending)
        with self._l:
            if replay_all:
                for ev in self._ring:
                    if sub.matches(ev):
                        sub.offer(ev, replay=True)
            elif from_index > 0:
                if from_index <= self._evicted_through:
                    oldest = (self._ring[0].index if self._ring
                              else self._evicted_through + 1)
                    raise EventIndexError(from_index, oldest)
                for ev in self._ring:
                    if ev.index >= from_index and sub.matches(ev):
                        sub.offer(ev, replay=True)
            self._subs.append(sub)
            self._index_sub(sub)
        return sub

    def mark_armed(self, applied_index: int) -> None:
        """Record the raft index already applied when the broker is
        attached to the write path: events at or below it were never
        buffered (lazy arming, server restart), so a resume below that
        horizon must fail the gap check instead of silently replaying
        nothing.  Reuses the eviction horizon — "never buffered" and
        "buffered then evicted" are the same gap to a subscriber."""
        with self._l:
            if applied_index > self._evicted_through:
                self._evicted_through = applied_index

    def _remove(self, sub: Subscription) -> None:
        with self._l:
            try:
                self._subs.remove(sub)
            except ValueError:
                return  # already removed; index buckets were cleaned then
            self._deindex_sub(sub)

    # -- introspection -----------------------------------------------------

    def oldest_buffered_index(self) -> int:
        with self._l:
            return self._ring[0].index if self._ring else 0

    def latest_index(self) -> int:
        with self._l:
            return self._ring[-1].index if self._ring else 0

    def buffered(self, n: Optional[int] = None) -> List[s.Event]:
        with self._l:
            events = list(self._ring)
        return events[-n:] if n else events

    def stats(self) -> Dict[str, int]:
        with self._l:
            subs = list(self._subs)
            depth = len(self._ring)
        lag = max((sub.pending() for sub in subs), default=0)
        return {"depth": depth, "subscribers": len(subs),
                "published": self.published, "evicted": self.evicted,
                "max_subscriber_lag": lag}

    def close(self) -> None:
        with self._l:
            subs = list(self._subs)
            self._subs = []
            self._subs_all = []
            self._subs_unindexed = []
            self._subs_topic_all = {}
            self._subs_topic_key = {}
        for sub in subs:
            with sub._cond:
                sub.closed = True
                sub._cond.notify_all()


# -- process-wide hooks -------------------------------------------------------

# Armed brokers (servers register on enable_event_stream).  The hot
# disarmed path in external publishers is one truthiness check.
_ARMED: List[EventBroker] = []
_ARMED_L = threading.Lock()
# Forensic tail mirrored from every armed broker's publishes; survives
# server shutdown so the chaos failure hook can still dump it.
_RECENT: deque = deque(maxlen=RECENT_CAPACITY)


def register(broker: EventBroker) -> None:
    with _ARMED_L:
        if broker not in _ARMED:
            _ARMED.append(broker)


def unregister(broker: EventBroker) -> None:
    with _ARMED_L:
        try:
            _ARMED.remove(broker)
        except ValueError:
            pass


def armed() -> bool:
    return bool(_ARMED)


def note_external(topic: str, etype: str, key: str,
                  payload: Optional[Dict] = None, eval_id: str = "") -> None:
    """Cross-cutting publish hook for sites with no broker handle (the
    process-wide breaker, the fault plane).  One branch while disarmed."""
    if not _ARMED:
        return
    with _ARMED_L:
        brokers = list(_ARMED)
    for broker in brokers:
        broker.publish_external(topic, etype, key, payload, eval_id)


def _note_recent(events: List[s.Event]) -> None:
    _RECENT.extend(events)


def recent(n: int = 100) -> List[s.Event]:
    """Last ``n`` events published by any armed broker this process
    (oldest first) — the chaos conftest's failure dump."""
    events = list(_RECENT)
    return events[-n:] if n else events


def clear_recent() -> None:
    _RECENT.clear()


def parse_topic_filter(spec: str) -> Optional[Dict[str, set]]:
    """``topic=`` query value → subscription filter.  Comma-separated
    entries, each ``Topic`` (all keys) or ``Topic:key``; ``*`` matches
    every topic.  Empty/absent → all events (None)."""
    spec = (spec or "").strip()
    if not spec or spec == "*":
        return None
    out: Dict[str, set] = {}
    bare: set = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        topic, _, key = part.partition(":")
        if not key:
            # A bare topic wants every key, regardless of any entry
            # that named a specific one.
            bare.add(topic)
            out[topic] = set()
        elif topic not in bare:
            out.setdefault(topic, set()).add(key)
    return out or None
