"""Leader-only periodic job dispatcher (reference: nomad/periodic.go:19-586).

Tracks periodic jobs in a launch-time heap; at fire time derives a child
job ``<id>/periodic-<epoch>`` and submits it through the normal register
path.  The periodic_launch state table provides catch-up after failover
(restored by the leader loop, leader.go:150)."""
from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..structs import structs as s

PERIODIC_LAUNCH_SUFFIX = "/periodic-"


class PeriodicDispatch:
    def __init__(self, dispatch_callback, logger: Optional[logging.Logger] = None):
        """dispatch_callback(parent_job, launch_time) registers the derived
        job + eval and records the launch."""
        self.dispatch = dispatch_callback
        self.logger = logger or logging.getLogger("nomad_tpu.periodic")
        self._l = threading.RLock()
        self._cond = threading.Condition(self._l)
        self._enabled = False
        self.tracked: Dict[str, s.Job] = {}
        # Heap entries carry the tracking generation at push time; a stale
        # generation means the job was re-added/removed since, and the entry
        # is a tombstone — prevents duplicate dispatch chains on job update.
        self._generation: Dict[str, int] = {}
        self._heap: List[Tuple[float, str, int]] = []
        self._thread: Optional[threading.Thread] = None

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            if enabled:
                self._thread = threading.Thread(target=self._run, daemon=True,
                                                name="periodic-dispatch")
                self._thread.start()
            else:
                self.tracked = {}
                self._heap = []
            self._cond.notify_all()

    def add(self, job: s.Job) -> None:
        """(periodic.go:147 Add) — track or update a periodic job."""
        with self._l:
            if not self._enabled:
                return
            if not job.is_periodic():
                self.remove(job.id)
                return
            self.tracked[job.id] = job
            gen = self._generation.get(job.id, 0) + 1
            self._generation[job.id] = gen
            nxt = job.periodic.next(time.time())
            if nxt > 0:
                heapq.heappush(self._heap, (nxt, job.id, gen))
            self._cond.notify_all()

    def remove(self, job_id: str) -> None:
        with self._l:
            self.tracked.pop(job_id, None)
            # Bump the generation so in-flight heap entries tombstone.
            self._generation[job_id] = self._generation.get(job_id, 0) + 1
            self._cond.notify_all()

    def force_run(self, job_id: str) -> Optional[s.Job]:
        """(periodic.go:252 ForceRun)."""
        with self._l:
            job = self.tracked.get(job_id)
        if job is None:
            return None
        return self._dispatch_launch(job, time.time())

    def _run(self) -> None:
        while True:
            with self._l:
                if not self._enabled:
                    return
                now = time.time()
                while self._heap and self._heap[0][0] <= now:
                    launch_time, job_id, gen = heapq.heappop(self._heap)
                    job = self.tracked.get(job_id)
                    if job is None or gen != self._generation.get(job_id):
                        continue  # tombstoned by a re-add/remove
                    # re-arm before dispatch so a slow dispatch can't skip
                    nxt = job.periodic.next(launch_time)
                    if nxt > 0:
                        heapq.heappush(self._heap, (nxt, job_id, gen))
                    self._do_dispatch(job, launch_time)
                wait = 0.5
                if self._heap:
                    wait = min(max(self._heap[0][0] - time.time(), 0.01), 5.0)
                self._cond.wait(wait)

    def _do_dispatch(self, job: s.Job, launch_time: float) -> None:
        try:
            self._dispatch_launch(job, launch_time)
        except Exception:
            self.logger.exception("periodic launch of %s failed", job.id)

    def _dispatch_launch(self, job: s.Job, launch_time: float) -> s.Job:
        derived = derive_job(job, launch_time)
        self.dispatch(job, derived, launch_time)
        return derived

    def tracked_jobs(self) -> List[s.Job]:
        with self._l:
            return list(self.tracked.values())


def derive_job(parent: s.Job, launch_time: float) -> s.Job:
    """Child job named '<id>/periodic-<epoch>' (periodic.go:408
    deriveJob)."""
    child = parent.copy()
    child.id = f"{parent.id}{PERIODIC_LAUNCH_SUFFIX}{int(launch_time)}"
    child.name = child.id
    child.parent_id = parent.id
    child.periodic = None
    child.status = ""
    return child
