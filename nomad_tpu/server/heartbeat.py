"""Leader-side node heartbeat TTL tracking
(reference: nomad/heartbeat.go:15-137).

TTL scales with fleet size: ttl = max(min_heartbeat_ttl,
nodes / max_heartbeats_per_second) + grace (config.go:185-197,264-266).
Expiry transitions the node to down through the log, which fans out
node-update evals via the server hook.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Dict, Optional

from .. import fault
from ..utils import tracing
from ..utils.telemetry import NULL_TELEMETRY

MIN_HEARTBEAT_TTL = 10.0
MAX_HEARTBEATS_PER_SECOND = 50.0
HEARTBEAT_GRACE = 10.0
# Server-assigned TTLs are jittered by up to this fraction so a fleet
# registered in one burst (agent rollout, load-harness client spin-up)
# does not renew in lockstep forever: identical TTLs turn N clients into
# one thundering herd hitting Node.UpdateStatus on the same beat.
HEARTBEAT_TTL_JITTER = 0.1


class HeartbeatTimers:
    # Owning server's event broker (attached by Server.enable_event_stream):
    # expiry events are per-server, not process-wide, so they must not go
    # through the global note_external hook — in multi-server processes
    # that would mirror them onto every stream with the wrong index.
    event_broker = None

    def __init__(
        self,
        on_expire: Callable[[str], None],
        min_ttl: float = MIN_HEARTBEAT_TTL,
        max_per_second: float = MAX_HEARTBEATS_PER_SECOND,
        grace: float = HEARTBEAT_GRACE,
        logger: Optional[logging.Logger] = None,
        metrics=None,
        ttl_jitter: float = HEARTBEAT_TTL_JITTER,
        rng: Optional[random.Random] = None,
    ):
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        self.on_expire = on_expire
        self.min_ttl = min_ttl
        self.max_per_second = max_per_second
        self.grace = grace
        self.ttl_jitter = max(0.0, ttl_jitter)
        self.rng = rng or random.Random()
        self.logger = logger or logging.getLogger("nomad_tpu.heartbeat")
        self._l = threading.Lock()
        # node id → monotonic expiry deadline.  One sweeper thread walks
        # the table instead of one threading.Timer per node: at harness
        # scale a 2500-node fleet meant 2500 live timer THREADS plus two
        # thread creations per renewal, which starved the very renewals
        # the timers were guarding.
        self._timers: Dict[str, float] = {}
        self._enabled = False
        self._sweeper: threading.Thread = None

    def set_enabled(self, enabled: bool) -> None:
        sweeper = None
        with self._l:
            self._enabled = enabled
            if not enabled:
                self._timers = {}
            else:
                # ALWAYS spawn on enable (an is_alive() check races a
                # disable→enable flap against the old sweeper's exit,
                # which would leave expiry permanently dead); the sweeper
                # exits when superseded.
                sweeper = self._sweeper = threading.Thread(
                    target=self._sweep, daemon=True,
                    name="heartbeat-sweeper")
        if sweeper is not None:
            sweeper.start()

    def _sweep(self) -> None:
        """Fire expiries for every deadline that passed.  Granularity
        scales with the configured TTL floor so test-sized TTLs expire
        promptly while production settings wake a few times a second."""
        interval = max(0.01, min(0.25, (self.min_ttl + self.grace) / 20.0))
        me = threading.current_thread()
        while True:
            with self._l:
                if not self._enabled or self._sweeper is not me:
                    return
                now = time.monotonic()
                due = [node_id for node_id, deadline in self._timers.items()
                       if deadline <= now]
            for node_id in due:
                self._invalidate(node_id)
            time.sleep(interval)

    def reset_heartbeat_timer(self, node_id: str) -> float:
        """(heartbeat.go:40 resetHeartbeatTimer) — returns the TTL granted."""
        act = fault.faultpoint("heartbeat.deliver", node_id=node_id)
        if act is not None:
            if act.kind == "drop":
                # Heartbeat blackout: the node's liveness signal is lost
                # before it reaches the timer — the running TTL keeps
                # counting down toward expiry (node → down).
                with self._l:
                    return self.min_ttl
            if act.kind == "delay":
                time.sleep(act.delay)
            elif act.kind in ("error", "crash"):
                act.raise_injected()
        with self._l:
            if not self._enabled:
                return self.min_ttl
            self.metrics.incr_counter("heartbeat.reset")
            ttl = max(self.min_ttl, len(self._timers) / self.max_per_second)
            # Granted TTL is jittered (uniform in [ttl, ttl·(1+jitter)])
            # so renewal arrivals stay dispersed: clients renew relative
            # to the GRANTED ttl, and an un-jittered grant keeps a
            # burst-registered fleet phase-locked indefinitely.  Always
            # upward: the expiry timer below uses the same jittered
            # value, so the liveness guarantee (ttl + grace) is intact.
            if self.ttl_jitter > 0:
                ttl *= 1.0 + self.rng.random() * self.ttl_jitter
            self._timers[node_id] = time.monotonic() + ttl + self.grace
            return ttl

    def _invalidate(self, node_id: str) -> None:
        """(heartbeat.go:86 invalidateHeartbeat)."""
        with self._l:
            deadline = self._timers.get(node_id)
            if deadline is None or deadline > time.monotonic():
                return  # cleared or renewed between sweep and fire
            del self._timers[node_id]
            if not self._enabled:
                return
        self.logger.warning("node %s heartbeat missed; marking down", node_id)
        self.metrics.incr_counter("heartbeat.invalidate")
        tracing.event("heartbeat.expire", node_id=node_id)
        # Event-stream mirror of the expiry (the NodeStatusUpdated the
        # expiry *causes* is published by the state store; this marks the
        # cause itself).  One branch while no broker is armed.
        eb = self.event_broker
        if eb is not None:
            eb.publish_external("Node", "NodeHeartbeatExpired", node_id)
        try:
            self.on_expire(node_id)
        except Exception:
            self.logger.exception("heartbeat invalidation for %s failed", node_id)

    def clear_heartbeat_timer(self, node_id: str) -> None:
        with self._l:
            self._timers.pop(node_id, None)

    def active(self) -> int:
        with self._l:
            return len(self._timers)
