"""Leader-side node heartbeat TTL tracking
(reference: nomad/heartbeat.go:15-137).

TTL scales with fleet size: ttl = max(min_heartbeat_ttl,
nodes / max_heartbeats_per_second) + grace (config.go:185-197,264-266).
Expiry transitions the node to down through the log, which fans out
node-update evals via the server hook.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from .. import fault
from ..utils import tracing
from ..utils.telemetry import NULL_TELEMETRY

MIN_HEARTBEAT_TTL = 10.0
MAX_HEARTBEATS_PER_SECOND = 50.0
HEARTBEAT_GRACE = 10.0


class HeartbeatTimers:
    # Owning server's event broker (attached by Server.enable_event_stream):
    # expiry events are per-server, not process-wide, so they must not go
    # through the global note_external hook — in multi-server processes
    # that would mirror them onto every stream with the wrong index.
    event_broker = None

    def __init__(
        self,
        on_expire: Callable[[str], None],
        min_ttl: float = MIN_HEARTBEAT_TTL,
        max_per_second: float = MAX_HEARTBEATS_PER_SECOND,
        grace: float = HEARTBEAT_GRACE,
        logger: Optional[logging.Logger] = None,
        metrics=None,
    ):
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        self.on_expire = on_expire
        self.min_ttl = min_ttl
        self.max_per_second = max_per_second
        self.grace = grace
        self.logger = logger or logging.getLogger("nomad_tpu.heartbeat")
        self._l = threading.Lock()
        self._timers: Dict[str, threading.Timer] = {}
        self._enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            self._enabled = enabled
            if not enabled:
                for timer in self._timers.values():
                    timer.cancel()
                self._timers = {}

    def reset_heartbeat_timer(self, node_id: str) -> float:
        """(heartbeat.go:40 resetHeartbeatTimer) — returns the TTL granted."""
        act = fault.faultpoint("heartbeat.deliver", node_id=node_id)
        if act is not None:
            if act.kind == "drop":
                # Heartbeat blackout: the node's liveness signal is lost
                # before it reaches the timer — the running TTL keeps
                # counting down toward expiry (node → down).
                with self._l:
                    return self.min_ttl
            if act.kind == "delay":
                time.sleep(act.delay)
            elif act.kind in ("error", "crash"):
                act.raise_injected()
        with self._l:
            if not self._enabled:
                return self.min_ttl
            self.metrics.incr_counter("heartbeat.reset")
            ttl = max(self.min_ttl, len(self._timers) / self.max_per_second)
            existing = self._timers.get(node_id)
            if existing is not None:
                existing.cancel()
            timer = threading.Timer(ttl + self.grace, self._invalidate, args=(node_id,))
            timer.daemon = True
            self._timers[node_id] = timer
            timer.start()
            return ttl

    def _invalidate(self, node_id: str) -> None:
        """(heartbeat.go:86 invalidateHeartbeat)."""
        with self._l:
            self._timers.pop(node_id, None)
            if not self._enabled:
                return
        self.logger.warning("node %s heartbeat missed; marking down", node_id)
        self.metrics.incr_counter("heartbeat.invalidate")
        tracing.event("heartbeat.expire", node_id=node_id)
        # Event-stream mirror of the expiry (the NodeStatusUpdated the
        # expiry *causes* is published by the state store; this marks the
        # cause itself).  One branch while no broker is armed.
        eb = self.event_broker
        if eb is not None:
            eb.publish_external("Node", "NodeHeartbeatExpired", node_id)
        try:
            self.on_expire(node_id)
        except Exception:
            self.logger.exception("heartbeat invalidation for %s failed", node_id)

    def clear_heartbeat_timer(self, node_id: str) -> None:
        with self._l:
            timer = self._timers.pop(node_id, None)
            if timer is not None:
                timer.cancel()

    def active(self) -> int:
        with self._l:
            return len(self._timers)
