"""BlockedEvals: tracks evals that failed placement, keyed by computed node
class, and re-admits them when capacity appears
(reference: nomad/blocked_evals.go:24-480).
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..structs import structs as s
from .eval_broker import EvalBroker

UNBLOCK_BUFFER = 8096


@dataclass
class _Wrapped:
    eval: s.Evaluation
    token: str


class BlockedEvals:
    def __init__(self, eval_broker: EvalBroker):
        self.eval_broker = eval_broker
        self._l = threading.RLock()
        self._enabled = False
        self.captured: Dict[str, _Wrapped] = {}
        self.escaped: Dict[str, _Wrapped] = {}
        self.jobs: Dict[str, str] = {}
        self.unblock_indexes: Dict[str, int] = {}
        self.duplicates: List[s.Evaluation] = []
        self._dup_cond = threading.Condition(self._l)
        self._capacity_q: "queue.Queue[Optional[Tuple[str, int]]]" = queue.Queue(
            maxsize=UNBLOCK_BUFFER)
        self._watcher: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def enabled(self) -> bool:
        with self._l:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            if self._enabled == enabled:
                return
            self._enabled = enabled
            if enabled:
                self._watcher = threading.Thread(
                    target=self._watch_capacity, daemon=True)
                self._watcher.start()
            else:
                self._capacity_q.put(None)  # stop sentinel
        if not enabled:
            self.flush()

    # -- block / unblock ---------------------------------------------------

    def block(self, ev: s.Evaluation) -> None:
        self._process_block(ev, "")

    def reblock(self, ev: s.Evaluation, token: str) -> None:
        self._process_block(ev, token)

    def _process_block(self, ev: s.Evaluation, token: str) -> None:
        with self._l:
            if not self._enabled:
                return
            if ev.job_id in self.jobs:
                # Only one blocked eval per job (blocked_evals.go:160).
                self.duplicates.append(ev)
                self._dup_cond.notify_all()
                return
            if self._missed_unblock(ev):
                # Capacity changed while the eval was in the scheduler; just
                # re-enqueue (blocked_evals.go:175).
                self.eval_broker.enqueue_all([(ev, token)])
                return
            self.jobs[ev.job_id] = ev.id
            wrapped = _Wrapped(ev, token)
            if ev.escaped_computed_class:
                self.escaped[ev.id] = wrapped
            else:
                self.captured[ev.id] = wrapped

    def _missed_unblock(self, ev: s.Evaluation) -> bool:
        """(blocked_evals.go:209)."""
        max_index = 0
        for klass, index in self.unblock_indexes.items():
            max_index = max(max_index, index)
            if klass not in ev.class_eligibility and ev.snapshot_index < index:
                return True
            if ev.class_eligibility.get(klass) and ev.snapshot_index < index:
                return True
        if ev.escaped_computed_class and ev.snapshot_index < max_index:
            return True
        return False

    def block_preempted(self, evals: List[s.Evaluation]) -> None:
        """Track the follow-up evals of preempted jobs (the plan applier
        calls this right after committing a preemption plan).  The
        standard block path applies unchanged: the evals carry no class
        eligibility, so any capacity change re-admits them, and the
        missed-unblock check covers capacity that arrived between the
        plan's raft apply (their snapshot_index) and this registration."""
        for ev in evals:
            self._process_block(ev, "")

    def untrack(self, job_id: str) -> None:
        """Stop tracking after a successful eval (blocked_evals.go:247)."""
        with self._l:
            if not self._enabled:
                return
            eval_id = self.jobs.get(job_id)
            if eval_id is None:
                return
            for table in (self.captured, self.escaped):
                wrapped = table.pop(eval_id, None)
                if wrapped is not None:
                    self.jobs.pop(wrapped.eval.job_id, None)

    def unblock(self, computed_class: str, index: int) -> None:
        """Called from the FSM on node/alloc capacity changes
        (blocked_evals.go:284) — buffered to avoid back-pressuring the log
        apply path."""
        with self._l:
            if not self._enabled:
                return
            self.unblock_indexes[computed_class] = index
        self._capacity_q.put((computed_class, index))

    def _watch_capacity(self) -> None:
        while True:
            update = self._capacity_q.get()
            if update is None:
                return
            self._unblock(*update)

    def _unblock(self, computed_class: str, index: int) -> None:
        with self._l:
            if not self._enabled:
                return
            unblocked: List[Tuple[s.Evaluation, str]] = []

            def admit(wrapped: _Wrapped) -> None:
                # Carry the unblock index on a copy: the stale-snapshot
                # worker pool (worker.py _required_index) must schedule
                # this eval from a snapshot that CONTAINS the capacity
                # change that woke it — a cached view from before the
                # unblock would re-fail the placement and re-block the
                # eval in a wake/re-block spin until the cache rolls.
                ev = wrapped.eval.copy()
                ev.snapshot_index = max(ev.snapshot_index, index)
                unblocked.append((ev, wrapped.token))

            # Escaped evals always unblock — any node could be feasible.
            for eid in list(self.escaped):
                wrapped = self.escaped.pop(eid)
                self.jobs.pop(wrapped.eval.job_id, None)
                admit(wrapped)
            # Captured evals unblock unless explicitly ineligible for this
            # class (unknown classes unblock for correctness).
            for eid in list(self.captured):
                wrapped = self.captured[eid]
                elig = wrapped.eval.class_eligibility.get(computed_class)
                if elig is False:
                    continue
                del self.captured[eid]
                self.jobs.pop(wrapped.eval.job_id, None)
                admit(wrapped)
            if unblocked:
                self.eval_broker.enqueue_all(unblocked)

    def unblock_failed(self) -> None:
        """Periodic retry of evals blocked by max-plan failures
        (blocked_evals.go:372)."""
        with self._l:
            if not self._enabled:
                return
            unblocked: List[Tuple[s.Evaluation, str]] = []
            for table in (self.captured, self.escaped):
                for eid in list(table):
                    wrapped = table[eid]
                    if wrapped.eval.triggered_by == s.EVAL_TRIGGER_MAX_PLANS:
                        del table[eid]
                        self.jobs.pop(wrapped.eval.job_id, None)
                        unblocked.append((wrapped.eval, wrapped.token))
            if unblocked:
                self.eval_broker.enqueue_all(unblocked)

    def get_duplicates(self, timeout: Optional[float]) -> List[s.Evaluation]:
        """Blocking fetch of duplicate blocked evals for cancellation
        (blocked_evals.go:407)."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._l:
            while True:
                if self.duplicates:
                    dups = self.duplicates
                    self.duplicates = []
                    return dups
                remaining = None if deadline is None else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    return []
                self._dup_cond.wait(remaining)

    def flush(self) -> None:
        with self._l:
            self.captured = {}
            self.escaped = {}
            self.jobs = {}
            self.duplicates = []

    def stats(self) -> Dict[str, int]:
        with self._l:
            return {
                "total_blocked": len(self.captured) + len(self.escaped),
                "total_escaped": len(self.escaped),
            }
