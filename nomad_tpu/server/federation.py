"""Federation plane: the global event-stream aggregator (ISSUE 17).

One raft cluster's event broker sees one region.  A federated operator
wants a single tail over all of them — "what is happening on the
planet" — without any cross-region raft (regions stay independent fault
domains).  The aggregator is the deliberately-boring answer: a
poll-based fan-in over each region's existing ``Event.Since`` RPC with
one cursor per region.

Ordering contract: events from ONE region arrive in that region's
raft-index order (the cursor guarantees no gaps and no duplicates, even
across partitions — a dark region simply pauses, and the cursor resumes
exactly where it left off after heal).  Events from DIFFERENT regions
interleave in poll-arrival order; there is no global clock, and
inventing one here would be a lie (each event carries its ``Region``
and region-local ``Index``, so consumers needing a total order per
region still have it).

Partition tolerance: a poll round never hangs on a dark region — each
region gets one bounded RPC, unreachable regions are counted and
skipped, and their cursors stay put so nothing is lost.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..utils import knobs


class RegionEventAggregator:
    """Fan-in tail over every region's event stream.

    ``region_addrs`` maps region name → the RPC address of any server in
    that region (the event ring is replicated per-server state derived
    from raft apply, so any member's tail is the region's tail).  Call
    :meth:`poll` on whatever cadence suits the consumer; each call
    returns the newly-seen events, every one tagged with ``Region``.
    """

    def __init__(self, region_addrs: Dict[str, str], pool=None,
                 max_batch: int = 512, timeout: Optional[float] = None):
        if pool is None:
            from .rpc import ConnPool

            pool = ConnPool()
        self.pool = pool
        self.max_batch = max_batch
        self.timeout = (timeout if timeout is not None else
                        knobs.get_float("NOMAD_TPU_REGION_PROBE_TIMEOUT"))
        self._l = threading.Lock()
        # region -> [addr, cursor_index]
        self._regions: Dict[str, List[Any]] = {
            r: [addr, 0] for r, addr in region_addrs.items()}
        self.polls = 0
        self.events_total = 0
        self.unreachable_total = 0
        self._last_unreachable: List[str] = []

    def add_region(self, region: str, addr: str) -> None:
        with self._l:
            self._regions.setdefault(region, [addr, 0])

    def cursors(self) -> Dict[str, int]:
        with self._l:
            return {r: int(c[1]) for r, c in self._regions.items()}

    def unreachable(self) -> List[str]:
        """Regions that failed their poll in the most recent round."""
        with self._l:
            return list(self._last_unreachable)

    def poll(self) -> List[Dict]:
        """One fan-in round: tail each region past its cursor.  Returns
        the new events (per-region order preserved; regions concatenated
        in sorted-name order within the round).  Never raises on a dark
        region and never hangs — unreachable regions are skipped with
        their cursors intact."""
        out: List[Dict] = []
        dark: List[str] = []
        with self._l:
            snapshot = [(r, c[0], int(c[1]))
                        for r, c in sorted(self._regions.items())]
        for region, addr, cursor in snapshot:
            try:
                reply = self.pool.call(
                    addr, "Event.Since",
                    {"MinIndex": cursor, "Max": self.max_batch},
                    timeout=self.timeout)
            except Exception:
                dark.append(region)
                continue
            events = reply.get("Events") or []
            # Event.Since is EXCLUSIVE (index > cursor) and one raft
            # apply can emit several events at the same index.  If the
            # batch cap landed mid-group, advancing the cursor to the
            # split index would silently drop the group's tail — trim
            # the partial group and pick it up whole next round.
            if len(events) >= self.max_batch:
                last = events[-1]["Index"]
                whole = [ev for ev in events if ev["Index"] < last]
                if whole:
                    events = whole
            for ev in events:
                ev = dict(ev)
                ev["Region"] = region
                out.append(ev)
            if events:
                with self._l:
                    cur = self._regions.get(region)
                    if cur is not None:
                        cur[1] = max(cur[1], events[-1]["Index"])
        with self._l:
            self.polls += 1
            self.events_total += len(out)
            self.unreachable_total += len(dark)
            self._last_unreachable = dark
        return out

    def stats(self) -> Dict:
        with self._l:
            return {"Polls": self.polls,
                    "Events": self.events_total,
                    "Unreachable": self.unreachable_total,
                    "Cursors": {r: int(c[1])
                                for r, c in self._regions.items()},
                    "Dark": list(self._last_unreachable)}
