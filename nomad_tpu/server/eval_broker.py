"""EvalBroker: leader-only priority queue of evaluations with
at-least-once delivery (reference: nomad/eval_broker.go:43-769).

Semantics preserved: per-scheduler-type ready heaps, per-JobID
serialization (jobEvals + blocked), unack map with Nack timers, delivery
limit → failed queue, wait/delay timers, compounding Nack re-enqueue
delay, requeue-on-ack for reblocked evals.

For the TPU build this is also where batching happens: dequeue_batch()
drains up to B ready evals of one scheduler type in one call — preserving
the per-job invariant because ready never holds two evals of one job.

Admission control (control-plane saturation, ROADMAP item 2): the broker
is the choke point between an unbounded client arrival stream and a
bounded scheduling pipeline, so it also owns

- **per-job coalescing** — a job with a queued eval AND a deferred
  duplicate sheds further duplicates (every eval is a full-job
  reconcile, so the kept one covers the shed one's trigger; the shed
  eval is cancelled through the log by the server's shed reaper);
- **a bounded pending queue** — ``max_pending`` caps tracked evals;
  ``check_admission`` raises :class:`BrokerLimitError` (the 429-style
  NACK, carrying ``retry_after``) at the RPC front door BEFORE the eval
  is persisted, so overload backpressures to clients riding the
  utils/backoff jittered-retry plumbing instead of growing the heap;
  priorities at or above ``bypass_priority`` (core GC, node repair)
  are always admitted.
"""
from __future__ import annotations

import heapq
import itertools
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs import structs as s
from ..tenancy.fairness import FairnessState, TenantQueue
from ..utils import tracing
from ..utils.telemetry import NULL_TELEMETRY

FAILED_QUEUE = "_failed"

#: Cap on per-tenant rows surfaced by extended_stats(): the stats
#: endpoint must stay O(1)-ish at 1k+ tenants, so only the busiest
#: rows ship and the rest are counted as elided.
STATS_MAX_TENANTS = 256


class EvalBrokerError(Exception):
    pass


class BrokerLimitError(EvalBrokerError):
    """Admission NACK: the pending-eval queue is at capacity.  Carries
    ``retry_after`` (seconds) so clients back off instead of hammering;
    the HTTP layer maps this to 429 + Retry-After, the RPC layer
    re-types it from the wire error string."""

    def __init__(self, retry_after: float, pending: int, limit: int,
                 namespace: str = ""):
        self.retry_after = retry_after
        self.pending = pending
        self.limit = limit
        self.namespace = namespace
        what = (f"tenant {namespace!r} at quota" if namespace
                else "eval broker at capacity")
        super().__init__(
            f"{what} ({pending}/{limit} pending); "
            f"retry_after={retry_after:.2f}")

    @staticmethod
    def from_message(msg: str) -> "BrokerLimitError":
        """Rebuild from the wire error string (rpc.py encodes errors as
        '<TypeName>: <message>')."""
        import re

        m = re.search(r"retry_after=([0-9.]+)", msg)
        retry = float(m.group(1)) if m else 1.0
        m = re.search(r"\((\d+)/(\d+) pending\)", msg)
        pending, limit = (int(m.group(1)), int(m.group(2))) if m else (0, 0)
        m = re.search(r"tenant '([^']*)' at quota", msg)
        ns = m.group(1) if m else ""
        return BrokerLimitError(retry, pending, limit, namespace=ns)


ERR_NOT_OUTSTANDING = "evaluation is not outstanding"
ERR_TOKEN_MISMATCH = "evaluation token does not match"
ERR_NACK_TIMEOUT_REACHED = "evaluation nack timeout reached"


@dataclass(order=True)
class _HeapEntry:
    # min-heap: higher priority first, then older create index, then seq.
    sort_key: Tuple[int, int, int]
    eval: s.Evaluation = field(compare=False)


class _Unack:
    """One outstanding delivery.  ``deadline`` (monotonic) replaces the
    reference's per-eval time.AfterFunc: a Python threading.Timer is a
    whole OS thread per dequeue, which the load harness measured as a
    material per-eval cost at saturation — one sweeper thread walks the
    deadlines instead."""

    __slots__ = ("eval", "token", "deadline", "fired", "paused")

    def __init__(self, ev: s.Evaluation, token: str,
                 deadline: Optional[float]):
        self.eval = ev
        self.token = token
        self.deadline = deadline
        self.fired = False
        self.paused = False


class EvalBroker:
    # Owning server's event broker, attached by Server.enable_event_stream.
    # The broker is per-server (unlike the process-wide breaker/fault
    # plane), so ack/nack events must not fan out through the global
    # note_external hook: in multi-server processes that would mirror
    # every server's evals onto every stream, stamped with the wrong
    # applied index.  Disarmed cost: one attribute load + branch.
    event_broker = None

    def __init__(
        self,
        nack_timeout: float = 60.0,
        initial_nack_delay: float = 1.0,
        subsequent_nack_delay: float = 20.0,
        delivery_limit: int = 3,
        metrics=None,
        max_pending: int = 0,
        coalesce: bool = True,
        bypass_priority: int = s.JOB_MAX_PRIORITY,
    ):
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        if nack_timeout < 0:
            raise ValueError("timeout cannot be negative")
        self.nack_timeout = nack_timeout
        self.initial_nack_delay = initial_nack_delay
        self.subsequent_nack_delay = subsequent_nack_delay
        self.delivery_limit = delivery_limit
        # Admission control: 0 = unbounded (the historical behavior).
        self.max_pending = max_pending
        self.coalesce = coalesce
        self.bypass_priority = bypass_priority

        self._l = threading.RLock()
        self._cond = threading.Condition(self._l)
        self._enabled = False
        self._seq = itertools.count()

        self.evals: Dict[str, int] = {}            # id → delivery attempts
        self.job_evals: Dict[str, str] = {}        # job id → queued eval id
        self.blocked: Dict[str, List[_HeapEntry]] = {}
        self.ready: Dict[str, TenantQueue] = {}
        self.unack: Dict[str, _Unack] = {}
        self.requeue: Dict[str, s.Evaluation] = {}  # token → eval
        self.time_wait: Dict[str, threading.Timer] = {}

        # Tenancy plane: shared fairness state (policy/usage/virtual
        # time) for every TenantQueue above, plus per-tenant pending /
        # shed / reject accounting for quota admission and the stats
        # surface.  All mutated under self._l.
        self.fairness = FairnessState()
        self._ns_pending: Dict[str, int] = {}
        self._ns_shed: Dict[str, int] = {}
        self._ns_rejects: Dict[str, int] = {}

        # Saturation counters + the shed hand-off (evals coalesced away;
        # the server's shed reaper cancels them through the log — the
        # broker cannot raft.apply itself without inverting the
        # raft-lock → broker-lock order the FSM enqueue hook takes).
        self.shed_total = 0
        self.coalesced_total = 0
        self.admission_rejects = 0
        self._shed: List[s.Evaluation] = []
        self._shed_cond = threading.Condition(self._l)
        self._sweeper: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def enabled(self) -> bool:
        with self._l:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        sweeper = None
        with self._l:
            self._enabled = enabled
            if enabled and self.nack_timeout > 0:
                # ALWAYS spawn on enable: an is_alive() check races a
                # disable→enable flap (the old sweeper observed the
                # disable and is mid-exit but still alive, so no new one
                # would start and nack redelivery would go dead).  The
                # sweeper exits when superseded, so a flap costs at most
                # one short-lived extra thread.
                sweeper = self._sweeper = threading.Thread(
                    target=self._sweep_nack_timeouts, daemon=True,
                    name="broker-nack-sweeper")
        if sweeper is not None:
            sweeper.start()
        if not enabled:
            self.flush()

    def _sweep_nack_timeouts(self) -> None:
        """The single owner of every unack deadline: scan, mark fired,
        nack outside the lock.  Granularity scales with the timeout so
        test-sized timeouts still fire promptly while the production
        60s default costs four wakeups a second at most."""
        interval = max(0.005, min(0.25, self.nack_timeout / 5.0))
        me = threading.current_thread()
        while True:
            with self._l:
                if not self._enabled or self._sweeper is not me:
                    return
                now = time.monotonic()
                due = []
                for eid, unack in self.unack.items():
                    if (not unack.paused and not unack.fired
                            and unack.deadline is not None
                            and unack.deadline <= now):
                        unack.fired = True
                        due.append((eid, unack.token))
            for eid, token in due:
                try:
                    self.nack(eid, token)
                except EvalBrokerError:
                    pass
            time.sleep(interval)

    # -- enqueue -----------------------------------------------------------

    def enqueue(self, ev: s.Evaluation) -> None:
        with self._l:
            self._process_enqueue(ev, "")

    def enqueue_all(self, evals: Dict[str, Tuple[s.Evaluation, str]] | List) -> None:
        """Enqueue many evals; each may carry a token from a reblock
        (eval_broker.go:169 EnqueueAll)."""
        with self._l:
            if isinstance(evals, dict):
                items = list(evals.values())
            else:
                items = [(e, "") if not isinstance(e, tuple) else e for e in evals]
            for ev, token in items:
                self._process_enqueue(ev, token)

    def _process_enqueue(self, ev: s.Evaluation, token: str) -> None:
        if ev.id in self.evals:
            if token == "":
                return
            # Reblock from the owning scheduler: requeue once acked.
            unack = self.unack.get(ev.id)
            if unack is not None and unack.token == token:
                self.requeue[token] = ev
            return
        elif self._enabled:
            self.evals[ev.id] = 0
            ns = ev.namespace or "default"
            self._ns_pending[ns] = self._ns_pending.get(ns, 0) + 1
            # The shared choke point — instrumented here, after the
            # dedup check and only while enabled, so every actual
            # admission (enqueue, enqueue_all via blocked-eval unblock,
            # post-ack requeue) records exactly one broker.enqueue;
            # duplicates and drops by a disabled broker record none.
            tr = tracing.TRACER
            if tr is not None:
                tr.event("broker.enqueue", eval_id=ev.id, job_id=ev.job_id,
                         eval_type=ev.type, priority=ev.priority)
            self.metrics.incr_counter("broker.enqueue")

        if ev.wait > 0:
            self._process_waiting_enqueue(ev)
            return
        self._enqueue_locked(ev, ev.type)

    def _process_waiting_enqueue(self, ev: s.Evaluation) -> None:
        timer = threading.Timer(ev.wait, self._enqueue_waiting, args=(ev,))
        timer.daemon = True
        self.time_wait[ev.id] = timer
        timer.start()

    def _enqueue_waiting(self, ev: s.Evaluation) -> None:
        with self._l:
            self.time_wait.pop(ev.id, None)
            self._enqueue_locked(ev, ev.type)

    def _enqueue_locked(self, ev: s.Evaluation, queue: str) -> None:
        if not self._enabled:
            return
        pending_eval = self.job_evals.get(ev.job_id, "")
        if not pending_eval:
            self.job_evals[ev.job_id] = ev.id
        elif pending_eval != ev.id:
            if self.coalesce and self._coalesce_deferred(ev):
                return
            heapq.heappush(self.blocked.setdefault(ev.job_id, []),
                           self._entry(ev))
            return

        q = self.ready.get(queue)
        if q is None:
            q = self.ready[queue] = TenantQueue(self.fairness)
        q.push(self._entry(ev))
        self._cond.notify_all()

    def _coalesce_deferred(self, ev: s.Evaluation) -> bool:
        """Per-job dedup of DEFERRED duplicates (the job already has a
        queued eval; ``ev`` would be the second-or-later in line).  Every
        eval is a full-job reconcile, so one deferred eval whose
        TRIGGER index (Evaluation.trigger_index — what the stale-snapshot
        worker fence schedules against) covers both subsumes the other —
        keep the higher-priority one, shed the loser for the reaper to
        cancel.  Coalescing is skipped when the would-be keeper's
        trigger index is LOWER than the loser's: the worker may schedule
        the keeper from a snapshot that predates the shed trigger (a
        node death, an unblock index) and the trigger would be lost.
        Returns True when ``ev`` was absorbed (caller must not enqueue
        it)."""
        deferred = self.blocked.get(ev.job_id)
        if not deferred:
            return False
        if len(deferred) > 1:  # legacy pile-up (coalesce toggled on late)
            return False
        other = deferred[0].eval
        keeper, loser = ((other, ev)
                         if (other.priority, other.trigger_index())
                         >= (ev.priority, ev.trigger_index())
                         else (ev, other))
        if keeper.trigger_index() < loser.trigger_index():
            return False
        if keeper is ev:
            deferred[0] = self._entry(ev)
        self._shed_locked(loser)
        self.coalesced_total += 1
        self.metrics.incr_counter("broker.coalesce")
        tr = tracing.TRACER
        if tr is not None:
            tr.event("broker.coalesce", eval_id=loser.id,
                     job_id=loser.job_id, kept_eval=keeper.id)
        return True  # ev was either shed or installed as the deferred slot

    def _shed_locked(self, ev: s.Evaluation) -> None:
        if self.evals.pop(ev.id, None) is not None:
            self._ns_pending_dec(ev.namespace or "default")
        ns = ev.namespace or "default"
        self._ns_shed[ns] = self._ns_shed.get(ns, 0) + 1
        self.shed_total += 1
        self.metrics.incr_counter("broker.shed")
        self._shed.append(ev)
        self._shed_cond.notify_all()

    def get_shed(self, timeout: Optional[float]) -> List[s.Evaluation]:
        """Blocking drain of coalesced-away evals (the server's shed
        reaper cancels them through the log, mirroring
        BlockedEvals.get_duplicates)."""
        with self._l:
            if not self._shed:
                self._shed_cond.wait(timeout)
            out, self._shed = self._shed, []
            return out

    # -- admission ---------------------------------------------------------

    def pending_count(self) -> int:
        with self._l:
            return len(self.evals)

    def ns_pending_count(self, namespace: str) -> int:
        with self._l:
            return self._ns_pending.get(namespace or "default", 0)

    def _ns_pending_dec(self, ns: str) -> None:
        """Caller holds the lock."""
        left = self._ns_pending.get(ns, 0) - 1
        if left > 0:
            self._ns_pending[ns] = left
        else:
            self._ns_pending.pop(ns, None)

    def check_admission(self, priority: int = 0, namespace: str = "",
                        ns_max_pending: int = 0) -> None:
        """Front-door admission check, called by the RPC surface BEFORE
        the eval-creating raft apply.  Raises BrokerLimitError when the
        broker tracks ``max_pending`` or more evals — or, when the
        caller resolved a per-tenant pending-eval quota
        (``ns_max_pending`` > 0), when ``namespace`` alone has that many
        pending — unless ``priority`` is at or above ``bypass_priority``
        (repair/GC traffic must not starve behind user submissions).
        Estimated retry_after grows with the overload ratio; callers
        add jitter via utils/backoff."""
        if self.max_pending <= 0 and ns_max_pending <= 0:
            return
        ns = namespace or "default"
        with self._l:
            if not self._enabled:
                return
            if priority >= self.bypass_priority:
                return
            ns_pending = self._ns_pending.get(ns, 0)
            if ns_max_pending > 0 and ns_pending >= ns_max_pending:
                self.admission_rejects += 1
                self._ns_rejects[ns] = self._ns_rejects.get(ns, 0) + 1
                self.metrics.incr_counter("broker.admission_reject")
                tr = tracing.TRACER
                if tr is not None:
                    tr.event("broker.admission_reject", namespace=ns,
                             pending=ns_pending, limit=ns_max_pending)
                retry_after = min(
                    5.0, 0.2 + 0.3 * (ns_pending / ns_max_pending))
                raise BrokerLimitError(retry_after, ns_pending,
                                       ns_max_pending, namespace=ns)
            pending = len(self.evals)
            if self.max_pending <= 0 or pending < self.max_pending:
                return
            self.admission_rejects += 1
            self._ns_rejects[ns] = self._ns_rejects.get(ns, 0) + 1
        self.metrics.incr_counter("broker.admission_reject")
        tr = tracing.TRACER
        if tr is not None:
            tr.event("broker.admission_reject", pending=pending,
                     limit=self.max_pending)
        retry_after = min(5.0, 0.2 + 0.3 * (pending / self.max_pending))
        raise BrokerLimitError(retry_after, pending, self.max_pending)

    def note_quota_reject(self, namespace: str) -> None:
        """Record an admission rejection decided OUTSIDE the broker
        (the server's alloc-quota ledger) so the per-tenant reject
        counters and metrics tell one story."""
        ns = namespace or "default"
        with self._l:
            self.admission_rejects += 1
            self._ns_rejects[ns] = self._ns_rejects.get(ns, 0) + 1
        self.metrics.incr_counter("broker.admission_reject")
        tr = tracing.TRACER
        if tr is not None:
            tr.event("broker.quota_reject", namespace=ns)

    # -- tenancy wiring ----------------------------------------------------

    def set_namespace_policy(self, name: str, weight: float,
                             objective: str) -> None:
        """Install/refresh a tenant's fairness policy (server-side, on
        namespace upsert) and rescore its queued entries."""
        with self._l:
            self.fairness.set_policy(name, weight, objective)
            for q in self.ready.values():
                q.note_usage_changed((name,))

    def drop_namespace_policy(self, name: str) -> None:
        with self._l:
            self.fairness.drop_policy(name)

    def set_objective(self, objective: str) -> None:
        """Cluster-wide default fairness objective (the
        NOMAD_TPU_TENANCY_OBJECTIVE knob)."""
        with self._l:
            self.fairness.objective = objective

    def set_cluster_capacity(self, cap: Tuple[int, int, int, int]) -> None:
        with self._l:
            self.fairness.set_capacity(cap)

    def note_usage_changed(self, usage: Dict[str, Tuple]) -> None:
        """Fold the state store's dirty per-tenant usage rows into the
        fairness scorer — O(changed tenants), the PR 9 usage-fold feed,
        never a scan of all tenants."""
        if not usage:
            return
        with self._l:
            for ns, vec in usage.items():
                self.fairness.set_usage(ns, vec)
            for q in self.ready.values():
                q.note_usage_changed(usage)

    def _entry(self, ev: s.Evaluation) -> _HeapEntry:
        return _HeapEntry((-ev.priority, ev.create_index, next(self._seq)), ev)

    # -- dequeue -----------------------------------------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[s.Evaluation], str]:
        """Blocking dequeue of the highest-priority ready eval
        (eval_broker.go:279)."""
        import time as _time

        deadline = None if timeout is None or timeout == 0 else _time.monotonic() + timeout
        with self._l:
            while True:
                ev, token = self._scan(schedulers)
                if ev is not None:
                    return ev, token
                if timeout == 0:
                    return None, ""
                remaining = None if deadline is None else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None, ""
                self._cond.wait(remaining if remaining is not None else 1.0)

    def dequeue_batch(
        self, schedulers: List[str], max_batch: int, timeout: Optional[float] = None
    ) -> List[Tuple[s.Evaluation, str]]:
        """Drain up to max_batch ready evals in one call — the batch
        assembler feeding the TPU kernel (SURVEY.md §2.9)."""
        out: List[Tuple[s.Evaluation, str]] = []
        ev, token = self.dequeue(schedulers, timeout)
        if ev is None:
            return out
        out.append((ev, token))
        with self._l:
            while len(out) < max_batch:
                ev, token = self._scan(schedulers)
                if ev is None:
                    break
                out.append((ev, token))
        return out

    def _scan(self, schedulers: List[str]) -> Tuple[Optional[s.Evaluation], str]:
        if not self._enabled:
            raise EvalBrokerError("eval broker disabled")
        eligible: List[str] = []
        eligible_priority = 0
        for sched in schedulers:
            heap = self.ready.get(sched)
            if not heap:
                continue
            priority = heap.peek_priority()
            if not eligible or priority > eligible_priority:
                eligible = [sched]
                eligible_priority = priority
            elif priority == eligible_priority:
                eligible.append(sched)
        if not eligible:
            return None, ""
        sched = eligible[0] if len(eligible) == 1 else random.choice(eligible)
        return self._dequeue_for_sched(sched)

    def _dequeue_for_sched(self, sched: str) -> Tuple[s.Evaluation, str]:
        ev = self.ready[sched].pop().eval
        token = s.generate_uuid()

        deadline = (time.monotonic() + self.nack_timeout
                    if self.nack_timeout > 0 else None)
        self.unack[ev.id] = _Unack(ev, token, deadline)
        self.evals[ev.id] = self.evals.get(ev.id, 0) + 1
        tr = tracing.TRACER
        if tr is not None:
            tr.event("broker.dequeue", eval_id=ev.id, job_id=ev.job_id,
                     eval_type=ev.type, attempt=self.evals[ev.id])
        self.metrics.incr_counter("broker.dequeue")
        return ev, token

    # -- outstanding / ack / nack -----------------------------------------

    def delivery_attempts(self, eval_id: str) -> int:
        """How many times this eval has been dequeued (the delivery-limit
        counter); 0 for evals the broker isn't tracking."""
        with self._l:
            return self.evals.get(eval_id, 0)

    def outstanding(self, eval_id: str) -> Tuple[str, bool]:
        with self._l:
            unack = self.unack.get(eval_id)
            if unack is None:
                return "", False
            return unack.token, True

    def outstanding_reset(self, eval_id: str, token: str) -> None:
        with self._l:
            unack = self._get_unack(eval_id, token)
            if unack.fired:
                raise EvalBrokerError(ERR_NACK_TIMEOUT_REACHED)
            if unack.deadline is not None:
                unack.deadline = time.monotonic() + self.nack_timeout

    def _get_unack(self, eval_id: str, token: str) -> _Unack:
        unack = self.unack.get(eval_id)
        if unack is None:
            raise EvalBrokerError(ERR_NOT_OUTSTANDING)
        if unack.token != token:
            raise EvalBrokerError(ERR_TOKEN_MISMATCH)
        return unack

    def ack(self, eval_id: str, token: str) -> None:
        """(eval_broker.go:481): release the job serialization slot, promote
        a blocked same-job eval, and process any requeue."""
        with self._l:
            try:
                unack = self._get_unack(eval_id, token)
                if unack.fired:
                    raise EvalBrokerError("Evaluation ID Ack'd after Nack timer expiration")
                job_id = unack.eval.job_id
                tr = tracing.TRACER
                if tr is not None:
                    tr.event("broker.ack", eval_id=eval_id, job_id=job_id,
                             attempts=self.evals.get(eval_id, 0))
                    # Close the submit→scheduled umbrella (eval.e2e):
                    # the ack is the moment the eval's plan has applied
                    # and the client-visible work is done.
                    tr.close_mark(eval_id, job_id=job_id,
                                  outcome="acked",
                                  attempts=self.evals.get(eval_id, 0))
                self.metrics.incr_counter("broker.ack")
                eb = self.event_broker
                if eb is not None:
                    eb.publish_external(
                        "Eval", "EvalAcked", eval_id,
                        {"JobID": job_id,
                         "Attempts": self.evals.get(eval_id, 0)},
                        eval_id=eval_id)

                del self.unack[eval_id]
                if self.evals.pop(eval_id, None) is not None:
                    self._ns_pending_dec(unack.eval.namespace or "default")
                self.job_evals.pop(job_id, None)

                blocked = self.blocked.get(job_id)
                if blocked:
                    ev = heapq.heappop(blocked).eval
                    if not blocked:
                        del self.blocked[job_id]
                    self._enqueue_locked(ev, ev.type)

                requeued = self.requeue.pop(token, None)
                if requeued is not None:
                    self._process_enqueue(requeued, "")
            finally:
                self.requeue.pop(token, None)

    def nack(self, eval_id: str, token: str) -> None:
        """(eval_broker.go:540): redeliver with compounding delay, or shunt
        to the failed queue at the delivery limit."""
        with self._l:
            self.requeue.pop(token, None)
            unack = self._get_unack(eval_id, token)
            del self.unack[eval_id]

            dequeues = self.evals.get(eval_id, 0)
            if dequeues >= self.delivery_limit:
                outcome, wait = "failed", 0.0
                self._enqueue_locked(unack.eval, FAILED_QUEUE)
            else:
                ev = unack.eval
                ev.wait = self._nack_reenqueue_delay(dequeues)
                outcome, wait = "requeue", ev.wait
                if ev.wait > 0:
                    self._process_waiting_enqueue(ev)
                else:
                    self._enqueue_locked(ev, ev.type)
            tr = tracing.TRACER
            if tr is not None:
                tr.event("broker.nack", eval_id=eval_id,
                         job_id=unack.eval.job_id, attempts=dequeues,
                         outcome=outcome, wait=wait)
                if outcome == "failed":
                    # Terminal nack: the umbrella closes with the burn
                    # recorded — a redelivery would reopen nothing.
                    tr.close_mark(eval_id, job_id=unack.eval.job_id,
                                  outcome="failed", attempts=dequeues)
            self.metrics.incr_counter("broker.nack")
            eb = self.event_broker
            if eb is not None:
                eb.publish_external(
                    "Eval", "EvalNacked", eval_id,
                    {"JobID": unack.eval.job_id, "Attempts": dequeues,
                     "Outcome": outcome}, eval_id=eval_id)

    def _nack_reenqueue_delay(self, prev_dequeues: int) -> float:
        if prev_dequeues <= 0:
            return 0.0
        if prev_dequeues == 1:
            return self.initial_nack_delay
        return (prev_dequeues - 1) * self.subsequent_nack_delay

    def pause_nack_timeout(self, eval_id: str, token: str) -> None:
        with self._l:
            unack = self._get_unack(eval_id, token)
            if unack.fired:
                raise EvalBrokerError(ERR_NACK_TIMEOUT_REACHED)
            unack.paused = True

    def resume_nack_timeout(self, eval_id: str, token: str) -> None:
        with self._l:
            unack = self._get_unack(eval_id, token)
            unack.paused = False
            if self.nack_timeout > 0:
                unack.deadline = time.monotonic() + self.nack_timeout

    # -- maintenance -------------------------------------------------------

    def flush(self) -> None:
        with self._l:
            # Unack deadlines die with the map (the sweeper re-reads it
            # under the lock); only the wait timers are real threads.
            for timer in self.time_wait.values():
                timer.cancel()
            self.evals = {}
            self.job_evals = {}
            self.blocked = {}
            self.ready = {}
            self.unack = {}
            self.requeue = {}
            self.time_wait = {}
            # Pending mirrors die with the queues; shed/reject/dequeue
            # counters are lifetime totals and survive the flush.
            self._ns_pending = {}
            # Shed evals not yet reaped die with the leadership that shed
            # them — the next leader's restore pass re-evaluates.
            self._shed = []
            self._cond.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._l:
            return {
                "total_ready": sum(len(h) for h in self.ready.values()),
                "total_unacked": len(self.unack),
                "total_blocked": sum(len(h) for h in self.blocked.values()),
                "total_waiting": len(self.time_wait),
                "by_scheduler": {k: len(h) for k, h in self.ready.items()},
            }

    def extended_stats(self) -> Dict:
        """The /v1/broker/stats saturation surface: pending by state and
        priority, the delivery-attempts histogram, and the admission /
        coalesce / shed counters — what the load harness reads and what
        an operator needs to tell "busy" from "melting"."""
        with self._l:
            failed = len(self.ready.get(FAILED_QUEUE, ()))
            by_state = {
                "ready": sum(len(h) for k, h in self.ready.items()
                             if k != FAILED_QUEUE),
                "unacked": len(self.unack),
                "deferred": sum(len(h) for h in self.blocked.values()),
                "waiting": len(self.time_wait),
                "failed": failed,
            }
            by_priority: Dict[int, int] = {}
            for heaps in (self.ready.values(), self.blocked.values()):
                for heap in heaps:
                    for entry in heap:
                        prio = entry.eval.priority
                        by_priority[prio] = by_priority.get(prio, 0) + 1
            attempts_hist: Dict[int, int] = {}
            for attempts in self.evals.values():
                attempts_hist[attempts] = attempts_hist.get(attempts, 0) + 1
            tenants, elided = self._tenant_stats_locked()
            return {
                "Enabled": self._enabled,
                "Pending": len(self.evals),
                "MaxPending": self.max_pending,
                "Coalesce": self.coalesce,
                "BypassPriority": self.bypass_priority,
                "ByState": by_state,
                "ByPriority": {str(k): v
                               for k, v in sorted(by_priority.items())},
                "DeliveryAttempts": {str(k): v for k, v
                                     in sorted(attempts_hist.items())},
                "ShedTotal": self.shed_total,
                "CoalescedTotal": self.coalesced_total,
                "AdmissionRejects": self.admission_rejects,
                "ShedUnreaped": len(self._shed),
                "Objective": self.fairness.objective,
                "Tenants": tenants,
                "TenantsElided": elided,
            }

    def _tenant_stats_locked(self) -> Tuple[Dict[str, Dict], int]:
        """Per-tenant broker breakdown, busiest (most pending) rows
        first, capped at STATS_MAX_TENANTS so the endpoint stays cheap
        at 1k+ tenants.  Caller holds the lock."""
        fs = self.fairness
        names = set(self._ns_pending)
        names.update(fs.dequeued)
        names.update(self._ns_shed)
        names.update(self._ns_rejects)
        ranked = sorted(names,
                        key=lambda n: (-self._ns_pending.get(n, 0), n))
        elided = max(0, len(ranked) - STATS_MAX_TENANTS)
        tenants: Dict[str, Dict] = {}
        for ns in ranked[:STATS_MAX_TENANTS]:
            tenants[ns] = {
                "Pending": self._ns_pending.get(ns, 0),
                "Dequeued": fs.dequeued.get(ns, 0),
                "Shed": self._ns_shed.get(ns, 0),
                "Rejects": self._ns_rejects.get(ns, 0),
                "Weight": fs.weight(ns),
                "DominantShare": round(fs.dominant_share(ns), 6),
                "VirtualTime": round(fs.vt.get(ns, 0.0), 6),
            }
        return tenants, elided

    def tenant_counters(self) -> Dict[str, Tuple[int, int, int, int]]:
        """(pending, dequeued, shed, rejects) per tenant — the metrics
        loop's cheap snapshot (no score computation)."""
        with self._l:
            fs = self.fairness
            names = set(self._ns_pending)
            names.update(fs.dequeued)
            names.update(self._ns_rejects)
            return {ns: (self._ns_pending.get(ns, 0),
                         fs.dequeued.get(ns, 0),
                         self._ns_shed.get(ns, 0),
                         self._ns_rejects.get(ns, 0))
                    for ns in names}
