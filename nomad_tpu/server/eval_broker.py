"""EvalBroker: leader-only priority queue of evaluations with
at-least-once delivery (reference: nomad/eval_broker.go:43-769).

Semantics preserved: per-scheduler-type ready heaps, per-JobID
serialization (jobEvals + blocked), unack map with Nack timers, delivery
limit → failed queue, wait/delay timers, compounding Nack re-enqueue
delay, requeue-on-ack for reblocked evals.

For the TPU build this is also where batching happens: dequeue_batch()
drains up to B ready evals of one scheduler type in one call — preserving
the per-job invariant because ready never holds two evals of one job.
"""
from __future__ import annotations

import heapq
import itertools
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs import structs as s
from ..utils import tracing
from ..utils.telemetry import NULL_TELEMETRY

FAILED_QUEUE = "_failed"


class EvalBrokerError(Exception):
    pass


ERR_NOT_OUTSTANDING = "evaluation is not outstanding"
ERR_TOKEN_MISMATCH = "evaluation token does not match"
ERR_NACK_TIMEOUT_REACHED = "evaluation nack timeout reached"


@dataclass(order=True)
class _HeapEntry:
    # min-heap: higher priority first, then older create index, then seq.
    sort_key: Tuple[int, int, int]
    eval: s.Evaluation = field(compare=False)


class _Unack:
    __slots__ = ("eval", "token", "timer", "fired", "paused")

    def __init__(self, ev: s.Evaluation, token: str, timer: Optional[threading.Timer]):
        self.eval = ev
        self.token = token
        self.timer = timer
        self.fired = False
        self.paused = False


class EvalBroker:
    # Owning server's event broker, attached by Server.enable_event_stream.
    # The broker is per-server (unlike the process-wide breaker/fault
    # plane), so ack/nack events must not fan out through the global
    # note_external hook: in multi-server processes that would mirror
    # every server's evals onto every stream, stamped with the wrong
    # applied index.  Disarmed cost: one attribute load + branch.
    event_broker = None

    def __init__(
        self,
        nack_timeout: float = 60.0,
        initial_nack_delay: float = 1.0,
        subsequent_nack_delay: float = 20.0,
        delivery_limit: int = 3,
        metrics=None,
    ):
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        if nack_timeout < 0:
            raise ValueError("timeout cannot be negative")
        self.nack_timeout = nack_timeout
        self.initial_nack_delay = initial_nack_delay
        self.subsequent_nack_delay = subsequent_nack_delay
        self.delivery_limit = delivery_limit

        self._l = threading.RLock()
        self._cond = threading.Condition(self._l)
        self._enabled = False
        self._seq = itertools.count()

        self.evals: Dict[str, int] = {}            # id → delivery attempts
        self.job_evals: Dict[str, str] = {}        # job id → queued eval id
        self.blocked: Dict[str, List[_HeapEntry]] = {}
        self.ready: Dict[str, List[_HeapEntry]] = {}
        self.unack: Dict[str, _Unack] = {}
        self.requeue: Dict[str, s.Evaluation] = {}  # token → eval
        self.time_wait: Dict[str, threading.Timer] = {}

    # -- lifecycle ---------------------------------------------------------

    def enabled(self) -> bool:
        with self._l:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            self._enabled = enabled
        if not enabled:
            self.flush()

    # -- enqueue -----------------------------------------------------------

    def enqueue(self, ev: s.Evaluation) -> None:
        with self._l:
            self._process_enqueue(ev, "")

    def enqueue_all(self, evals: Dict[str, Tuple[s.Evaluation, str]] | List) -> None:
        """Enqueue many evals; each may carry a token from a reblock
        (eval_broker.go:169 EnqueueAll)."""
        with self._l:
            if isinstance(evals, dict):
                items = list(evals.values())
            else:
                items = [(e, "") if not isinstance(e, tuple) else e for e in evals]
            for ev, token in items:
                self._process_enqueue(ev, token)

    def _process_enqueue(self, ev: s.Evaluation, token: str) -> None:
        if ev.id in self.evals:
            if token == "":
                return
            # Reblock from the owning scheduler: requeue once acked.
            unack = self.unack.get(ev.id)
            if unack is not None and unack.token == token:
                self.requeue[token] = ev
            return
        elif self._enabled:
            self.evals[ev.id] = 0
            # The shared choke point — instrumented here, after the
            # dedup check and only while enabled, so every actual
            # admission (enqueue, enqueue_all via blocked-eval unblock,
            # post-ack requeue) records exactly one broker.enqueue;
            # duplicates and drops by a disabled broker record none.
            tr = tracing.TRACER
            if tr is not None:
                tr.event("broker.enqueue", eval_id=ev.id, job_id=ev.job_id,
                         eval_type=ev.type, priority=ev.priority)
            self.metrics.incr_counter("broker.enqueue")

        if ev.wait > 0:
            self._process_waiting_enqueue(ev)
            return
        self._enqueue_locked(ev, ev.type)

    def _process_waiting_enqueue(self, ev: s.Evaluation) -> None:
        timer = threading.Timer(ev.wait, self._enqueue_waiting, args=(ev,))
        timer.daemon = True
        self.time_wait[ev.id] = timer
        timer.start()

    def _enqueue_waiting(self, ev: s.Evaluation) -> None:
        with self._l:
            self.time_wait.pop(ev.id, None)
            self._enqueue_locked(ev, ev.type)

    def _enqueue_locked(self, ev: s.Evaluation, queue: str) -> None:
        if not self._enabled:
            return
        pending_eval = self.job_evals.get(ev.job_id, "")
        if not pending_eval:
            self.job_evals[ev.job_id] = ev.id
        elif pending_eval != ev.id:
            heapq.heappush(self.blocked.setdefault(ev.job_id, []),
                           self._entry(ev))
            return

        heapq.heappush(self.ready.setdefault(queue, []), self._entry(ev))
        self._cond.notify_all()

    def _entry(self, ev: s.Evaluation) -> _HeapEntry:
        return _HeapEntry((-ev.priority, ev.create_index, next(self._seq)), ev)

    # -- dequeue -----------------------------------------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[s.Evaluation], str]:
        """Blocking dequeue of the highest-priority ready eval
        (eval_broker.go:279)."""
        import time as _time

        deadline = None if timeout is None or timeout == 0 else _time.monotonic() + timeout
        with self._l:
            while True:
                ev, token = self._scan(schedulers)
                if ev is not None:
                    return ev, token
                if timeout == 0:
                    return None, ""
                remaining = None if deadline is None else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None, ""
                self._cond.wait(remaining if remaining is not None else 1.0)

    def dequeue_batch(
        self, schedulers: List[str], max_batch: int, timeout: Optional[float] = None
    ) -> List[Tuple[s.Evaluation, str]]:
        """Drain up to max_batch ready evals in one call — the batch
        assembler feeding the TPU kernel (SURVEY.md §2.9)."""
        out: List[Tuple[s.Evaluation, str]] = []
        ev, token = self.dequeue(schedulers, timeout)
        if ev is None:
            return out
        out.append((ev, token))
        with self._l:
            while len(out) < max_batch:
                ev, token = self._scan(schedulers)
                if ev is None:
                    break
                out.append((ev, token))
        return out

    def _scan(self, schedulers: List[str]) -> Tuple[Optional[s.Evaluation], str]:
        if not self._enabled:
            raise EvalBrokerError("eval broker disabled")
        eligible: List[str] = []
        eligible_priority = 0
        for sched in schedulers:
            heap = self.ready.get(sched)
            if not heap:
                continue
            priority = heap[0].eval.priority
            if not eligible or priority > eligible_priority:
                eligible = [sched]
                eligible_priority = priority
            elif priority == eligible_priority:
                eligible.append(sched)
        if not eligible:
            return None, ""
        sched = eligible[0] if len(eligible) == 1 else random.choice(eligible)
        return self._dequeue_for_sched(sched)

    def _dequeue_for_sched(self, sched: str) -> Tuple[s.Evaluation, str]:
        heap = self.ready[sched]
        ev = heapq.heappop(heap).eval
        token = s.generate_uuid()

        timer: Optional[threading.Timer] = None
        if self.nack_timeout > 0:
            timer = threading.Timer(self.nack_timeout, self._nack_timeout_fire,
                                    args=(ev.id, token))
            timer.daemon = True
        unack = _Unack(ev, token, timer)
        self.unack[ev.id] = unack
        if timer is not None:
            timer.start()
        self.evals[ev.id] = self.evals.get(ev.id, 0) + 1
        tr = tracing.TRACER
        if tr is not None:
            tr.event("broker.dequeue", eval_id=ev.id, job_id=ev.job_id,
                     eval_type=ev.type, attempt=self.evals[ev.id])
        self.metrics.incr_counter("broker.dequeue")
        return ev, token

    def _nack_timeout_fire(self, eval_id: str, token: str) -> None:
        with self._l:
            unack = self.unack.get(eval_id)
            if unack is None or unack.token != token:
                return
            unack.fired = True
        try:
            self.nack(eval_id, token)
        except EvalBrokerError:
            pass

    # -- outstanding / ack / nack -----------------------------------------

    def delivery_attempts(self, eval_id: str) -> int:
        """How many times this eval has been dequeued (the delivery-limit
        counter); 0 for evals the broker isn't tracking."""
        with self._l:
            return self.evals.get(eval_id, 0)

    def outstanding(self, eval_id: str) -> Tuple[str, bool]:
        with self._l:
            unack = self.unack.get(eval_id)
            if unack is None:
                return "", False
            return unack.token, True

    def outstanding_reset(self, eval_id: str, token: str) -> None:
        with self._l:
            unack = self._get_unack(eval_id, token)
            if unack.fired:
                raise EvalBrokerError(ERR_NACK_TIMEOUT_REACHED)
            if unack.timer is not None:
                unack.timer.cancel()
                unack.timer = threading.Timer(
                    self.nack_timeout, self._nack_timeout_fire,
                    args=(eval_id, token))
                unack.timer.daemon = True
                unack.timer.start()

    def _get_unack(self, eval_id: str, token: str) -> _Unack:
        unack = self.unack.get(eval_id)
        if unack is None:
            raise EvalBrokerError(ERR_NOT_OUTSTANDING)
        if unack.token != token:
            raise EvalBrokerError(ERR_TOKEN_MISMATCH)
        return unack

    def ack(self, eval_id: str, token: str) -> None:
        """(eval_broker.go:481): release the job serialization slot, promote
        a blocked same-job eval, and process any requeue."""
        with self._l:
            try:
                unack = self._get_unack(eval_id, token)
                if unack.fired:
                    raise EvalBrokerError("Evaluation ID Ack'd after Nack timer expiration")
                if unack.timer is not None:
                    unack.timer.cancel()
                job_id = unack.eval.job_id
                tr = tracing.TRACER
                if tr is not None:
                    tr.event("broker.ack", eval_id=eval_id, job_id=job_id,
                             attempts=self.evals.get(eval_id, 0))
                self.metrics.incr_counter("broker.ack")
                eb = self.event_broker
                if eb is not None:
                    eb.publish_external(
                        "Eval", "EvalAcked", eval_id,
                        {"JobID": job_id,
                         "Attempts": self.evals.get(eval_id, 0)},
                        eval_id=eval_id)

                del self.unack[eval_id]
                self.evals.pop(eval_id, None)
                self.job_evals.pop(job_id, None)

                blocked = self.blocked.get(job_id)
                if blocked:
                    ev = heapq.heappop(blocked).eval
                    if not blocked:
                        del self.blocked[job_id]
                    self._enqueue_locked(ev, ev.type)

                requeued = self.requeue.pop(token, None)
                if requeued is not None:
                    self._process_enqueue(requeued, "")
            finally:
                self.requeue.pop(token, None)

    def nack(self, eval_id: str, token: str) -> None:
        """(eval_broker.go:540): redeliver with compounding delay, or shunt
        to the failed queue at the delivery limit."""
        with self._l:
            self.requeue.pop(token, None)
            unack = self._get_unack(eval_id, token)
            if unack.timer is not None:
                unack.timer.cancel()
            del self.unack[eval_id]

            dequeues = self.evals.get(eval_id, 0)
            if dequeues >= self.delivery_limit:
                outcome, wait = "failed", 0.0
                self._enqueue_locked(unack.eval, FAILED_QUEUE)
            else:
                ev = unack.eval
                ev.wait = self._nack_reenqueue_delay(dequeues)
                outcome, wait = "requeue", ev.wait
                if ev.wait > 0:
                    self._process_waiting_enqueue(ev)
                else:
                    self._enqueue_locked(ev, ev.type)
            tr = tracing.TRACER
            if tr is not None:
                tr.event("broker.nack", eval_id=eval_id,
                         job_id=unack.eval.job_id, attempts=dequeues,
                         outcome=outcome, wait=wait)
            self.metrics.incr_counter("broker.nack")
            eb = self.event_broker
            if eb is not None:
                eb.publish_external(
                    "Eval", "EvalNacked", eval_id,
                    {"JobID": unack.eval.job_id, "Attempts": dequeues,
                     "Outcome": outcome}, eval_id=eval_id)

    def _nack_reenqueue_delay(self, prev_dequeues: int) -> float:
        if prev_dequeues <= 0:
            return 0.0
        if prev_dequeues == 1:
            return self.initial_nack_delay
        return (prev_dequeues - 1) * self.subsequent_nack_delay

    def pause_nack_timeout(self, eval_id: str, token: str) -> None:
        with self._l:
            unack = self._get_unack(eval_id, token)
            if unack.fired:
                raise EvalBrokerError(ERR_NACK_TIMEOUT_REACHED)
            if unack.timer is not None:
                unack.timer.cancel()
            unack.paused = True

    def resume_nack_timeout(self, eval_id: str, token: str) -> None:
        with self._l:
            unack = self._get_unack(eval_id, token)
            unack.paused = False
            unack.timer = threading.Timer(
                self.nack_timeout, self._nack_timeout_fire, args=(eval_id, token))
            unack.timer.daemon = True
            unack.timer.start()

    # -- maintenance -------------------------------------------------------

    def flush(self) -> None:
        with self._l:
            for unack in self.unack.values():
                if unack.timer is not None:
                    unack.timer.cancel()
            for timer in self.time_wait.values():
                timer.cancel()
            self.evals = {}
            self.job_evals = {}
            self.blocked = {}
            self.ready = {}
            self.unack = {}
            self.requeue = {}
            self.time_wait = {}
            self._cond.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._l:
            return {
                "total_ready": sum(len(h) for h in self.ready.values()),
                "total_unacked": len(self.unack),
                "total_blocked": sum(len(h) for h in self.blocked.values()),
                "total_waiting": len(self.time_wait),
                "by_scheduler": {k: len(h) for k, h in self.ready.items()},
            }
