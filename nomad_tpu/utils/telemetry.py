"""Telemetry: gauges / counters / timing samples with a pluggable sink
(reference: armon/go-metrics as used throughout the server —
`metrics.MeasureSince` around every hot path, periodic gauge emitters at
nomad/server.go:292-305; the published-metric inventory lives in
website/source/docs/agent/telemetry.html.md)."""

from __future__ import annotations

import bisect
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class MetricsSink:
    """Sink interface (go-metrics MetricSink): statsite/statsd/datadog in
    the reference; in-memory + blackhole here, externals pluggable."""

    def set_gauge(self, key: str, value: float) -> None:
        raise NotImplementedError

    def incr_counter(self, key: str, value: float = 1.0) -> None:
        raise NotImplementedError

    def add_sample(self, key: str, value: float) -> None:
        raise NotImplementedError


class BlackholeSink(MetricsSink):
    def set_gauge(self, key, value):
        pass

    def incr_counter(self, key, value=1.0):
        pass

    def add_sample(self, key, value):
        pass


class _Aggregate:
    __slots__ = ("count", "sum", "min", "max")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def summary(self) -> Dict:
        mean = self.sum / self.count if self.count else 0.0
        return {"count": self.count, "sum": round(self.sum, 6),
                "min": round(self.min, 6), "max": round(self.max, 6),
                "mean": round(mean, 6)}


# Bucket upper bounds, 1-2.5-5 per decade: 10µs–60s for ms timings,
# extended through 1e7 so count-valued samples (asks per batch, rounds)
# don't all collapse into the +Inf bucket at north-star scale.
# Quantiles interpolate linearly inside a bucket, clamped to the
# observed min/max, so worst-case error is one bucket's width.
DEFAULT_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 25000.0, 60000.0,
    100000.0, 250000.0, 500000.0, 1000000.0, 2500000.0, 5000000.0,
    10000000.0,
)

# Exact-percentile window: while a key has seen ≤ this many samples the
# quantiles come from a sorted copy of the raw values (bench-grade
# fidelity for short runs); beyond it the histogram buckets take over.
EXACT_WINDOW = 256


class _Histogram(_Aggregate):
    """Sample aggregate with streaming p50/p95/p99: bucketed counts plus
    a bounded ring of raw samples for exact small-N quantiles."""

    __slots__ = ("bounds", "buckets", "ring")

    def __init__(self, bounds: tuple = DEFAULT_BUCKETS) -> None:
        super().__init__()
        self.bounds = bounds
        self.buckets = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self.ring: deque = deque(maxlen=EXACT_WINDOW)

    def add(self, v: float) -> None:
        super().add(v)
        self.buckets[bisect.bisect_left(self.bounds, v)] += 1
        self.ring.append(v)

    def percentile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        if self.count <= len(self.ring):
            ordered = sorted(self.ring)
            idx = min(len(ordered) - 1, int(q * len(ordered)))
            return ordered[idx]
        # Bucket interpolation: walk cumulative counts to the target
        # rank, interpolate within the containing bucket's bounds.
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.buckets):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else self.max
                frac = (rank - cum) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
            cum += c
        return self.max

    def summary(self) -> Dict:
        out = super().summary()
        out["p50"] = round(self.percentile(0.50), 6)
        out["p95"] = round(self.percentile(0.95), 6)
        out["p99"] = round(self.percentile(0.99), 6)
        return out


class InmemSink(MetricsSink):
    """Interval-ringed in-memory aggregation (go-metrics InmemSink), the
    default backing for agent-info / /v1/metrics."""

    def __init__(self, interval: float = 10.0, retain: int = 6):
        self.interval = interval
        self.retain = retain
        self._l = threading.Lock()
        self._intervals: List[Dict] = []
        # Process-lifetime monotonic totals, never reset by interval
        # rolls: counters as running sums, samples as [count, sum].
        # Prometheus rate()/increase() need monotonic series; the 10s
        # interval sums would reset faster than a typical scrape period
        # and silently drop most increments.
        self._counter_totals: Dict[str, float] = {}
        self._sample_totals: Dict[str, List[float]] = {}
        self._roll(time.time())

    def _roll(self, now: float) -> Dict:
        cur = {"start": now, "gauges": {}, "counters": {}, "samples": {}}
        self._intervals.append(cur)
        del self._intervals[:-self.retain]
        return cur

    def _current(self) -> Dict:
        now = time.time()
        cur = self._intervals[-1]
        if now - cur["start"] >= self.interval:
            cur = self._roll(now)
        return cur

    def set_gauge(self, key, value):
        with self._l:
            self._current()["gauges"][key] = value

    def incr_counter(self, key, value=1.0):
        with self._l:
            counters = self._current()["counters"]
            agg = counters.get(key)
            if agg is None:
                agg = counters[key] = _Aggregate()
            agg.add(value)
            self._counter_totals[key] = \
                self._counter_totals.get(key, 0.0) + value

    def add_sample(self, key, value):
        with self._l:
            # get-then-insert, not setdefault: a _Histogram carries a
            # 22-slot bucket list + ring, too heavy to build-and-discard
            # on every sample of an existing key.
            samples = self._current()["samples"]
            agg = samples.get(key)
            if agg is None:
                agg = samples[key] = _Histogram()
            # Totals live independently of the interval ring — a fresh
            # interval must not reset them.
            tot = self._sample_totals.get(key)
            if tot is None:
                tot = self._sample_totals[key] = [0, 0.0]
            agg.add(value)
            tot[0] += 1
            tot[1] += value

    def data(self) -> List[Dict]:
        """Recent intervals, aggregates summarized (InmemSink.Data)."""
        with self._l:
            out = []
            for iv in self._intervals:
                out.append({
                    "Start": iv["start"],
                    "Gauges": dict(iv["gauges"]),
                    "Counters": {k: v.summary()
                                 for k, v in iv["counters"].items()},
                    "Samples": {k: v.summary()
                                for k, v in iv["samples"].items()},
                })
            return out

    def latest(self) -> Dict:
        """Summary of only the newest interval (stats()'s hot call —
        avoids aggregating every retained interval under the lock),
        plus the process-lifetime monotonic totals for scrapers."""
        with self._l:
            iv = self._intervals[-1]
            return {
                "Start": iv["start"],
                "Gauges": dict(iv["gauges"]),
                "Counters": {k: v.summary() for k, v in iv["counters"].items()},
                "Samples": {k: v.summary() for k, v in iv["samples"].items()},
                "CounterTotals": dict(self._counter_totals),
                "SampleTotals": {k: (v[0], v[1])
                                 for k, v in self._sample_totals.items()},
            }


class Telemetry:
    """The measuring front end handed to subsystems
    (go-metrics Metrics object)."""

    def __init__(self, sink: Optional[MetricsSink] = None,
                 prefix: str = "nomad"):
        self.sink = sink if sink is not None else InmemSink()
        self.prefix = prefix

    def _key(self, key: str) -> str:
        return f"{self.prefix}.{key}" if self.prefix else key

    def set_gauge(self, key: str, value: float) -> None:
        self.sink.set_gauge(self._key(key), value)

    def incr_counter(self, key: str, value: float = 1.0) -> None:
        self.sink.incr_counter(self._key(key), value)

    def add_sample(self, key: str, value: float) -> None:
        self.sink.add_sample(self._key(key), value)

    def measure_since(self, key: str, start: float) -> None:
        """Record elapsed milliseconds (metrics.MeasureSince).  ``start``
        must come from ``time.perf_counter()`` — the same clock the
        tracing plane uses, so a timestamp can feed both a sample and a
        retroactive span."""
        self.sink.add_sample(self._key(key),
                             (time.perf_counter() - start) * 1000.0)

    class _Timer:
        def __init__(self, t: "Telemetry", key: str):
            self.t = t
            self.key = key

        def __enter__(self):
            self.start = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.t.measure_since(self.key, self.start)
            return False

    def measure(self, key: str) -> "Telemetry._Timer":
        return Telemetry._Timer(self, key)


NULL_TELEMETRY = Telemetry(sink=BlackholeSink())


# ---------------------------------------------------------------------------
# Prometheus text exposition (format 0.0.4) — /v1/metrics?format=prometheus
# ---------------------------------------------------------------------------

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(key: str) -> str:
    name = _PROM_NAME_RE.sub("_", key)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


def render_prometheus(latest: Dict) -> str:
    """Render an InmemSink.latest() summary as Prometheus text
    exposition: gauges as-is, counters as ``<name>_total``, samples as
    summaries with p50/p95/p99 quantile labels + ``_sum``/``_count``.

    Counters and summary ``_sum``/``_count`` come from the sink's
    process-lifetime monotonic totals (``CounterTotals`` /
    ``SampleTotals``), never the 10s interval aggregates — interval
    resets would be faster than a typical scrape period and rate()
    would silently drop most increments.  Quantiles are moment-in-time
    estimates from the newest interval, the standard summary shape."""
    lines: List[str] = []
    for key in sorted(latest.get("Gauges", ())):
        name = _prom_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_prom_value(latest['Gauges'][key])}")
    counter_totals = latest.get("CounterTotals") or {
        k: v.get("sum", 0.0) for k, v in latest.get("Counters", {}).items()}
    for key in sorted(counter_totals):
        name = _prom_name(key) + "_total"
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_prom_value(counter_totals[key])}")
    samples = latest.get("Samples", {})
    sample_totals = latest.get("SampleTotals") or {}
    # Union of keys: a key whose interval rolled quiet still has totals,
    # and its _sum/_count series must not go stale — only the quantile
    # estimates (interval-local by design) may be absent.
    for key in sorted(set(samples) | set(sample_totals)):
        agg = samples.get(key, {})
        name = _prom_name(key)
        lines.append(f"# TYPE {name} summary")
        for q, field_name in (("0.5", "p50"), ("0.95", "p95"),
                              ("0.99", "p99")):
            if field_name in agg:
                lines.append(f'{name}{{quantile="{q}"}} '
                             f"{_prom_value(agg[field_name])}")
        count, total = sample_totals.get(
            key, (agg.get("count", 0), agg.get("sum", 0.0)))
        lines.append(f"{name}_sum {_prom_value(total)}")
        lines.append(f"{name}_count {int(count)}")
    return "\n".join(lines) + "\n"
