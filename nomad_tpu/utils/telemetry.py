"""Telemetry: gauges / counters / timing samples with a pluggable sink
(reference: armon/go-metrics as used throughout the server —
`metrics.MeasureSince` around every hot path, periodic gauge emitters at
nomad/server.go:292-305; the published-metric inventory lives in
website/source/docs/agent/telemetry.html.md)."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class MetricsSink:
    """Sink interface (go-metrics MetricSink): statsite/statsd/datadog in
    the reference; in-memory + blackhole here, externals pluggable."""

    def set_gauge(self, key: str, value: float) -> None:
        raise NotImplementedError

    def incr_counter(self, key: str, value: float = 1.0) -> None:
        raise NotImplementedError

    def add_sample(self, key: str, value: float) -> None:
        raise NotImplementedError


class BlackholeSink(MetricsSink):
    def set_gauge(self, key, value):
        pass

    def incr_counter(self, key, value=1.0):
        pass

    def add_sample(self, key, value):
        pass


class _Aggregate:
    __slots__ = ("count", "sum", "min", "max")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def summary(self) -> Dict:
        mean = self.sum / self.count if self.count else 0.0
        return {"count": self.count, "sum": round(self.sum, 6),
                "min": round(self.min, 6), "max": round(self.max, 6),
                "mean": round(mean, 6)}


class InmemSink(MetricsSink):
    """Interval-ringed in-memory aggregation (go-metrics InmemSink), the
    default backing for agent-info / /v1/metrics."""

    def __init__(self, interval: float = 10.0, retain: int = 6):
        self.interval = interval
        self.retain = retain
        self._l = threading.Lock()
        self._intervals: List[Dict] = []
        self._roll(time.time())

    def _roll(self, now: float) -> Dict:
        cur = {"start": now, "gauges": {}, "counters": {}, "samples": {}}
        self._intervals.append(cur)
        del self._intervals[:-self.retain]
        return cur

    def _current(self) -> Dict:
        now = time.time()
        cur = self._intervals[-1]
        if now - cur["start"] >= self.interval:
            cur = self._roll(now)
        return cur

    def set_gauge(self, key, value):
        with self._l:
            self._current()["gauges"][key] = value

    def incr_counter(self, key, value=1.0):
        with self._l:
            agg = self._current()["counters"].setdefault(key, _Aggregate())
            agg.add(value)

    def add_sample(self, key, value):
        with self._l:
            agg = self._current()["samples"].setdefault(key, _Aggregate())
            agg.add(value)

    def data(self) -> List[Dict]:
        """Recent intervals, aggregates summarized (InmemSink.Data)."""
        with self._l:
            out = []
            for iv in self._intervals:
                out.append({
                    "Start": iv["start"],
                    "Gauges": dict(iv["gauges"]),
                    "Counters": {k: v.summary()
                                 for k, v in iv["counters"].items()},
                    "Samples": {k: v.summary()
                                for k, v in iv["samples"].items()},
                })
            return out

    def latest(self) -> Dict:
        """Summary of only the newest interval (stats()'s hot call —
        avoids aggregating every retained interval under the lock)."""
        with self._l:
            iv = self._intervals[-1]
            return {
                "Start": iv["start"],
                "Gauges": dict(iv["gauges"]),
                "Counters": {k: v.summary() for k, v in iv["counters"].items()},
                "Samples": {k: v.summary() for k, v in iv["samples"].items()},
            }


class Telemetry:
    """The measuring front end handed to subsystems
    (go-metrics Metrics object)."""

    def __init__(self, sink: Optional[MetricsSink] = None,
                 prefix: str = "nomad"):
        self.sink = sink if sink is not None else InmemSink()
        self.prefix = prefix

    def _key(self, key: str) -> str:
        return f"{self.prefix}.{key}" if self.prefix else key

    def set_gauge(self, key: str, value: float) -> None:
        self.sink.set_gauge(self._key(key), value)

    def incr_counter(self, key: str, value: float = 1.0) -> None:
        self.sink.incr_counter(self._key(key), value)

    def add_sample(self, key: str, value: float) -> None:
        self.sink.add_sample(self._key(key), value)

    def measure_since(self, key: str, start: float) -> None:
        """Record elapsed milliseconds (metrics.MeasureSince)."""
        self.sink.add_sample(self._key(key),
                             (time.monotonic() - start) * 1000.0)

    class _Timer:
        def __init__(self, t: "Telemetry", key: str):
            self.t = t
            self.key = key

        def __enter__(self):
            self.start = time.monotonic()
            return self

        def __exit__(self, *exc):
            self.t.measure_since(self.key, self.start)
            return False

    def measure(self, key: str) -> "Telemetry._Timer":
        return Telemetry._Timer(self, key)


NULL_TELEMETRY = Telemetry(sink=BlackholeSink())
