"""Incident flight recorder (``NOMAD_TPU_BLACKBOX=1``).

When an incident fires — the kernel circuit breaker opens, the safety
auditor records a violation, the lock-order sanitizer finds a cycle, or
the plan-apply p99 breaches its SLO — the forensic window is *now*: the
span ring, the event tail, and the profiler window all age out within
minutes.  The flight recorder freezes that window to disk as one JSON
bundle:

- recent span timeline (``tracing.recent``) and event-ring tail
  (``event_broker.recent``);
- a metrics snapshot + per-region/tenant broker stats from every
  registered server in the process;
- the continuous-profile window and contention ledger
  (``contprof.window``), plus an all-thread stack dump
  (``profiling.thread_dump``);
- knob values and breaker state.

Auto-captures are **bounded and deduplicated**: a per-reason minimum
interval (``NOMAD_TPU_BLACKBOX_MIN_INTERVAL_S``), a short global floor,
and a process-lifetime cap (``NOMAD_TPU_BLACKBOX_MAX_BUNDLES``) keep a
crash-looping trigger from filling the disk.  Operator-forced captures
(``nomad-tpu debug``, ``/v1/debug/blackbox``) bypass the limits, and
:func:`assemble_bundle` works even while disarmed so the on-demand
surfaces never depend on arming.

Capture runs on a spawned daemon thread: triggers fire from inside the
breaker's and auditor's critical sections, and bundle assembly takes
broker/sink locks — running it inline would deadlock or add lock-graph
edges.  The synchronous part of :func:`note_trigger` is only the
admission check under a raw (untracked) lock.

Disarmed (the default) the module global ``_STATE`` is ``None`` and
every trigger site costs one global load + branch — the ``fault.py``
discipline shared by the tracing and profiling planes.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from . import knobs, lockcheck, tracing
from .lockcheck import _REAL_LOCK as _RAW_LOCK

__all__ = [
    "FlightRecorder", "enable", "disable", "enabled",
    "maybe_arm_from_env", "note_trigger", "capture", "assemble_bundle",
    "register_server", "unregister_server", "bundles",
]

GLOBAL_FLOOR_S = 1.0      # min seconds between ANY two auto-captures
SPAN_TAIL = 400           # spans bundled from the tracing ring
EVENT_TAIL = 200          # events bundled from the process event tail
PROFILE_WINDOW_S = 60.0   # continuous-profile window per bundle

# Servers registered for state capture (server __init__/shutdown).
_SERVERS: List[Any] = []
_SERVERS_L = _RAW_LOCK()


def register_server(server: Any) -> None:
    with _SERVERS_L:
        if server not in _SERVERS:
            _SERVERS.append(server)


def unregister_server(server: Any) -> None:
    with _SERVERS_L:
        try:
            _SERVERS.remove(server)
        except ValueError:
            pass


def _jsonable(obj: Any) -> Any:
    """Best-effort conversion to JSON-serializable data; the recorder
    must never lose a bundle to one odd payload value."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_jsonable(v) for v in obj]
    return repr(obj)


def _event_dicts(events: List[Any]) -> List[Dict[str, Any]]:
    out = []
    for ev in events:
        out.append({
            "Topic": getattr(ev, "topic", ""),
            "Type": getattr(ev, "type", ""),
            "Key": getattr(ev, "key", ""),
            "Index": getattr(ev, "index", 0),
            "Payload": _jsonable(getattr(ev, "payload", {})),
            "EvalID": getattr(ev, "eval_id", ""),
            "SpanID": getattr(ev, "span_id", 0),
        })
    return out


def assemble_bundle(reason: str, detail: Optional[Dict] = None
                    ) -> Dict[str, Any]:
    """Build the in-memory bundle.  Works disarmed — the HTTP/CLI
    on-demand surfaces call this directly; the armed recorder adds the
    rate limiting and the write-to-disk around it."""
    bundle: Dict[str, Any] = {
        "Reason": reason,
        "Detail": _jsonable(detail or {}),
        "Wall": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "UnixTime": round(time.time(), 3),
        "Pid": os.getpid(),
        "Knobs": {k.name: knobs.raw(k.name) for k in knobs.registered()
                  if knobs.raw(k.name) is not None},
    }
    bundle["Spans"] = tracing.recent(SPAN_TAIL)
    # Server-package and ops-package reads go through sys.modules: the
    # utils layer must not import them (cycle), and ops drags in jax.
    ebm = sys.modules.get("nomad_tpu.server.event_broker")
    bundle["Events"] = _event_dicts(ebm.recent(EVENT_TAIL)) \
        if ebm is not None else []
    from . import contprof, profiling
    bundle["Profile"] = contprof.window(PROFILE_WINDOW_S)
    bundle["Locks"] = {
        "Waits": lockcheck.wait_stats(top=10),
        "Edges": len(lockcheck.edges()),
        "BlockingCalls": len(lockcheck.blocking_calls()),
    }
    bundle["Threads"] = profiling.thread_dump()
    brk = sys.modules.get("nomad_tpu.ops.breaker")
    if brk is not None:
        bundle["Breaker"] = {"State": brk.BREAKER.state,
                             "Trips": brk.BREAKER.trips}
    with _SERVERS_L:
        servers = list(_SERVERS)
    out_servers = []
    for srv in servers:
        try:
            out_servers.append({
                "Name": getattr(getattr(srv, "config", None),
                                "node_name", "?"),
                "Stats": _jsonable(srv.stats()),
                "BrokerStats": _jsonable(srv.broker_stats()),
                "Metrics": _jsonable(srv.metrics.sink.latest()),
            })
        except Exception:  # a shutting-down server must not kill capture
            continue
    bundle["Servers"] = out_servers
    return bundle


class FlightRecorder:
    """Rate-limited incident capture to a bundle directory."""

    def __init__(self, directory: Optional[str] = None,
                 min_interval_s: Optional[float] = None,
                 max_bundles: Optional[int] = None):
        if directory is None:
            directory = knobs.get_str("NOMAD_TPU_BLACKBOX_DIR") or \
                os.path.join(tempfile.gettempdir(), "nomad_tpu_blackbox")
        self.directory = directory
        if min_interval_s is None:
            min_interval_s = knobs.get_float(
                "NOMAD_TPU_BLACKBOX_MIN_INTERVAL_S", 30.0)
        self.min_interval_s = max(0.0, float(min_interval_s or 0.0))
        if max_bundles is None:
            max_bundles = knobs.get_int("NOMAD_TPU_BLACKBOX_MAX_BUNDLES",
                                        32)
        self.max_bundles = max(1, int(max_bundles or 32))
        self._l = _RAW_LOCK()  # admission only — never held in capture
        self._last_by_reason: Dict[str, float] = {}
        self._last_any = 0.0
        self._auto_count = 0
        self._seq = 0
        self.captured: List[str] = []  # bundle paths, oldest first

    def _admit(self, reason: str) -> bool:
        """Auto-capture admission: per-reason min interval, global
        floor, lifetime cap.  Cheap and synchronous — this is the only
        part that runs on the trigger's thread."""
        now = time.perf_counter()
        with self._l:
            if self._auto_count >= self.max_bundles:
                return False
            last = self._last_by_reason.get(reason)
            if last is not None and now - last < self.min_interval_s:
                return False
            if self._last_any and now - self._last_any < GLOBAL_FLOOR_S:
                return False
            self._last_by_reason[reason] = now
            self._last_any = now
            self._auto_count += 1
            return True

    def _bundle_path(self, reason: str) -> str:
        with self._l:
            self._seq += 1
            seq = self._seq
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in reason)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        return os.path.join(self.directory,
                            f"blackbox_{stamp}_{seq:03d}_{safe}.json")

    def capture(self, reason: str, detail: Optional[Dict] = None,
                force: bool = False) -> Optional[str]:
        """Assemble + write one bundle; returns its path.  ``force``
        (operator-initiated) bypasses rate limiting and the cap."""
        if not force and not self._admit(reason):
            return None
        try:
            bundle = assemble_bundle(reason, detail)
            os.makedirs(self.directory, exist_ok=True)
            path = self._bundle_path(reason)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(bundle, fh, indent=1, default=repr)
            os.replace(tmp, path)
        except Exception:  # pragma: no cover — recorder never raises
            return None
        with self._l:
            self.captured.append(path)
        ebm = sys.modules.get("nomad_tpu.server.event_broker")
        if ebm is not None:
            ebm.note_external("Blackbox", "BundleCaptured", reason,
                              {"Path": path})
        return path


# ---------------------------------------------------------------------------
# process-wide arming (fault.py discipline: None ⇒ disarmed)
# ---------------------------------------------------------------------------

_STATE: Optional[FlightRecorder] = None


def enable(directory: Optional[str] = None,
           min_interval_s: Optional[float] = None,
           max_bundles: Optional[int] = None) -> FlightRecorder:
    global _STATE
    if _STATE is None:
        _STATE = FlightRecorder(directory, min_interval_s, max_bundles)
    return _STATE


def disable() -> None:
    global _STATE
    _STATE = None


def enabled() -> bool:
    return _STATE is not None


def maybe_arm_from_env() -> bool:
    """Arm when NOMAD_TPU_BLACKBOX=1 — called at server construction so
    bench children and loadgen followers inherit the recorder."""
    if _STATE is None and knobs.get_bool("NOMAD_TPU_BLACKBOX"):
        enable()
        return True
    return False


def bundles() -> List[str]:
    st = _STATE
    return list(st.captured) if st is not None else []


def note_trigger(reason: str, detail: Optional[Dict] = None) -> None:
    """Incident hook for the breaker / auditor / sanitizer / SLO watch.
    One global load + branch while disarmed; when armed, the admission
    check runs synchronously and the capture itself on a daemon thread
    (trigger sites hold their subsystem's locks)."""
    st = _STATE
    if st is None:
        return
    if not st._admit(reason):
        return
    snap = _jsonable(detail or {})
    t = threading.Thread(
        target=lambda: st.capture(reason, snap, force=True),
        name="blackbox-capture", daemon=True)
    t.start()


def capture(reason: str, detail: Optional[Dict] = None,
            force: bool = True) -> Optional[str]:
    """Synchronous capture through the armed recorder (CLI/HTTP path);
    returns the bundle path, or None when disarmed or suppressed."""
    st = _STATE
    if st is None:
        return None
    return st.capture(reason, detail, force=force)
