"""snake_case <-> Go-style CamelCase name mapping.

The reference's user-visible JSON (api/ package structs) and diff output use
Go field names; our dataclasses use snake_case.  One mapping, used by both
the wire codec and the job-diff renderer.
"""

from __future__ import annotations

_TOKEN_MAP = {
    "id": "ID", "cpu": "CPU", "iops": "IOPS", "mb": "MB", "mbits": "MBits",
    "url": "URL", "ttl": "TTL", "http": "HTTP", "tls": "TLS", "ip": "IP",
    "uuid": "UUID", "gc": "GC", "ltarget": "LTarget", "rtarget": "RTarget",
    "tg": "TG", "dc": "DC", "rpc": "RPC", "tmpl": "Tmpl",
}


def go_name(snake: str) -> str:
    """kill_timeout -> KillTimeout, memory_mb -> MemoryMB, job_id -> JobID."""
    return "".join(_TOKEN_MAP.get(t, t.capitalize()) for t in snake.split("_"))
