"""mTLS configuration for the RPC layer (reference: helper/tlsutil —
region-wrapped mutual TLS for server↔server and client↔server RPC).

``TLSConfig`` carries the CA + cert/key paths from the agent's tls{}
block; ``server_context``/``client_context`` build ssl contexts that
REQUIRE the peer to present a certificate signed by the cluster CA
(mutual auth), with hostname verification replaced by CA pinning the way
the reference verifies ``server.<region>.nomad`` style names against the
cluster CA rather than public DNS.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass
class TLSConfig:
    enabled: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    verify_server_hostname: bool = False  # CA pinning by default


def server_context(cfg: TLSConfig) -> Optional[ssl.SSLContext]:
    """TLS context for listeners: present our cert, demand a CA-signed
    peer cert (tlsutil.Config.IncomingTLSConfig with VerifyIncoming)."""
    if not cfg.enabled:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    ctx.load_verify_locations(cfg.ca_file)
    ctx.verify_mode = ssl.CERT_REQUIRED  # mutual: clients must present
    return ctx


def client_context(cfg: TLSConfig) -> Optional[ssl.SSLContext]:
    """TLS context for dialers: verify the server against the cluster CA
    and present our own cert (tlsutil OutgoingTLSConfig)."""
    if not cfg.enabled:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    ctx.load_verify_locations(cfg.ca_file)
    if not cfg.verify_server_hostname:
        # Cluster-CA pinning: any cert signed by OUR CA is a cluster
        # member; hostnames are dynamic addresses, not DNS identities.
        ctx.check_hostname = False
    return ctx
