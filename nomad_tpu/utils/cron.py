"""Minimal cron expression evaluation for periodic jobs.

The reference embeds gorhill/cronexpr (used via nomad/periodic.go and
structs.go PeriodicConfig.Next).  This is a clean 5-field implementation
(minute hour day-of-month month day-of-week) supporting ``*``, lists,
ranges, and ``/step``, plus the common ``@hourly``-style shortcuts.
"""
from __future__ import annotations

import calendar
import time
from typing import List, Optional, Set

_SHORTCUTS = {
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
    "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}

_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]

_MONTH_NAMES = {name.lower(): i for i, name in enumerate(calendar.month_abbr) if name}
# cron day-of-week convention: 0=Sunday
_DOW_NAMES = {"sun": 0, "mon": 1, "tue": 2, "wed": 3, "thu": 4, "fri": 5, "sat": 6}


class CronParseError(ValueError):
    pass


def _parse_field(field: str, lo: int, hi: int, names=None) -> Set[int]:
    out: Set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError as e:
                raise CronParseError(f"bad step {step_s!r}") from e
            if step <= 0:
                raise CronParseError(f"bad step {step}")
        if part in ("*", "?"):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = _atom(a, names), _atom(b, names)
        else:
            start = _atom(part, names)
            end = start if step == 1 else hi
        if start < lo or end > hi or start > end:
            raise CronParseError(f"field value out of range: {part!r}")
        out.update(range(start, end + 1, step))
    return out


def _atom(s: str, names) -> int:
    s = s.strip().lower()
    if names and s in names:
        return names[s]
    try:
        return int(s)
    except ValueError as e:
        raise CronParseError(f"bad value {s!r}") from e


class CronExpr:
    def __init__(self, spec: str):
        spec = spec.strip()
        spec = _SHORTCUTS.get(spec, spec)
        fields = spec.split()
        # Field-count conventions follow gorhill/cronexpr (used by the
        # reference): 5 = standard; 6 = standard + trailing year;
        # 7 = leading seconds + standard + year (seconds are floored to :00).
        self.years: Optional[Set[int]] = None
        if len(fields) == 7:
            fields = fields[1:]
        if len(fields) == 6:
            year_field = fields[5]
            if year_field not in ("*", "?"):
                self.years = _parse_field(year_field, 1970, 2099)
            fields = fields[:5]
        if len(fields) != 5:
            raise CronParseError(f"expected 5 cron fields, got {len(fields)}")
        self.minutes = _parse_field(fields[0], *_RANGES[0])
        self.hours = _parse_field(fields[1], *_RANGES[1])
        self.dom = _parse_field(fields[2], *_RANGES[2])
        self.months = _parse_field(fields[3], *_RANGES[3], names=_MONTH_NAMES)
        self.dow = _parse_field(fields[4], *_RANGES[4], names=_DOW_NAMES)
        self.dom_star = fields[2] in ("*", "?")
        self.dow_star = fields[4] in ("*", "?")

    def _day_matches(self, tm: time.struct_time) -> bool:
        dow_cron = (tm.tm_wday + 1) % 7  # python Mon=0 → cron Sun=0
        dom_ok = tm.tm_mday in self.dom
        dow_ok = dow_cron in self.dow
        # Standard cron: if both dom and dow are restricted, either may match.
        if not self.dom_star and not self.dow_star:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def next(self, after: float) -> float:
        """The next matching time strictly after ``after`` (unix seconds);
        0.0 if none within ~4 years."""
        t = int(after) - (int(after) % 60) + 60
        limit = int(after) + 4 * 366 * 86400
        if self.years:
            # An explicit year field may point far ahead; search to its end.
            horizon = int(time.mktime((max(self.years) + 1, 1, 1, 0, 0, 0, 0, 1, -1)))
            limit = max(limit, horizon)
        while t < limit:
            tm = time.localtime(t)
            if self.years is not None and tm.tm_year not in self.years:
                if all(tm.tm_year > y for y in self.years):
                    return 0.0
                t = int(time.mktime((tm.tm_year + 1, 1, 1, 0, 0, 0, 0, 1, -1)))
                continue
            if tm.tm_mon not in self.months:
                # jump to the 1st of next month
                year, month = tm.tm_year, tm.tm_mon + 1
                if month > 12:
                    year, month = year + 1, 1
                t = int(time.mktime((year, month, 1, 0, 0, 0, 0, 1, -1)))
                continue
            if not self._day_matches(tm):
                # Advance to the next calendar day's midnight; mktime
                # normalizes mday+1 and DST so a 23-hour day can't skip it.
                t = int(time.mktime((tm.tm_year, tm.tm_mon, tm.tm_mday + 1, 0, 0, 0, 0, 1, -1)))
                continue
            if tm.tm_hour not in self.hours:
                t += 3600 - tm.tm_min * 60 - tm.tm_sec
                continue
            if tm.tm_min not in self.minutes:
                t += 60 - tm.tm_sec
                continue
            return float(t)
        return 0.0


def cron_next(spec: str, after: float) -> float:
    return CronExpr(spec).next(after)
