"""Semantic-ish version parsing and constraint matching.

Replicates the behavior of hashicorp/go-version as used by the scheduler's
``version`` constraint operand (reference: scheduler/feasible.go:487
checkVersionConstraint): versions like ``1.2.3``, ``0.7.1-rc1``; constraint
strings like ``>= 0.6.0, < 0.8``.
"""
from __future__ import annotations

import re
from functools import total_ordering
from typing import List, Optional, Tuple

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)" r"(?:-([0-9A-Za-z.-]+))?" r"(?:\+([0-9A-Za-z.-]+))?$"
)


@total_ordering
class Version:
    def __init__(self, text: str):
        m = _VERSION_RE.match(text.strip())
        if not m:
            raise ValueError(f"malformed version: {text!r}")
        self.segments: Tuple[int, ...] = tuple(int(p) for p in m.group(1).split("."))
        self.prerelease: str = m.group(2) or ""
        self.metadata: str = m.group(3) or ""

    def _padded(self, n: int = 3) -> Tuple[int, ...]:
        segs = self.segments
        return segs + (0,) * (n - len(segs)) if len(segs) < n else segs

    def __eq__(self, other) -> bool:
        if not isinstance(other, Version):
            return NotImplemented
        n = max(len(self.segments), len(other.segments), 3)
        return (self._padded(n), self.prerelease) == (other._padded(n), other.prerelease)

    def __lt__(self, other) -> bool:
        if not isinstance(other, Version):
            return NotImplemented
        n = max(len(self.segments), len(other.segments), 3)
        if self._padded(n) != other._padded(n):
            return self._padded(n) < other._padded(n)
        # A prerelease sorts before its release.
        if (self.prerelease == "") != (other.prerelease == ""):
            return self.prerelease != ""
        return self.prerelease < other.prerelease

    def __repr__(self) -> str:
        return f"Version({'.'.join(map(str, self.segments))}{'-' + self.prerelease if self.prerelease else ''})"


_CONSTRAINT_RE = re.compile(r"^\s*(>=|<=|!=|>|<|=|~>)?\s*(.+?)\s*$")


class Constraint:
    def __init__(self, text: str):
        m = _CONSTRAINT_RE.match(text)
        if not m or not m.group(2):
            raise ValueError(f"malformed constraint: {text!r}")
        self.op = m.group(1) or "="
        self.version = Version(m.group(2))

    def check(self, v: Version) -> bool:
        if self.op == "=":
            return v == self.version
        if self.op == "!=":
            return v != self.version
        if self.op == ">":
            return v > self.version
        if self.op == ">=":
            return v >= self.version
        if self.op == "<":
            return v < self.version
        if self.op == "<=":
            return v <= self.version
        if self.op == "~>":
            # pessimistic operator: >= x.y.z and < x.(y+1) style bump of the
            # second-to-last specified segment
            if v < self.version:
                return False
            segs = list(self.version.segments)
            if len(segs) == 1:
                upper = [segs[0] + 1]
            else:
                upper = segs[:-2] + [segs[-2] + 1, 0]
            bound = Version(".".join(map(str, upper)))
            return v < bound
        return False


class Constraints:
    """A comma-separated conjunction of constraints."""

    def __init__(self, text: str):
        parts = [p for p in (x.strip() for x in text.split(",")) if p]
        if not parts:
            raise ValueError("empty constraint")
        self.constraints: List[Constraint] = [Constraint(p) for p in parts]

    def check(self, v: Version) -> bool:
        return all(c.check(v) for c in self.constraints)


def parse_version(text: str) -> Optional[Version]:
    try:
        return Version(text)
    except ValueError:
        return None


def parse_constraints(text: str) -> Optional[Constraints]:
    try:
        return Constraints(text)
    except ValueError:
        return None
