"""Central registry for every ``NOMAD_TPU_*`` environment knob.

The repo grew ~60 env knobs across four PR generations, each read with
its own inline ``os.environ.get(...)`` idiom and its own parsing quirks
("" vs unset, ``("1", "true")`` vs ``not in ("0", "false")``).  Two
failure modes followed: knob semantics drifted between read sites, and
the README table drifted from the code.  This module is the single
authority:

- every knob is **declared once** here (name, type, default, one-line
  doc) — reads of undeclared names raise :class:`UnknownKnobError`;
- every read goes through :func:`get_bool` / :func:`get_int` /
  :func:`get_float` / :func:`get_str` / :func:`raw` — the static
  analysis pass (``python -m nomad_tpu.analysis``) fails the tree on
  any ad-hoc ``os.environ`` read of a ``NOMAD_TPU_*`` name outside
  this file;
- the README "Env knobs" table is **generated** from the registry
  (:func:`render_readme_table`) and asserted in sync by the same pass.

Parsing semantics (the one place that decides):

- values are re-read from ``os.environ`` on every call — knobs are
  runtime kill-switches, never cached at import;
- bool: unset or empty ⇒ default; otherwise anything except
  ``0/false/no/off`` (case-insensitive) is true;
- int/float: unset, empty, or unparseable ⇒ default (a malformed knob
  must degrade to the default, not crash a server mid-flight) — but an
  unparseable value warns ONCE per name on stderr so an operator typo
  (``NOMAD_TPU_BENCH_MESH_NODES=50k``) cannot silently benchmark the
  wrong shape;
- save/restore sites (arm a knob for a drill, restore after) use
  :func:`raw`, which returns the verbatim env value or ``None``.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterator, Optional

__all__ = [
    "Knob", "UnknownKnobError", "registered", "lookup", "raw",
    "get_bool", "get_int", "get_float", "get_str",
    "render_readme_table",
]

_FALSY = ("0", "false", "no", "off")


class UnknownKnobError(KeyError):
    """A NOMAD_TPU_* name was read that is not declared in the registry
    — declare it in utils/knobs.py (with a doc line) before use."""


@dataclass(frozen=True)
class Knob:
    name: str
    kind: str          # "bool" | "int" | "float" | "str"
    default: object    # None ⇒ "unset" is meaningful to the caller
    doc: str
    # Shown in the README default column when the real default is
    # computed elsewhere (class attribute, sibling config field).
    default_label: Optional[str] = None

    def default_text(self) -> str:
        if self.default_label is not None:
            return self.default_label
        if self.default is None:
            return "unset"
        if self.kind == "bool":
            return "1" if self.default else "0"
        return str(self.default)


_REGISTRY: Dict[str, Knob] = {}


def _knob(name: str, kind: str, default, doc: str,
          default_label: Optional[str] = None) -> None:
    _REGISTRY[name] = Knob(name, kind, default, doc, default_label)


# ---------------------------------------------------------------------------
# The registry.  Grouped roughly by subsystem; insertion order is the
# README table order.
# ---------------------------------------------------------------------------

# -- device hot path --------------------------------------------------------
_knob("NOMAD_TPU_FUSED", "bool", True,
      "Fused score-and-commit: ONE device dispatch + ONE fetch per "
      "batch; 0 keeps the bit-identical two-phase split")
_knob("NOMAD_TPU_QUANT", "bool", True,
      "Quantized int8/int16 static resource rows (exact-or-absent "
      "round-trip, guarded)")
_knob("NOMAD_TPU_PALLAS", "bool", False,
      "Opt into the Pallas kernels (OFF pending hardware go/no-go, "
      "see README)")
_knob("NOMAD_TPU_RNG_SEED", "int", None,
      "Pin the per-batch tie-break jitter seed for deterministic "
      "placement reproduction")
_knob("NOMAD_TPU_TIMING", "str", "",
      "Timing diagnostics: 1 = phase summaries, 2 = staged two-phase "
      "sync split (diagnostics only)")
_knob("NOMAD_TPU_PREEMPTION", "bool", False,
      "Default for schedulers constructed without an explicit "
      "preemption flag")
_knob("NOMAD_TPU_NO_COMPILE_CACHE", "bool", False,
      "Disable the persistent XLA compilation cache")
_knob("NOMAD_TPU_COMPILE_CACHE_DIR", "str", None,
      "Persistent XLA compile cache location",
      default_label="~/.cache/nomad_tpu/xla")
_knob("NOMAD_TPU_PIPELINE", "bool", False,
      "Pipelined BatchWorker drain: prepare batch k+1 overlaps batch "
      "k's device pass")

# -- device-resident state --------------------------------------------------
_knob("NOMAD_TPU_RESIDENT", "bool", True,
      "Device-resident usage cache (delta scatter-adds instead of "
      "per-batch re-encode)")
_knob("NOMAD_TPU_RESIDENT_DEVICE", "bool", True,
      "Donated on-device usage mirror (single-chip and per-shard mesh "
      "twins); 0 keeps the sparse-delta upload")
_knob("NOMAD_TPU_RESIDENT_GUARD_EVERY", "int", 64,
      "Resident-mirror differential-guard cadence in hits (0 disables "
      "the guard)")
_knob("NOMAD_TPU_ALLOC_LOG_CAP", "int", 262144,
      "Usage-delta log bound in alloc rows; overflow forces consumers "
      "to full re-encode")

# -- TPU-path circuit breaker -----------------------------------------------
_knob("NOMAD_TPU_BREAKER_THRESHOLD", "float", 0.9,
      "Minimum kernel/oracle agreement ratio before the breaker opens")
_knob("NOMAD_TPU_BREAKER_WINDOW", "int", 64,
      "Sliding agreement window (checks)")
_knob("NOMAD_TPU_BREAKER_MIN_CHECKS", "int", 8,
      "Checks required in-window before the breaker may trip")
_knob("NOMAD_TPU_BREAKER_COOLDOWN", "float", 10.0,
      "Seconds open before a half-open probe")
_knob("NOMAD_TPU_BREAKER_DISABLE", "bool", False,
      "1 ⇒ the breaker never trips (forensics only — degradation "
      "routing stays off)")

# -- columnar store / codec / native twins ----------------------------------
_knob("NOMAD_TPU_COLUMNAR", "bool", True,
      "Columnar numpy mirrors of the node table + binary NTPUSNP2 "
      "snapshots; 0 restores the object walk and legacy blobs")
_knob("NOMAD_TPU_COLUMNAR_GUARD_EVERY", "int", 16,
      "Columnar-vs-walk differential-guard cadence in encodes (tests "
      "pin 1)")
_knob("NOMAD_TPU_CODEC", "bool", True,
      "Generated struct codec for RPC/raft/snapshots; 0 encodes "
      "msgpack (decode sniffs both forever)")
_knob("NOMAD_TPU_CODEC_GUARD_EVERY", "int", 512,
      "Native/python string-column twin bit-compare cadence (tests "
      "pin 1)")
_knob("NOMAD_TPU_DECODE_GUARD_EVERY", "int", 64,
      "Native packed-result-decode twin bit-compare cadence (tests "
      "pin 1)")
_knob("NOMAD_TPU_NO_NATIVE", "bool", False,
      "Force the pure-Python fallbacks for every native (C++) "
      "component")
_knob("NOMAD_TPU_NATIVE_CACHE", "str", None,
      "Content-addressed native .so build cache",
      default_label="~/.cache/nomad_tpu/native")
_knob("NOMAD_TPU_NATIVE_ASAN", "bool", False,
      "Build the native components with ASan+UBSan and run them under "
      "the sanitizer runtimes (selfcheck corpus leg)")

# -- control plane ----------------------------------------------------------
_knob("NOMAD_TPU_STALE_SNAPSHOT", "bool", True,
      "Workers reuse a cached snapshot when it covers the eval's "
      "trigger indexes + plan fence; 0 restores snapshot-per-eval")
_knob("NOMAD_TPU_STALE_SNAPSHOT_LAG", "int", 512,
      "Max raft entries a reused snapshot may lag the applied index")
_knob("NOMAD_TPU_PLAN_PIPELINE", "int", 8,
      "Concurrent in-flight plan commits (1 restores the strictly "
      "serial applier)")
_knob("NOMAD_TPU_BROKER_MAX_PENDING", "int", 0,
      "Eval-broker admission bound (0 = unbounded historical "
      "behavior); overflow 429-NACKs with Retry-After")
_knob("NOMAD_TPU_BROKER_COALESCE", "bool", True,
      "Per-job coalescing of deferred duplicate evals")
_knob("NOMAD_TPU_BROKER_BYPASS_PRIO", "int", None,
      "Priority at or above which admission control is bypassed",
      default_label="JOB_MAX_PRIORITY (100)")
_knob("NOMAD_TPU_FOLLOWER_SCHED", "bool", True,
      "Follower-read scheduling: FollowerWorkers on non-leader "
      "servers pull evals and forward plans")
_knob("NOMAD_TPU_REMOTE_NACK_PAUSE", "bool", False,
      "Follower workers pause/resume the broker nack deadline over "
      "the wire (short-deadline deployments)")
_knob("NOMAD_TPU_HEARTBEAT_JITTER", "float", 0.1,
      "Upward heartbeat-TTL jitter fraction (thundering-herd "
      "dispersal)")

# -- raft / WAL / snapshots -------------------------------------------------
_knob("NOMAD_TPU_RAFT_HEARTBEAT_S", "float", None,
      "Leader heartbeat interval override (loaded measurement "
      "clusters slow elections)",
      default_label="RaftNode.HEARTBEAT_INTERVAL")
_knob("NOMAD_TPU_RAFT_ELECTION_MIN_S", "float", None,
      "Election timeout lower bound override",
      default_label="RaftNode.ELECTION_TIMEOUT[0]")
_knob("NOMAD_TPU_RAFT_ELECTION_MAX_S", "float", None,
      "Election timeout upper bound override",
      default_label="RaftNode.ELECTION_TIMEOUT[1]")
_knob("NOMAD_TPU_FILELOG_SNAPSHOT_ENTRIES", "int", 8192,
      "Auto-snapshot threshold: WAL entries since the last snapshot "
      "(0 disables)")
_knob("NOMAD_TPU_FILELOG_SNAPSHOT_BYTES", "int", 64 << 20,
      "Auto-snapshot threshold: WAL bytes since the last snapshot")
_knob("NOMAD_TPU_FILELOG_SNAPSHOT_INTERVAL", "float", 1.0,
      "Auto-snapshot watcher poll interval (seconds)")
_knob("NOMAD_TPU_SNAPSHOT_CHUNK", "int", 4 << 20,
      "InstallSnapshot streaming chunk size in bytes")

# -- observability / events / chaos -----------------------------------------
_knob("NOMAD_TPU_TRACE", "bool", False,
      "Arm the eval-lifecycle tracing plane at server construction")
_knob("NOMAD_TPU_EVENTS", "bool", False,
      "Arm the cluster event stream at server construction (also "
      "armed lazily by the first subscriber)")
_knob("NOMAD_TPU_EVENTS_RING", "int", 4096,
      "Event-stream ring buffer size")
_knob("NOMAD_TPU_CHAOS", "bool", False,
      "Register the Chaos.* control RPC endpoints (never on a "
      "production wire surface)")
_knob("NOMAD_TPU_CHAOS_NET", "str", "",
      "JSON net-chaos spec armed at server construction "
      "(partitions/rules/seed)")
_knob("NOMAD_TPU_LOCKCHECK", "bool", False,
      "Arm the runtime lock-order sanitizer (utils/lockcheck.py): "
      "instrumented locks record acquisition order, teardown asserts "
      "acyclicity and prints the witness cycle")
_knob("NOMAD_TPU_CONTPROF", "bool", False,
      "Arm the continuous host-attribution profiler "
      "(utils/contprof.py) at server construction: a low-Hz sampler "
      "classifies every thread's stack into subsystem CPU-share "
      "gauges (nomad.cpu.<subsystem>)")
_knob("NOMAD_TPU_CONTPROF_HZ", "float", 10.0,
      "Continuous-profiler sampling rate in Hz (clamped to 1-100)")
_knob("NOMAD_TPU_CONTPROF_RING", "int", 120,
      "Continuous-profiler ring: how many 5s aggregation windows are "
      "retained for the /v1/profile/continuous surface")
_knob("NOMAD_TPU_CONTPROF_GIL_MS", "float", 5.0,
      "GIL-pressure probe requested sleep in milliseconds (the probe "
      "measures scheduling-delay jitter against it; 0 disables the "
      "probe thread)")
_knob("NOMAD_TPU_BLACKBOX", "bool", False,
      "Arm the incident flight recorder (utils/blackbox.py) at "
      "server construction: breaker opens, auditor violations, lock "
      "cycles and plan-apply SLO breaches capture a JSON bundle")
_knob("NOMAD_TPU_BLACKBOX_DIR", "str", None,
      "Flight-recorder bundle directory",
      default_label="<tmpdir>/nomad_tpu_blackbox")
_knob("NOMAD_TPU_BLACKBOX_MIN_INTERVAL_S", "float", 30.0,
      "Flight recorder: minimum seconds between two auto-captures "
      "for the same trigger reason (dedup/rate limit)")
_knob("NOMAD_TPU_BLACKBOX_MAX_BUNDLES", "int", 32,
      "Flight recorder: hard cap on auto-captured bundles per "
      "process (operator-forced captures are exempt)")
_knob("NOMAD_TPU_BLACKBOX_SLO_PLAN_P99_MS", "float", 0.0,
      "Plan-apply p99 SLO in milliseconds watched by the metrics "
      "emitter; a breach auto-captures a flight-recorder bundle "
      "(0 disables the watch)")

# -- multi-tenant serving plane ---------------------------------------------
_knob("NOMAD_TPU_TENANCY_OBJECTIVE", "str", "drf",
      "Cluster-wide default fair-dequeue objective "
      "(drf | weighted-rr | fifo); a Namespace row's objective field "
      "overrides per tenant")
_knob("NOMAD_TPU_TENANCY_METRICS_TOP", "int", 10,
      "How many busiest tenants get per-tenant tenant.* gauges each "
      "metrics tick (0 disables)")

# -- region federation ------------------------------------------------------
_knob("NOMAD_TPU_REGION_DIAL_ROUNDS", "int", 2,
      "Cross-region forwarding: how many full passes over the target "
      "region's known servers before giving up with NoPathToRegion")
_knob("NOMAD_TPU_REGION_RETRY_AFTER_CAP", "float", 5.0,
      "Cap on the retry_after hint carried by NoPathToRegion (seconds)")
_knob("NOMAD_TPU_REGION_PROBE_TIMEOUT", "float", 1.0,
      "Timeout for best-effort cross-region leader probes in the "
      "/v1/regions detail surface (seconds)")

# -- loadgen / bench --------------------------------------------------------
_knob("NOMAD_TPU_SWITCH_INTERVAL", "float", None,
      "sys.setswitchinterval override applied for loadgen "
      "measurement runs")
_knob("NOMAD_TPU_LG_PROFILE", "bool", False,
      "Start the sampling profiler in loadgen follower children")
_knob("NOMAD_TPU_BENCH_BUDGET_S", "float", None,
      "Bench trajectory wall-clock budget override (seconds)")
_knob("NOMAD_TPU_BENCH_CHECK_THRESHOLD", "float", None,
      "bench --check regression tolerance override",
      default_label="1.5")
_knob("NOMAD_TPU_BENCH_PARTIAL", "str", None,
      "Bench child: path receiving partial trajectory JSON after "
      "every phase")
_knob("NOMAD_TPU_BENCH_CHILD", "str", None,
      "Internal: marks a bench trajectory child process")
_knob("NOMAD_TPU_BENCH_TPU_RETRY", "str", None,
      "Internal: marks the bench core-phases-on-TPU retry child")
_knob("NOMAD_TPU_BENCH_MESH_CHILD", "str", None,
      "Internal: marks the forced-8-device config_mesh child")
_knob("NOMAD_TPU_BENCH_MESH_STEADY_CHILD", "str", None,
      "Internal: marks the config_mesh_steady child")
_knob("NOMAD_TPU_BENCH_MESH10M", "bool", False,
      "Opt into the ~10min 10M-node config_mesh_10m bench point")
_knob("NOMAD_TPU_BENCH_MESH_NODES", "int", None,
      "config_mesh cluster size override", default_label="1000000")
_knob("NOMAD_TPU_BENCH_MESH_JOBS", "int", None,
      "config_mesh job count override", default_label="100")
_knob("NOMAD_TPU_BENCH_MESH_COUNT", "int", None,
      "config_mesh per-job taskgroup count override",
      default_label="100000")
_knob("NOMAD_TPU_BENCH_MESH_STEADY_NODES", "int", None,
      "config_mesh_steady warm cluster size override",
      default_label="1000000")
_knob("NOMAD_TPU_BENCH_MESH_STEADY_BATCHES", "int", None,
      "config_mesh_steady stream length override", default_label="200")
_knob("NOMAD_TPU_BENCH_SNAP_NODES", "int", 50000,
      "config_snapshot node count")
_knob("NOMAD_TPU_BENCH_SNAP_ALLOCS", "int", 250000,
      "config_snapshot alloc count")


# ---------------------------------------------------------------------------
# accessors
# ---------------------------------------------------------------------------

_UNSET = object()


def lookup(name: str) -> Knob:
    knob = _REGISTRY.get(name)
    if knob is None:
        raise UnknownKnobError(
            f"{name} is not declared in nomad_tpu/utils/knobs.py — "
            f"register it (with a doc line) before reading it")
    return knob


def registered() -> Iterator[Knob]:
    """All knobs in declaration (= README table) order."""
    return iter(_REGISTRY.values())


def raw(name: str) -> Optional[str]:
    """Verbatim env value (or None) for save/restore around drills and
    bench phases.  Registry-checked like every other accessor."""
    lookup(name)
    return os.environ.get(name)


def _resolve_default(name: str, default):
    if default is _UNSET:
        return lookup(name).default
    lookup(name)
    return default


def get_bool(name: str, default=_UNSET) -> bool:
    dflt = _resolve_default(name, default)
    val = os.environ.get(name)
    if val is None:
        return bool(dflt)
    val = val.strip().lower()
    if val == "":
        return bool(dflt)
    return val not in _FALSY


_WARNED_MALFORMED: set = set()


def _warn_malformed(name: str, val: str, kind: str, dflt) -> None:
    if name in _WARNED_MALFORMED:
        return
    _WARNED_MALFORMED.add(name)
    import sys

    print(f"nomad_tpu: malformed {kind} knob {name}={val!r} — "
          f"using default {dflt!r}", file=sys.stderr)


def get_int(name: str, default=_UNSET) -> Optional[int]:
    dflt = _resolve_default(name, default)
    val = os.environ.get(name)
    if val is None or not val.strip():
        return dflt
    try:
        return int(val)
    except ValueError:
        _warn_malformed(name, val, "int", dflt)
        return dflt


def get_float(name: str, default=_UNSET) -> Optional[float]:
    dflt = _resolve_default(name, default)
    val = os.environ.get(name)
    if val is None or not val.strip():
        return dflt
    try:
        return float(val)
    except ValueError:
        _warn_malformed(name, val, "float", dflt)
        return dflt


def get_str(name: str, default=_UNSET) -> Optional[str]:
    dflt = _resolve_default(name, default)
    val = os.environ.get(name)
    if val is None:
        return dflt
    return val


# ---------------------------------------------------------------------------
# README table generation
# ---------------------------------------------------------------------------

TABLE_BEGIN = "<!-- knob-table:begin (generated by python -m nomad_tpu.analysis --write-knob-table) -->"
TABLE_END = "<!-- knob-table:end -->"


def render_readme_table() -> str:
    """The README env-knob table, generated so it cannot drift.  The
    analysis pass asserts the README section between the markers equals
    this rendering byte-for-byte."""
    lines = [
        TABLE_BEGIN,
        "",
        "| Knob | Type | Default | Meaning |",
        "|---|---|---|---|",
    ]
    for knob in registered():
        lines.append(
            f"| `{knob.name}` | {knob.kind} | `{knob.default_text()}` "
            f"| {knob.doc} |")
    lines.append("")
    lines.append(TABLE_END)
    return "\n".join(lines)
