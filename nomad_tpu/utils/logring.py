"""Agent log ring + streaming (reference: command/agent/log_*.go — the
gated writer and log writer ring feeding /v1/agent/monitor-style
streaming, plus the level filter).

A logging.Handler keeps the last N formatted records in a ring; monitors
attach a queue and receive every subsequent record (the gated-writer
role: late attachers first drain the retained backlog)."""

from __future__ import annotations

import collections
import logging
import queue
import threading
from typing import Iterator, List, Optional


class LogRingHandler(logging.Handler):
    """Ring buffer of formatted log lines with live fan-out."""

    def __init__(self, capacity: int = 512):
        super().__init__()
        self.capacity = capacity
        self._l = threading.Lock()
        self._ring: "collections.deque[str]" = collections.deque(
            maxlen=capacity)
        self._monitors: List["queue.Queue[str]"] = []
        self.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:
            return
        with self._l:
            self._ring.append(line)
            monitors = list(self._monitors)
        for q in monitors:
            try:
                q.put_nowait(line)
            except queue.Full:
                pass  # slow monitor: drop, never block logging

    def backlog(self) -> List[str]:
        with self._l:
            return list(self._ring)

    def monitor(self, level: int = logging.INFO,
                stop_event: Optional[threading.Event] = None,
                ) -> Iterator[str]:
        """Yield retained lines then follow live ones (the monitor
        command's stream).  The caller stops by closing the generator or
        setting ``stop_event``."""
        q: "queue.Queue[str]" = queue.Queue(maxsize=1024)
        with self._l:
            backlog = list(self._ring)
            self._monitors.append(q)
        try:
            for line in backlog:
                yield line
            while stop_event is None or not stop_event.is_set():
                try:
                    yield q.get(timeout=0.5)
                except queue.Empty:
                    continue
        finally:
            with self._l:
                if q in self._monitors:
                    self._monitors.remove(q)
