"""Gossip keyring file management (<data_dir>/keyring.json).

Shared by the CLI keyring verb (cli/commands.py) and the agent HTTP
surface (/v1/agent/keyring/<op>, command/agent/http.go:158 +
agent_endpoint.go:166 KeyringOperationRequest).  Key semantics mirror
serf's keyring management: install adds a key (first install becomes
primary), use re-points the primary, remove refuses to drop the primary.
Keys are 32 bytes of base64; the wire encryption itself is a transport
concern (the reference's serf encrypt option).
"""
from __future__ import annotations

import base64
import contextlib
import json
import os
import threading
from typing import Dict

# The agent HTTP server is threaded; every mutation is a
# load→mutate→save round, so serialize them process-wide...
_LOCK = threading.Lock()


@contextlib.contextmanager
def _ring_lock(data_dir: str):
    """...and across processes: the CLI's file mode mutates the same
    keyring.json a live agent serves, so a thread lock alone still
    loses updates.  fcntl.flock on a sidecar lockfile covers both."""
    with _LOCK:
        os.makedirs(data_dir or ".", exist_ok=True)
        lockfile = keyring_path(data_dir) + ".lock"
        fh = open(lockfile, "a")
        try:
            try:
                import fcntl
                fcntl.flock(fh.fileno(), fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # non-posix: thread lock only
            yield
        finally:
            fh.close()


class KeyringError(ValueError):
    pass


def keyring_path(data_dir: str) -> str:
    return os.path.join(data_dir or ".", "keyring.json")


def load(data_dir: str) -> Dict:
    path = keyring_path(data_dir)
    if os.path.exists(path):
        with open(path) as fh:
            return json.load(fh)
    return {"Keys": [], "Primary": ""}


def save(data_dir: str, ring: Dict) -> None:
    os.makedirs(data_dir or ".", exist_ok=True)
    path = keyring_path(data_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(ring, fh, indent=2)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def validate_key(key: str) -> None:
    try:
        if len(base64.b64decode(key)) != 32:
            raise ValueError
    except Exception:
        raise KeyringError("key must be 32 bytes of base64") from None


def list_keys(data_dir: str) -> Dict:
    ring = load(data_dir)
    return {"Keys": list(ring["Keys"]), "Primary": ring["Primary"]}


def install(data_dir: str, key: str) -> None:
    validate_key(key)
    with _ring_lock(data_dir):
        ring = load(data_dir)
        if key not in ring["Keys"]:
            ring["Keys"].append(key)
        if not ring["Primary"]:
            ring["Primary"] = key
        save(data_dir, ring)


def use(data_dir: str, key: str) -> None:
    validate_key(key)
    with _ring_lock(data_dir):
        ring = load(data_dir)
        if key not in ring["Keys"]:
            raise KeyringError("key is not in the keyring")
        ring["Primary"] = key
        save(data_dir, ring)


def remove(data_dir: str, key: str) -> None:
    validate_key(key)
    with _ring_lock(data_dir):
        ring = load(data_dir)
        if key == ring["Primary"]:
            raise KeyringError("cannot remove the primary key")
        if key in ring["Keys"]:
            ring["Keys"].remove(key)
            save(data_dir, ring)


def key_response(data_dir: str) -> Dict:
    """The serf.KeyResponse shape the reference endpoint returns
    (agent_endpoint.go:205-215): per-key node counts — a single-process
    keyring reports one node."""
    ring = load(data_dir)
    return {
        "Messages": {},
        "NumNodes": 1,
        "Keys": {k: 1 for k in ring["Keys"]},
        "PrimaryKeys": ({ring["Primary"]: 1} if ring["Primary"] else {}),
    }
