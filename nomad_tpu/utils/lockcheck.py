"""Runtime lock-order sanitizer (``NOMAD_TPU_LOCKCHECK=1``).

The repo's worst bugs have been lock-shaped: fsync held under the raft
log lock (PR 9), the FileLog snapshot sequencer drained under the log
lock (PR 10).  The static pass (``nomad_tpu/analysis``) catches those
shapes at lint time from the source; this module catches the dynamic
ones — the lock-order inversions that only exist across modules at
runtime — with the same disarmed-by-default discipline as ``fault.py``:

- **Disarmed** (the default and the only production state) nothing is
  patched and nothing is tracked; an already-created tracked lock costs
  ONE module-global load + ``None`` check per operation.
- **Armed** (:func:`arm`, or ``NOMAD_TPU_LOCKCHECK=1`` at package
  import) ``threading.Lock``/``threading.RLock`` construction from
  nomad_tpu code returns a :class:`TrackedLock` wrapper.  Each wrapper
  is named by its creation site; every acquisition records
  ``held → acquired`` edges into a process-wide lock-order graph, and
  ``time.sleep``/``os.fsync`` under any tracked lock is recorded as a
  held-lock blocking call.
- **Teardown** (:func:`assert_acyclic`, armed for chaos/cluster tests
  in conftest) asserts the accumulated graph has no cycle and prints
  the witness chain — which thread took which edge at which source
  line — when it does.

Locks created by foreign code (stdlib, jax) get the real primitive:
the constructor patch inspects the caller and only wraps construction
reached from a ``nomad_tpu`` source file, so the graph never carries
noise edges from library internals.

Contention ledger (ISSUE 19): while armed, every tracked acquisition
also measures how long the acquire blocked (two ``perf_counter`` reads
around the inner acquire — always cheap) into a process-wide per-name
wait ledger.  :func:`wait_stats` ranks the contended locks; the
continuous profiler (``utils/contprof.py``) exports them as
``nomad.lock.<name>.wait_seconds`` histograms and the loadgen report's
``host_attribution`` section names the top five per leg.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderError", "arm", "disarm", "armed", "maybe_arm_from_env",
    "assert_acyclic", "find_cycle", "cycle_in_edges", "edges",
    "blocking_calls", "reset", "wait_stats", "reset_waits",
    "held_tracked", "TrackedLock", "make_tracked",
]


class LockOrderError(AssertionError):
    """The lock-order graph acquired a cycle; the message carries the
    witness chain (edge, thread, acquire sites)."""


class _State:
    """Everything the armed sanitizer tracks.  One instance per arm();
    the module global being ``None`` IS the disarmed fast path."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # (src_name, dst_name) -> witness: (thread, src_site, dst_site)
        self.edges: Dict[Tuple[str, str], Tuple[str, str, str]] = {}
        # (lock_name, blocking_kind, site) records, bounded.
        self.blocking: List[Tuple[str, str, str]] = []
        self.local = threading.local()

    def held(self) -> List["TrackedLock"]:
        stack = getattr(self.local, "stack", None)
        if stack is None:
            stack = self.local.stack = []
        return stack


_STATE: Optional[_State] = None

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = time.sleep
_REAL_FSYNC = os.fsync

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MAX_BLOCKING_RECORDS = 1024

# -- contention ledger --------------------------------------------------------
# Per-name wait aggregates shared by every TrackedLock instance created
# at the same source line (two servers in one process contend the same
# code path).  The registry and each aggregate use RAW locks so the
# ledger itself never grows graph edges.

WAIT_RING = 512
MAX_WAIT_NAMES = 4096


class _WaitStats:
    """Wait-time aggregate for one lock name: count/sum/max plus a
    bounded ring of raw waits for exact small-N percentiles."""

    __slots__ = ("name", "count", "total_s", "max_s", "ring", "_l")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.ring: deque = deque(maxlen=WAIT_RING)
        self._l = _REAL_LOCK()

    def add(self, wait_s: float) -> None:
        with self._l:
            self.count += 1
            self.total_s += wait_s
            if wait_s > self.max_s:
                self.max_s = wait_s
            self.ring.append(wait_s)

    def clear(self) -> None:
        with self._l:
            self.count = 0
            self.total_s = 0.0
            self.max_s = 0.0
            self.ring.clear()

    def summary(self) -> Dict:
        with self._l:
            vals = sorted(self.ring)
            count, total, mx = self.count, self.total_s, self.max_s

        def pct(q: float) -> float:
            if not vals:
                return 0.0
            return vals[min(len(vals) - 1, int(q * len(vals)))]

        return {
            "name": self.name,
            "count": count,
            "wait_s_sum": round(total, 6),
            "wait_s_max": round(mx, 6),
            "p50_ms": round(pct(0.50) * 1000.0, 4),
            "p95_ms": round(pct(0.95) * 1000.0, 4),
            "p99_ms": round(pct(0.99) * 1000.0, 4),
        }


_WAITS: Dict[str, _WaitStats] = {}
_WAITS_L = _REAL_LOCK()


def _wait_stats_for(name: str) -> _WaitStats:
    with _WAITS_L:
        ws = _WAITS.get(name)
        if ws is None:
            if len(_WAITS) >= MAX_WAIT_NAMES:
                name = "<overflow>"
                ws = _WAITS.get(name)
                if ws is None:
                    ws = _WAITS[name] = _WaitStats(name)
            else:
                ws = _WAITS[name] = _WaitStats(name)
        return ws


def wait_stats(top: Optional[int] = None) -> List[Dict]:
    """Contended-lock ranking: per-name wait summaries sorted by total
    blocked seconds, the names with zero recorded waits elided."""
    with _WAITS_L:
        stats = list(_WAITS.values())
    out = [ws.summary() for ws in stats]
    out = [o for o in out if o["count"]]
    out.sort(key=lambda o: (-o["wait_s_sum"], o["name"]))
    return out[:top] if top else out


def reset_waits() -> None:
    """Zero the ledger in place (per-leg snapshots).  Aggregates are
    cleared, not dropped: live TrackedLocks hold direct references."""
    with _WAITS_L:
        stats = list(_WAITS.values())
    for ws in stats:
        ws.clear()


_SELF_FILE = os.path.abspath(__file__).rstrip("co")  # .py for .pyc


def _caller_site(depth: int = 2) -> str:
    """First frame outside this module (the with-statement protocol
    routes __enter__ → acquire, which would otherwise be the site)."""
    d = depth
    while True:
        try:
            frame = sys._getframe(d)
        except ValueError:
            frame = sys._getframe(d - 1)
            break
        if not frame.f_code.co_filename.startswith(_SELF_FILE):
            break
        d += 1
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


def _from_nomad(depth: int = 2, limit: int = 4) -> Optional[str]:
    """Walk up to ``limit`` frames looking for a nomad_tpu source file;
    returns its site string (the lock's name) or None.  The walk covers
    one level of stdlib indirection (``threading.Condition()`` creating
    its RLock) without adopting library-internal locks."""
    for d in range(depth, depth + limit):
        try:
            frame = sys._getframe(d)
        except ValueError:
            return None
        fn = frame.f_code.co_filename
        if fn.startswith(_PKG_DIR):
            if os.sep + "utils" + os.sep + "lockcheck" in fn:
                continue
            return f"{os.path.relpath(fn, _PKG_DIR)}:{frame.f_lineno}"
        # threading.py internals are transparent; anything else foreign
        # (site-packages, stdlib beyond threading) means a foreign lock.
        if not fn.endswith("threading.py"):
            return None
    return None


class TrackedLock:
    """Wrapper over a real Lock/RLock recording acquisition order.
    After :func:`disarm`, live wrappers keep working at one global load
    per operation (``_STATE is None`` short-circuit)."""

    __slots__ = ("_inner", "name", "_rlock", "_count", "_owner_stack",
                 "_wait")

    def __init__(self, inner, name: str, rlock: bool):
        self._inner = inner
        self.name = name
        self._rlock = rlock
        self._count = 0  # recursion depth, tracking thread only
        self._owner_stack = None  # held-stack list the entry lives on
        self._wait = None  # per-name _WaitStats, resolved lazily

    # -- tracking ----------------------------------------------------------

    def _note_acquired(self, site: str) -> None:
        st = _STATE
        if st is None:
            return
        stack = st.held()
        if self._rlock and any(t is self for t in stack):
            self._count += 1
            return
        for held in stack:
            if held is self:
                continue
            if held.name == self.name:
                # Distinct instances created at the same source line
                # (two servers in one process) share a name; an edge
                # name→name would be a guaranteed-false 1-cycle.
                continue
            key = (held.name, self.name)
            if key not in st.edges:
                with st.lock:
                    if key not in st.edges:
                        st.edges[key] = (
                            threading.current_thread().name,
                            held.name, site)
        self._count = 1
        stack.append(self)
        self._owner_stack = stack

    def _note_released(self, full: bool = False) -> None:
        st = _STATE
        if st is None:
            return
        stack = st.held()
        if (not full and self._rlock and self in stack
                and self._count > 1):
            self._count -= 1
            return
        self._count = 0
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                self._owner_stack = None
                return
        # Released by a thread that didn't acquire it (legal for plain
        # Locks used as signals): clear the entry from the acquiring
        # thread's stack so it doesn't poison that thread's edges
        # forever.  list.remove is GIL-atomic, good enough for a
        # sanitizer's bookkeeping.
        owner = self._owner_stack
        if owner is not None:
            try:
                owner.remove(self)
            except ValueError:
                pass
            self._owner_stack = None

    # -- the lock protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _STATE is None:
            return self._inner.acquire(blocking, timeout)
        t0 = time.perf_counter()
        got = self._inner.acquire(blocking, timeout)
        if got:
            wait = time.perf_counter() - t0
            ws = self._wait
            if ws is None:
                ws = self._wait = _wait_stats_for(self.name)
            ws.add(wait)
            self._note_acquired(_caller_site())
        return got

    def release(self) -> None:
        if _STATE is not None:
            self._note_released()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition integration ---------------------------------------------
    # Condition snapshots these at construction; wait() releases the
    # lock through _release_save and reacquires through
    # _acquire_restore, so the held stack must follow.

    def _release_save(self):
        # Condition.wait fully releases the lock whatever its recursion
        # depth — drop the whole stack entry, not one level.  The
        # wrapper's depth rides the saved state so _acquire_restore can
        # resync it with the inner lock's restored recursion count
        # (otherwise a wait at depth >1 leaves the wrapper one level
        # shallow and the first release() silently empties the stack
        # while the inner lock is still held).
        depth = self._count
        if _STATE is not None:
            self._note_released(full=True)
        if hasattr(self._inner, "_release_save"):
            return (depth, self._inner._release_save())
        self._inner.release()
        return (depth, None)

    def _acquire_restore(self, state) -> None:
        depth, inner_state = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        if _STATE is not None:
            self._note_acquired(_caller_site())
            if self._rlock and depth > 1:
                self._count = depth

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _at_fork_reinit(self) -> None:  # pragma: no cover — fork safety
        self._inner._at_fork_reinit()

    def __repr__(self) -> str:
        return f"<TrackedLock {self.name} rlock={self._rlock}>"


def make_tracked(name: str, rlock: bool = False) -> "TrackedLock":
    """Explicitly instrumented lock regardless of caller location —
    for tests and selfcheck drills that exercise the sanitizer from
    outside the nomad_tpu tree.  Works disarmed too (one global load
    per op, nothing recorded)."""
    return TrackedLock(_REAL_RLOCK() if rlock else _REAL_LOCK(),
                       name, rlock=rlock)


def _make_lock():
    inner = _REAL_LOCK()
    if _STATE is None:
        return inner
    site = _from_nomad()
    if site is None:
        return inner
    return TrackedLock(inner, site, rlock=False)


def _make_rlock():
    inner = _REAL_RLOCK()
    if _STATE is None:
        return inner
    site = _from_nomad()
    if site is None:
        return inner
    return TrackedLock(inner, site, rlock=True)


def _checked_sleep(secs):
    st = _STATE
    if st is not None:
        held = st.held()
        if held and len(st.blocking) < MAX_BLOCKING_RECORDS:
            site = _caller_site()
            with st.lock:
                st.blocking.append((held[-1].name, "time.sleep", site))
    return _REAL_SLEEP(secs)


def _checked_fsync(fd):
    st = _STATE
    if st is not None:
        held = st.held()
        if held and len(st.blocking) < MAX_BLOCKING_RECORDS:
            site = _caller_site()
            with st.lock:
                st.blocking.append((held[-1].name, "os.fsync", site))
    return _REAL_FSYNC(fd)


# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------


def arm() -> None:
    """Patch lock construction + the blocking primitives.  Idempotent."""
    global _STATE
    if _STATE is not None:
        return
    _STATE = _State()
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    time.sleep = _checked_sleep
    os.fsync = _checked_fsync


def disarm() -> None:
    """Restore the real primitives.  Live TrackedLocks keep delegating
    (one global load per op) so locks created while armed stay valid."""
    global _STATE
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    time.sleep = _REAL_SLEEP
    os.fsync = _REAL_FSYNC
    _STATE = None


def armed() -> bool:
    return _STATE is not None


def maybe_arm_from_env() -> bool:
    """Arm when NOMAD_TPU_LOCKCHECK=1 — called at package import so
    subprocess servers (bench children, loadgen followers) inherit the
    sanitizer from the environment."""
    from . import knobs

    if knobs.get_bool("NOMAD_TPU_LOCKCHECK"):
        arm()
        return True
    return False


def reset() -> None:
    """Clear accumulated edges/records without disarming (per-test)."""
    st = _STATE
    if st is not None:
        with st.lock:
            st.edges.clear()
            del st.blocking[:]


# ---------------------------------------------------------------------------
# inspection / teardown assertions
# ---------------------------------------------------------------------------


def edges() -> Dict[Tuple[str, str], Tuple[str, str, str]]:
    st = _STATE
    if st is None:
        return {}
    with st.lock:
        return dict(st.edges)


def blocking_calls() -> List[Tuple[str, str, str]]:
    st = _STATE
    if st is None:
        return []
    with st.lock:
        return list(st.blocking)


def held_tracked() -> List[str]:
    """Names of tracked locks held by the calling thread (tests)."""
    st = _STATE
    if st is None:
        return []
    return [t.name for t in st.held()]


def cycle_in_edges(edge_keys) -> Optional[List[Tuple[str, str]]]:
    """First cycle in a set of ``(src, dst)`` edges as the list of
    edges along it, or None.  Iterative DFS with an explicit stack (no
    recursion limit on long chains); neighbors visited in sorted order
    for a deterministic witness.  Shared by the runtime sanitizer and
    the static lock-order rule (``analysis/lockrules``)."""
    graph: Dict[str, List[str]] = {}
    for (a, b) in edge_keys:
        graph.setdefault(a, []).append(b)
    for adj in graph.values():
        adj.sort()
    visited: Set[str] = set()
    for root in sorted(graph):
        if root in visited:
            continue
        visited.add(root)
        stack = [(root, iter(graph.get(root, ())))]
        on_path: List[str] = [root]
        on_path_set: Set[str] = {root}
        while stack:
            _node, it = stack[-1]
            descended = False
            for nxt in it:
                if nxt in on_path_set:
                    start = on_path.index(nxt)
                    chain = on_path[start:] + [nxt]
                    return list(zip(chain, chain[1:]))
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, iter(graph.get(nxt, ()))))
                    on_path.append(nxt)
                    on_path_set.add(nxt)
                    descended = True
                    break
            if not descended:
                stack.pop()
                on_path_set.discard(on_path.pop())
    return None


def find_cycle() -> Optional[List[Tuple[str, str]]]:
    """First cycle in the accumulated lock-order graph, or None."""
    return cycle_in_edges(edges())


def witness(cycle: List[Tuple[str, str]]) -> str:
    """Human-readable witness chain for a cycle from find_cycle()."""
    all_edges = edges()
    lines = ["lock-order cycle:"]
    for (a, b) in cycle:
        thread, _src, dst_site = all_edges.get(
            (a, b), ("?", a, "?"))
        lines.append(f"  {a} -> {b}  (thread {thread}, "
                     f"acquired at {dst_site})")
    return "\n".join(lines)


def assert_acyclic() -> None:
    """Raise LockOrderError (with the witness chain) if the graph has a
    cycle.  The chaos/cluster conftest teardown calls this."""
    cycle = find_cycle()
    if cycle is not None:
        msg = witness(cycle)
        print(msg, file=sys.stderr)
        # A runtime lock-order cycle is a flight-recorder incident:
        # capture the forensic bundle before the assertion unwinds the
        # process state.  Late import — blackbox reads this module's
        # ledger back.
        from . import blackbox
        blackbox.note_trigger("lockcheck.cycle", {"witness": msg})
        raise LockOrderError(msg)
