"""Device-platform identification shared by fingerprints and kernels."""


def is_tpu_platform(platform: str) -> bool:
    """Whether a jax device platform string is a TPU. The real chip in
    this environment registers through the experimental 'axon' PJRT
    plugin rather than as 'tpu'; both compile through Mosaic."""
    return platform in ("tpu", "axon")


def virtual_mesh_env(n_devices: int, base_env=None) -> dict:
    """Subprocess environment that provisions an ``n_devices`` virtual
    CPU mesh: any pre-existing forced-device-count flag is stripped
    from XLA_FLAGS (it may be lower than needed), exactly ``n_devices``
    is pinned, and the platform is forced to CPU.  XLA reads the flag
    at backend init, so this only works for a FRESH interpreter — the
    one shared recipe behind the selfcheck mesh drill, bench
    config_mesh, and the driver dryrun (tests/conftest.py inlines a
    variant because it must run before any import).

    Note: environments that pre-import jax pin the platform at
    interpreter startup; the child must still call
    ``jax.config.update('jax_platforms', 'cpu')`` (see
    __graft_entry__).
    """
    import os
    import re

    env = dict(base_env if base_env is not None else os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags.strip() +
                        f" --xla_force_host_platform_device_count="
                        f"{n_devices}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    return env
