"""Device-platform identification shared by fingerprints and kernels."""


def is_tpu_platform(platform: str) -> bool:
    """Whether a jax device platform string is a TPU. The real chip in
    this environment registers through the experimental 'axon' PJRT
    plugin rather than as 'tpu'; both compile through Mosaic."""
    return platform in ("tpu", "axon")
