"""Runtime profiling: the pprof-equivalent debug surface plus JAX device
tracing.

The reference mounts net/http/pprof under /debug/pprof when enableDebug
is set (command/agent/http.go:173-178) — CPU profiles, heap profiles, and
goroutine stacks.  The equivalents here:

- profile:   sampling profiler over a bounded window — stacks of EVERY
             live thread sampled at ~200Hz and aggregated (pprof's CPU
             profile is also a sampler; a cProfile hook would only see
             the handler's own thread).
- heap:      tracemalloc top allocation sites (started lazily on first
             request; subsequent requests diff against a live tracer).
- threads:   stack dump of every live thread (goroutine-dump analogue).
- trace:     jax.profiler device trace written to a directory for
             TensorBoard/XProf — the device-side replacement for pprof
             the SURVEY calls for ("JAX profiler + XLA HLO dumps replace
             pprof for device side", SURVEY.md §5).

All captures are bounded and lock-free with respect to the runtime: the
CPU profiler uses the interpreter's global profile hook for its window;
heap/threads are point-in-time snapshots.
"""
from __future__ import annotations

import io
import sys
import threading
import time
import traceback
from typing import Dict, Optional

_profile_lock = threading.Lock()


def cpu_profile(seconds: float = 1.0, sort: str = "cumulative",
                top: int = 60, hz: float = 200.0) -> str:
    """Sample every live thread's stack for ``seconds`` and render an
    aggregated report: per-frame inclusive/leaf sample counts across ALL
    threads (cProfile's hook is per-thread — it would only ever see this
    handler sleeping).  Serialized by a module lock so concurrent profile
    requests don't double the sampling load."""
    seconds = max(0.05, min(float(seconds), 30.0))
    interval = 1.0 / max(1.0, min(hz, 1000.0))
    if not _profile_lock.acquire(timeout=0.1):
        raise RuntimeError("another cpu profile is in progress")
    try:
        me = threading.get_ident()
        inclusive: dict = {}
        leaf: dict = {}
        samples = 0
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                depth = 0
                f = frame
                first = True
                while f is not None and depth < 64:
                    code = f.f_code
                    # co_qualname is 3.11+; co_name on older runtimes
                    key = (code.co_filename, code.co_firstlineno,
                           getattr(code, "co_qualname", code.co_name))
                    inclusive[key] = inclusive.get(key, 0) + 1
                    if first:
                        leaf[key] = leaf.get(key, 0) + 1
                        first = False
                    f = f.f_back
                    depth += 1
            samples += 1
            time.sleep(interval)
        out = io.StringIO()
        out.write(f"{samples} samples over {seconds:.2f}s "
                  f"({len(inclusive)} function calls observed)\n\n")
        out.write(f"{'incl':>8} {'leaf':>8}  function\n")
        ranked = sorted(inclusive.items(),
                        key=lambda kv: -(leaf.get(kv[0], 0) if sort == "leaf"
                                         else kv[1]))
        for key, n in ranked[:top]:
            fname, lineno, qual = key
            out.write(f"{n:>8} {leaf.get(key, 0):>8}  "
                      f"{qual} ({fname}:{lineno})\n")
        return out.getvalue()
    finally:
        _profile_lock.release()


_heap_started = False


def heap_profile(top: int = 40) -> Dict:
    """tracemalloc snapshot of the top allocation sites.

    The tracer is started on the first request (like pprof's heap
    profile, which is always-on in Go; Python's tracer costs ~2x alloc
    overhead, so it's opt-in via first use of this endpoint)."""
    global _heap_started
    import tracemalloc

    if not _heap_started:
        tracemalloc.start(10)
        _heap_started = True
        return {"status": "tracer started; re-request for data"}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    current, peak = tracemalloc.get_traced_memory()
    return {
        "current_bytes": current,
        "peak_bytes": peak,
        "top": [
            {
                "site": str(st.traceback[0]) if st.traceback else "?",
                "size_bytes": st.size,
                "count": st.count,
            }
            for st in stats
        ],
    }


def thread_dump() -> str:
    """Stack trace of every live thread — the goroutine-dump analogue
    (pprof /debug/pprof/goroutine?debug=2)."""
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = io.StringIO()
    for tid, frame in sorted(frames.items()):
        t = by_id.get(tid)
        name = t.name if t is not None else "?"
        daemon = " daemon" if (t is not None and t.daemon) else ""
        out.write(f"thread {tid} [{name}]{daemon}:\n")
        traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue()


class DeviceTracer:
    """Bounded jax.profiler trace sessions (device-side profiling).

    One active trace at a time; the trace directory is returned so the
    operator can pull it into TensorBoard/XProf."""

    def __init__(self, base_dir: Optional[str] = None):
        import os
        import tempfile

        self.base_dir = base_dir or os.path.join(
            tempfile.gettempdir(), "nomad_tpu_traces")
        self._lock = threading.Lock()
        self._active_dir: Optional[str] = None
        self._started_at = 0.0

    def start(self) -> str:
        import os

        import jax

        with self._lock:
            if self._active_dir is not None:
                raise RuntimeError(
                    f"trace already active in {self._active_dir}")
            d = os.path.join(self.base_dir, time.strftime("%Y%m%d-%H%M%S"))
            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
            self._active_dir = d
            self._started_at = time.monotonic()
            return d

    def stop(self) -> Dict:
        import jax

        with self._lock:
            if self._active_dir is None:
                raise RuntimeError("no active trace")
            jax.profiler.stop_trace()
            d, self._active_dir = self._active_dir, None
            return {"dir": d,
                    "duration_s": round(time.monotonic() - self._started_at,
                                        3)}

    def capture(self, seconds: float = 1.0) -> Dict:
        """start → sleep → stop in one bounded call (the /trace?seconds=N
        endpoint shape)."""
        seconds = max(0.05, min(float(seconds), 30.0))
        d = self.start()
        try:
            time.sleep(seconds)
        finally:
            info = self.stop()
        info["dir"] = d
        return info


_tracer_lock = threading.Lock()
_tracer: Optional[DeviceTracer] = None


def get_tracer() -> DeviceTracer:
    """Process-wide tracer singleton: the jax profiler is process-global,
    so two DeviceTracer instances started concurrently would corrupt each
    other's sessions."""
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = DeviceTracer()
        return _tracer
