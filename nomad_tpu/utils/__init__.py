"""Shared helpers (reference: helper/)."""
