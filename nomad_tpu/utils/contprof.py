"""Continuous host-attribution profiler (``NOMAD_TPU_CONTPROF=1``).

The control plane's scaling story is host-bound (BENCH_r08: the M=4
multi-worker speedup collapsed to ~1x under a GIL-saturated host), but
nothing in the repo could say *where* host time goes.  This module is
the measurement plane: a background sampler at low Hz walks
``sys._current_frames()`` and classifies every thread's stack into a
fixed subsystem taxonomy via a frame→subsystem map derived from module
paths, maintaining rolling per-subsystem CPU-share gauges
(``nomad.cpu.<subsystem>``).  Three consumers:

- the server metrics emitter exports the shares through each server's
  telemetry sink (so ``/v1/metrics?format=prometheus`` and
  ``Status.Metrics`` carry them);
- ``/v1/profile/continuous`` serves a bounded recent window
  (:func:`window`);
- the loadgen harness snapshots a per-leg ``host_attribution`` report
  section (:func:`host_attribution`), which ``bench --check`` gates on
  (≥80% of non-idle samples attributed, <3% armed overhead).

Two riders share the plane's arming story:

- **GIL-pressure probe**: a sentinel thread requests a short sleep and
  measures the scheduling delay beyond it — the standard CPython
  GIL-saturation estimator.  p50/p99 of the delay are the
  ``gil_pressure`` numbers per loadgen leg.
- **Contention ledger** (``utils/lockcheck.py``): wait-time histograms
  per tracked lock, merged into the metrics surfaces here
  (``nomad.lock.<name>.wait_seconds``).

Cost discipline (the ``fault.py`` contract): disarmed (the default and
the only production state) the module global ``PROFILER`` is ``None``
and nothing samples; there are no instrumented call sites, so the
disarmed cost is literally zero.  Arm with :func:`enable`, or
``NOMAD_TPU_CONTPROF=1`` read at server construction.
"""
from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import knobs, lockcheck
from .lockcheck import _REAL_LOCK as _RAW_LOCK

__all__ = [
    "SUBSYSTEMS", "classify_frames", "ContinuousProfiler", "PROFILER",
    "enable", "disable", "enabled", "maybe_arm_from_env", "window",
    "shares", "host_attribution", "merge_metrics", "reset",
]

# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

#: The fixed subsystem taxonomy.  Every sampled stack maps to exactly
#: one of these; ``other`` is the attribution failure bucket the
#: coverage gate (≥80% of non-idle samples NOT other) watches.
SUBSYSTEMS = (
    "codec.encode", "codec.decode", "raft.apply", "plan.evaluate",
    "plan.apply", "broker", "worker.snapshot", "ops.dispatch",
    "ops.fetch", "http", "federation", "loadgen", "idle", "other",
)

# Leaf-frame idle markers: a thread whose leaf frame is a stdlib
# blocking wrapper is waiting, not burning CPU.  (C-level waits —
# lock.acquire, socket.recv — sample as their innermost *Python*
# caller, which for the common paths below is a stdlib wrapper.)
_IDLE_FILES = ("/selectors.py", "/socketserver.py", "/socket.py",
               "/ssl.py", "/subprocess.py")
_IDLE_THREADING_FUNCS = frozenset((
    "wait", "_wait_for_tstate_lock", "join"))


def _is_idle_leaf(path: str, func: str) -> bool:
    if path.endswith("/threading.py"):
        return func in _IDLE_THREADING_FUNCS
    for frag in _IDLE_FILES:
        if path.endswith(frag):
            return True
    # The sanitizer's patched time.sleep: the sleeping caller's leaf
    # frame while lockcheck is armed.
    if path.endswith("/lockcheck.py") and func == "_checked_sleep":
        return True
    # time.sleep leaves the CALLER as the leaf frame; known poll loops
    # that pace with a bare sleep would otherwise bill their sleep as
    # CPU.  The heartbeat sweeper is the big one (wakes up to 100×/s).
    if path.endswith("/server/heartbeat.py") and func == "_sweep":
        return True
    # Our own GIL probe spends its life inside its sleep loop.
    if path.endswith("/contprof.py"):
        return True
    return False


def _frame_subsystem(path: str, func: str) -> Optional[str]:
    """Map ONE nomad_tpu frame to a subsystem, or None when the frame
    is transparent (helper layers: state/structs/utils) or foreign.
    ``path`` is '/'-normalized, ``func`` the code object name."""
    if "nomad_tpu/" not in path:
        return None
    fl = func.lower()
    if "/codec/" in path:
        if "unpack" in fl or "decode" in fl or "sniff" in fl \
                or "from_wire" in fl:
            return "codec.decode"
        return "codec.encode"
    if path.endswith("/ops/decode.py"):
        return "codec.decode"
    if path.endswith("/ops/encode.py"):
        return "ops.dispatch"
    if path.endswith("/ops/batch_sched.py"):
        if "fetch" in fl:
            return "ops.fetch"
        if "dispatch" in fl:
            return "ops.dispatch"
        return "plan.evaluate"
    if path.endswith(("/ops/kernels.py", "/ops/xfer.py",
                      "/ops/resident.py", "/ops/pallas_score.py")):
        return "ops.fetch" if "fetch" in fl or "unpack" in fl \
            else "ops.dispatch"
    if "/ops/" in path:
        return "plan.evaluate"
    if path.endswith(("/server/raft.py", "/server/fsm.py",
                      "/server/log_codec.py")):
        return "raft.apply"
    if path.endswith("/server/plan_apply.py"):
        return "plan.evaluate" if "evaluate" in fl else "plan.apply"
    if path.endswith(("/server/plan_queue.py",
                      "/server/follower_sched.py")):
        return "plan.apply"
    if path.endswith(("/server/eval_broker.py",
                      "/server/blocked_evals.py",
                      "/server/event_broker.py",
                      "/server/heartbeat.py")) or "/tenancy/" in path:
        return "broker"
    if path.endswith("/server/worker.py"):
        return "worker.snapshot" if "snapshot" in fl \
            else "plan.evaluate"
    if "/scheduler/" in path:
        return "plan.evaluate"
    if "federation" in path and ("/server/" in path
                                 or "/loadgen/" in path):
        return "federation"
    if path.endswith("/server/rpc.py") or "/agent/" in path \
            or "/api/" in path or path.endswith("/server/endpoints.py"):
        return "http"
    if "/loadgen/" in path:
        return "loadgen"
    return None


def classify_frames(frames: Sequence[Tuple[str, str]]) -> str:
    """Classify one thread's stack — ``frames`` is leaf-first
    ``(filename, funcname)`` pairs — into a subsystem.  The leaf is
    checked for stdlib idle markers first; otherwise the leaf-most
    frame with a subsystem mapping wins (that is where CPU burns);
    stacks mapping nowhere are ``other``."""
    if not frames:
        return "other"
    path0 = frames[0][0].replace("\\", "/")
    if _is_idle_leaf(path0, frames[0][1]):
        return "idle"
    for fname, func in frames:
        sub = _frame_subsystem(fname.replace("\\", "/"), func)
        if sub is not None:
            return sub
    return "other"


def _pct(ordered: List[float], q: float) -> float:
    if not ordered:
        return 0.0
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


# ---------------------------------------------------------------------------
# the profiler
# ---------------------------------------------------------------------------

WINDOW_S = 5.0
MAX_STACK_DEPTH = 48
GIL_RING = 65536


class ContinuousProfiler:
    """Background low-Hz stack sampler + GIL-pressure probe over a
    bounded ring of aggregation windows."""

    def __init__(self, hz: Optional[float] = None,
                 window_s: float = WINDOW_S,
                 retain: Optional[int] = None,
                 gil_ms: Optional[float] = None):
        if hz is None:
            hz = knobs.get_float("NOMAD_TPU_CONTPROF_HZ", 10.0)
        self.hz = max(1.0, min(float(hz or 10.0), 100.0))
        self.window_s = max(1.0, float(window_s))
        if retain is None:
            retain = knobs.get_int("NOMAD_TPU_CONTPROF_RING", 120)
        if gil_ms is None:
            gil_ms = knobs.get_float("NOMAD_TPU_CONTPROF_GIL_MS", 5.0)
        self.gil_ms = max(0.0, float(gil_ms or 0.0))
        # A RAW lock: the profiler must not feed its own bookkeeping
        # into the lock-order graph or the contention ledger.
        self._l = _RAW_LOCK()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._own_idents: set = set()
        # Ring of closed windows: (wall_start, duration_s, counts).
        self._windows: deque = deque(maxlen=max(2, int(retain or 120)))
        self._cur: Dict[str, int] = {}
        self._cur_start = time.time()
        self._cur_mono = time.perf_counter()
        # Process-lifetime (since last reset) cumulative counts — the
        # loadgen per-leg attribution basis.
        self._cum: Dict[str, int] = {}
        self._cum_total = 0
        # GIL probe: scheduling-delay samples in ms, bounded.
        self._gil: deque = deque(maxlen=GIL_RING)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(target=self._sample_loop,
                             name="contprof-sampler", daemon=True)
        self._threads.append(t)
        if self.gil_ms > 0:
            g = threading.Thread(target=self._gil_loop,
                                 name="contprof-gil", daemon=True)
            self._threads.append(g)
        for th in self._threads:
            th.start()

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=2.0)

    # -- sampling ----------------------------------------------------------

    def _sample_once(self) -> None:
        frames = sys._current_frames()
        ticked: List[str] = []
        for tid, frame in frames.items():
            if tid in self._own_idents:
                continue
            stack: List[Tuple[str, str]] = []
            f = frame
            depth = 0
            while f is not None and depth < MAX_STACK_DEPTH:
                code = f.f_code
                stack.append((code.co_filename, code.co_name))
                f = f.f_back
                depth += 1
            ticked.append(classify_frames(stack))
        now_wall = time.time()
        now_mono = time.perf_counter()
        with self._l:
            for sub in ticked:
                self._cur[sub] = self._cur.get(sub, 0) + 1
                self._cum[sub] = self._cum.get(sub, 0) + 1
            self._cum_total += len(ticked)
            if now_mono - self._cur_mono >= self.window_s:
                self._windows.append(
                    (self._cur_start, now_mono - self._cur_mono,
                     self._cur))
                self._cur = {}
                self._cur_start = now_wall
                self._cur_mono = now_mono

    def _sample_loop(self) -> None:
        self._own_idents.add(threading.get_ident())
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self._sample_once()
            except Exception:  # pragma: no cover — never kill sampling
                pass

    def _gil_loop(self) -> None:
        self._own_idents.add(threading.get_ident())
        req_s = self.gil_ms / 1000.0
        while not self._stop.is_set():
            t0 = time.perf_counter()
            time.sleep(req_s)
            delay_ms = (time.perf_counter() - t0 - req_s) * 1000.0
            # deque.append is atomic under the GIL; no lock on the
            # probe's hot path.
            self._gil.append(max(0.0, delay_ms))

    # -- read side ---------------------------------------------------------

    def gil_pressure_ms(self, tail: Optional[int] = None) -> Dict:
        vals = list(self._gil)
        if tail is not None:
            vals = vals[-tail:] if tail > 0 else []
        ordered = sorted(vals)
        return {
            "count": len(ordered),
            "p50": round(_pct(ordered, 0.50), 4),
            "p95": round(_pct(ordered, 0.95), 4),
            "p99": round(_pct(ordered, 0.99), 4),
            "max": round(ordered[-1], 4) if ordered else 0.0,
        }

    def _recent_counts(self, seconds: float) -> Tuple[Dict[str, int],
                                                      float]:
        """Aggregate counts over the windows covering the last
        ``seconds``, plus the open window."""
        now_mono = time.perf_counter()
        with self._l:
            counts = dict(self._cur)
            covered = now_mono - self._cur_mono
            for _start, dur, wcounts in reversed(self._windows):
                if covered >= seconds:
                    break
                for k, v in wcounts.items():
                    counts[k] = counts.get(k, 0) + v
                covered += dur
        return counts, covered

    @staticmethod
    def _shares(counts: Dict[str, int]) -> Dict[str, float]:
        total = sum(counts.values())
        if not total:
            return {}
        return {k: round(v / total, 4)
                for k, v in sorted(counts.items(), key=lambda kv: -kv[1])}

    @staticmethod
    def _coverage(counts: Dict[str, int]) -> float:
        """Fraction of non-idle samples attributed to a real subsystem
        (1 - other/non_idle); 1.0 when nothing non-idle was sampled."""
        total = sum(counts.values())
        non_idle = total - counts.get("idle", 0)
        if non_idle <= 0:
            return 1.0
        return round(1.0 - counts.get("other", 0) / non_idle, 4)

    def shares(self, seconds: float = 30.0) -> Dict[str, float]:
        counts, _ = self._recent_counts(seconds)
        return self._shares(counts)

    def window(self, seconds: float = 60.0) -> Dict[str, Any]:
        """The /v1/profile/continuous payload: counts/shares/coverage
        over the recent window plus the GIL and lock riders."""
        seconds = max(1.0, min(float(seconds), 3600.0))
        counts, covered = self._recent_counts(seconds)
        return {
            "Enabled": True,
            "Hz": self.hz,
            "WindowS": self.window_s,
            "RequestedS": seconds,
            "CoveredS": round(min(covered, seconds), 2),
            "ThreadSamples": sum(counts.values()),
            "Counts": dict(counts),
            "Shares": self._shares(counts),
            "NonIdleCoverage": self._coverage(counts),
            "GilDelayMs": self.gil_pressure_ms(),
            "Locks": lockcheck.wait_stats(top=10),
        }

    def host_attribution(self, top_locks: int = 5,
                         top_subsystems: int = 5) -> Dict[str, Any]:
        """The loadgen report section: attribution since the last
        :meth:`reset` (the harness resets at leg start)."""
        with self._l:
            counts = dict(self._cum)
            for k, v in self._cur.items():
                counts[k] = counts.get(k, 0) + v
        shares_ = self._shares(counts)
        top = [[k, v] for k, v in shares_.items()
               if k not in ("idle",)][:top_subsystems]
        return {
            "enabled": True,
            "hz": self.hz,
            "thread_samples": sum(counts.values()),
            "shares": shares_,
            "non_idle_coverage": self._coverage(counts),
            "top_subsystems": top,
            "top_locks": lockcheck.wait_stats(top=top_locks),
            "gil_pressure_ms": self.gil_pressure_ms(),
        }

    def reset(self) -> None:
        """Zero the cumulative attribution + GIL samples (per-leg
        snapshots).  The open window restarts too — its counts feed
        host_attribution() — but the closed-window ring is left alone;
        it is the operator surface, not the leg accounting."""
        with self._l:
            self._cum = {}
            self._cum_total = 0
            self._cur = {}
            self._cur_start = time.time()
            self._cur_mono = time.perf_counter()
        self._gil.clear()


# ---------------------------------------------------------------------------
# process-wide arming (fault.py discipline: None ⇒ disarmed)
# ---------------------------------------------------------------------------

PROFILER: Optional[ContinuousProfiler] = None


def enable(hz: Optional[float] = None,
           gil_ms: Optional[float] = None) -> ContinuousProfiler:
    global PROFILER
    if PROFILER is not None:
        return PROFILER
    p = ContinuousProfiler(hz=hz, gil_ms=gil_ms)
    p.start()
    PROFILER = p
    return p


def disable() -> None:
    global PROFILER
    p, PROFILER = PROFILER, None
    if p is not None:
        p.stop()


def enabled() -> bool:
    return PROFILER is not None


def maybe_arm_from_env() -> bool:
    """Arm when NOMAD_TPU_CONTPROF=1 — called at server construction
    (like the tracing plane) so bench children and loadgen followers
    inherit the profiler from the environment."""
    if PROFILER is None and knobs.get_bool("NOMAD_TPU_CONTPROF"):
        enable()
        return True
    return False


def window(seconds: float = 60.0) -> Dict[str, Any]:
    p = PROFILER
    if p is None:
        return {"Enabled": False}
    return p.window(seconds)


def shares(seconds: float = 30.0) -> Dict[str, float]:
    p = PROFILER
    return p.shares(seconds) if p is not None else {}


def host_attribution(top_locks: int = 5) -> Optional[Dict[str, Any]]:
    p = PROFILER
    return p.host_attribution(top_locks=top_locks) \
        if p is not None else None


def reset() -> None:
    p = PROFILER
    if p is not None:
        p.reset()


# ---------------------------------------------------------------------------
# metrics bridge (the codec.merge_metrics pattern)
# ---------------------------------------------------------------------------

MERGE_TOP_LOCKS = 8


def merge_metrics(latest: Dict) -> Dict:
    """Merge the profiler gauges and the contention-ledger histograms
    into a server sink's ``latest()`` summary — the bridge that puts
    ``nomad.cpu.<subsystem>`` and ``nomad.lock.<name>.wait_seconds`` on
    ``/v1/metrics`` (both formats) and ``Status.Metrics``.  Each rider
    merges independently: lock waits appear whenever the sanitizer is
    armed, CPU shares whenever the profiler is."""
    p = PROFILER
    if p is not None:
        gauges = latest.setdefault("Gauges", {})
        for sub, share in p.shares(30.0).items():
            gauges[f"nomad.cpu.{sub}"] = share
        gil = p.gil_pressure_ms()
        gauges["nomad.runtime.gil_delay_p50_ms"] = gil["p50"]
        gauges["nomad.runtime.gil_delay_p99_ms"] = gil["p99"]
    waits = lockcheck.wait_stats(top=MERGE_TOP_LOCKS)
    if waits:
        samples = latest.setdefault("Samples", {})
        totals = latest.setdefault("SampleTotals", {})
        for w in waits:
            key = f"nomad.lock.{w['name']}.wait_seconds"
            count = w["count"]
            total_s = w["wait_s_sum"]
            samples[key] = {
                "count": count,
                "sum": total_s,
                "min": 0.0,
                "max": w["wait_s_max"],
                "mean": round(total_s / count, 9) if count else 0.0,
                "p50": round(w["p50_ms"] / 1000.0, 9),
                "p95": round(w["p95_ms"] / 1000.0, 9),
                "p99": round(w["p99_ms"] / 1000.0, 9),
            }
            totals[key] = (count, total_s)
    return latest
