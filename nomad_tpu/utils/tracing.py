"""Eval-lifecycle tracing plane: cheap structured spans threaded through
the scheduling pipeline (broker → worker → batch scheduler → plan
applier → raft), queryable per eval.

Why not logs: at batch scale, "where did eval X spend its time" is a
join across six subsystems on four threads.  Spans carry ids, parents,
``perf_counter`` timestamps, and attrs; everything touching one evaluation
tags ``eval_id`` (batch spans tag ``eval_ids``), so the whole lifecycle
— enqueue → dequeue → batch phases → plan submit → apply — comes back
from one index lookup (``/v1/trace/eval/<id>`` in agent/http.py).

Cost discipline (the ``fault.py`` contract): the plane is **off by
default** and the only production state is off.  Every instrumented
site reads one module global (``TRACER``) and branches; disarmed there
are no locks, no allocations, no timestamps.  Arm process-wide with
``tracing.enable()`` (tests, the selfcheck drill) or the
``NOMAD_TPU_TRACE=1`` env var (read at server construction).

Threading model: spans nest via a thread-local stack (parent linkage
within a thread); an eval's lifecycle *crosses* threads (RPC handler →
worker → plan applier), so cross-thread correlation is by ``eval_id``
attr, not parent pointers.  ``trace_for_eval`` returns every span
tagged with the eval, sorted by start time — the timeline.

Correlation with the chaos plane: ``fault.py`` reports every rule fire
here (``note_fault`` → a ``fault.fire`` span carrying the same
(point, rule, action) triple that ``fault.trace()`` records), and
``ops/breaker.py`` reports state transitions (``breaker.transition``
spans) — so a trace of a chaos-injected eval shows *which* injected
fault and breaker movement shaped its path.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

__all__ = [
    "Span", "Tracer", "TRACER", "NOOP", "now",
    "enable", "disable", "enabled", "span", "event", "record",
    "trace_for_eval", "recent", "note_fault", "mark", "close_mark",
]

#: The span clock.  ``time.perf_counter()``: monotonic like
#: ``time.monotonic()`` (immune to NTP steps) but highest-resolution,
#: so sub-millisecond phase spans don't quantize.  Callers feeding
#: already-measured timestamps into :func:`record` must use THIS clock
#: (``tracing.now()``) — mixing bases corrupts span ordering and the
#: wall-clock backdating.
now = time.perf_counter

# Bounded-store defaults: the recency ring holds ~4k completed spans;
# independently, the eval index (LRU over the last ~1k distinct eval
# ids) pins ≤256 spans per indexed eval even after they leave the ring,
# so the armed-plane worst case is ~256k retained spans, not 4k.
DEFAULT_CAPACITY = 4096
DEFAULT_MAX_EVALS = 1024
MAX_SPANS_PER_EVAL = 256
# A batch span tags every member eval; at bench scale a batch can carry
# 1k+ evals, and indexing/serializing millions of ids per phase span
# under the tracer lock would swamp the armed plane.  Beyond this cap
# the span keeps the first N ids (indexed + serialized) plus an
# `eval_ids_elided` count.
MAX_EVAL_IDS_PER_SPAN = 128
# Cross-thread umbrella marks (eval.e2e: RPC submit → broker ack): an
# eval whose ack never comes (leadership churn) must not pin its mark
# forever, so the mark table is a bounded LRU.
MAX_MARKS = 4096


class Span:
    """One completed (or in-flight) operation.  ``start``/``end`` are
    ``tracing.now()`` (``time.perf_counter()``) — comparable across
    threads, immune to wall clock steps; ``wall`` is the wall-clock
    start kept only as the epoch anchor for humans."""

    __slots__ = ("span_id", "parent_id", "name", "start", "end", "wall",
                 "attrs")

    def __init__(self, span_id: int, parent_id: int, name: str,
                 start: float, attrs: Dict[str, Any]):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.end = start
        self.wall = time.time()
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        """Attach attrs mid-span (e.g. the nack reason on failure)."""
        self.attrs.update(attrs)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "SpanID": self.span_id,
            "ParentID": self.parent_id,
            "Name": self.name,
            "Start": self.start,
            "End": self.end,
            "DurationMs": round((self.end - self.start) * 1000.0, 4),
            "Wall": self.wall,
            "Attrs": self.attrs,
        }


class _EvalBucket:
    """Per-eval span index entry: the retained spans plus how many were
    evicted once the MAX_SPANS_PER_EVAL cap was hit."""

    __slots__ = ("spans", "dropped")

    def __init__(self) -> None:
        self.spans: List[Span] = []
        self.dropped = 0


class _NoopSpan:
    """Shared do-nothing span/context-manager handed out while tracing is
    disabled — call sites keep one code path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


#: Shared disabled-plane singleton; call sites that must not even build
#: the attrs dict while disarmed branch on TRACER and use this directly.
NOOP = _NOOP = _NoopSpan()


class _ActiveSpan:
    """Context manager pushing/popping one span on the thread-local
    stack; an exception escaping the block is recorded on the span."""

    __slots__ = ("tracer", "sp")

    def __init__(self, tracer: "Tracer", sp: Span):
        self.tracer = tracer
        self.sp = sp

    def __enter__(self) -> Span:
        self.tracer._push(self.sp)
        return self.sp

    def __exit__(self, etype, evalue, tb) -> bool:
        if etype is not None:
            self.sp.attrs.setdefault("error", etype.__name__)
            self.sp.attrs.setdefault("error_detail", str(evalue))
        self.tracer._pop(self.sp)
        return False


class Tracer:
    """The armed state: a bounded ring of completed spans plus an LRU
    index eval_id → spans.  All mutation under one lock; span creation
    itself (the common case) takes the lock once at finish."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 max_evals: int = DEFAULT_MAX_EVALS):
        self._l = threading.Lock()
        self._seq = itertools.count(1)
        self._spans: deque = deque(maxlen=max(16, capacity))
        self._by_eval: "OrderedDict[str, _EvalBucket]" = OrderedDict()
        self.max_evals = max(1, max_evals)
        self._local = threading.local()
        # eval_id → (tracing.now() submit time, attrs): the open end of
        # a cross-thread umbrella span (mark/close_mark).
        self._marks: "OrderedDict[str, tuple]" = OrderedDict()

    # -- thread-local span stack ------------------------------------------

    def _stack(self) -> List[Span]:
        stk = getattr(self._local, "stack", None)
        if stk is None:
            stk = self._local.stack = []
        return stk

    def _push(self, sp: Span) -> None:
        self._stack().append(sp)

    def _pop(self, sp: Span) -> None:
        stk = self._stack()
        if stk and stk[-1] is sp:
            stk.pop()
        elif sp in stk:  # defensive: mis-nested exit
            stk.remove(sp)
        sp.end = now()
        self._record(sp)

    def current(self) -> Optional[Span]:
        stk = getattr(self._local, "stack", None)
        return stk[-1] if stk else None

    # -- span creation -----------------------------------------------------

    def _new_span(self, name: str, attrs: Dict[str, Any]) -> Span:
        evs = attrs.get("eval_ids")
        if evs is not None and len(evs) > MAX_EVAL_IDS_PER_SPAN:
            attrs["eval_ids"] = list(evs[:MAX_EVAL_IDS_PER_SPAN])
            attrs["eval_ids_elided"] = len(evs) - MAX_EVAL_IDS_PER_SPAN
        parent = self.current()
        parent_id = parent.span_id if parent is not None else 0
        # Inherit the eval correlation key from the enclosing span so
        # inner spans (wait_for_index, phases) need not repeat it.
        if parent is not None and "eval_id" not in attrs \
                and "eval_ids" not in attrs:
            pev = parent.attrs.get("eval_id")
            if pev is not None:
                attrs["eval_id"] = pev
            else:
                pevs = parent.attrs.get("eval_ids")
                if pevs is not None:
                    attrs["eval_ids"] = pevs
        return Span(next(self._seq), parent_id, name, now(), attrs)

    def span(self, name: str, **attrs: Any) -> _ActiveSpan:
        return _ActiveSpan(self, self._new_span(name, attrs))

    def event(self, name: str, **attrs: Any) -> Span:
        """Zero-duration span (a point-in-time lifecycle marker:
        broker enqueue/ack, a breaker transition, a fault fire)."""
        sp = self._new_span(name, attrs)
        self._record(sp)
        return sp

    def record(self, name: str, start: float, end: float,
               **attrs: Any) -> Span:
        """Retroactively record a completed span from already-measured
        ``tracing.now()`` timestamps (the batch scheduler's phase
        timers)."""
        sp = self._new_span(name, attrs)
        # Backdate the wall clock along with the monotonic start — it was
        # stamped at creation (i.e. the phase's END), not at `start`.
        sp.wall -= sp.start - start
        sp.start = start
        sp.end = end
        self._record(sp)
        return sp

    # -- cross-thread umbrella marks ---------------------------------------

    def mark(self, eval_id: str, **attrs: Any) -> None:
        """Open an umbrella: remember WHEN (``tracing.now()``) this eval
        was submitted, so whichever thread later closes it can record
        one span covering the whole client-visible lifecycle."""
        with self._l:
            self._marks[eval_id] = (now(), attrs)
            self._marks.move_to_end(eval_id)
            while len(self._marks) > MAX_MARKS:
                self._marks.popitem(last=False)

    def close_mark(self, eval_id: str, name: str = "eval.e2e",
                   **attrs: Any) -> None:
        """Close the umbrella opened by :meth:`mark` — records one
        retroactive ``eval.e2e`` span (submit → now) stitching client
        RPC → broker → worker → plan-apply across threads.  No-op when
        no mark exists (evals born inside the scheduler)."""
        with self._l:
            entry = self._marks.pop(eval_id, None)
        if entry is None:
            return
        start, mark_attrs = entry
        merged = dict(mark_attrs)
        merged.update(attrs)
        merged["eval_id"] = eval_id
        self.record(name, start, now(), **merged)

    # -- storage / query ---------------------------------------------------

    def _record(self, sp: Span) -> None:
        keys = []
        ev = sp.attrs.get("eval_id")
        if ev is not None:
            keys.append(ev)
        evs = sp.attrs.get("eval_ids")
        if evs:
            keys.extend(evs)
        with self._l:
            self._spans.append(sp)
            for key in keys:
                bucket = self._by_eval.get(key)
                if bucket is None:
                    bucket = self._by_eval[key] = _EvalBucket()
                    while len(self._by_eval) > self.max_evals:
                        self._by_eval.popitem(last=False)
                else:
                    self._by_eval.move_to_end(key)
                bucket.spans.append(sp)
                if len(bucket.spans) > MAX_SPANS_PER_EVAL:
                    # Drop the OLDEST span: the terminal spans (ack/nack,
                    # final attempt) answer "how did this eval end" and
                    # must survive.
                    del bucket.spans[0]
                    bucket.dropped += 1

    def trace_for_eval(self, eval_id: str) -> List[Dict[str, Any]]:
        with self._l:
            bucket = self._by_eval.get(eval_id)
            spans = list(bucket.spans) if bucket is not None else []
            dropped = bucket.dropped if bucket is not None else 0
        spans.sort(key=lambda sp: sp.start)
        out = [sp.to_dict() for sp in spans]
        if dropped and out:
            # Flag the (new) head of a truncated timeline on the rendered
            # copy only — the Span's attrs dict is shared across the
            # buckets of every eval in the batch.
            out[0] = dict(out[0], Attrs=dict(out[0]["Attrs"],
                                             trace_truncated=dropped))
        return out

    def recent(self, n: int = 100) -> List[Dict[str, Any]]:
        """The last ``n`` completed spans, oldest first."""
        if n <= 0:  # spans[-0:] would return everything
            return []
        with self._l:
            spans = list(self._spans)
        return [sp.to_dict() for sp in spans[-n:]]


# -- process-wide arming ------------------------------------------------------

# The single global every instrumented site reads.  ``None`` ⇒ disabled
# ⇒ one load + one comparison per site (the fault.py discipline).
TRACER: Optional[Tracer] = None


def enable(capacity: int = DEFAULT_CAPACITY,
           max_evals: int = DEFAULT_MAX_EVALS) -> Tracer:
    global TRACER
    TRACER = Tracer(capacity=capacity, max_evals=max_evals)
    return TRACER


def disable() -> None:
    global TRACER
    TRACER = None


def enabled() -> bool:
    return TRACER is not None


def span(name: str, **attrs: Any):
    """``with tracing.span("worker.attempt", eval_id=...) as sp:`` —
    the no-op singleton when disabled."""
    tr = TRACER
    if tr is None:
        return _NOOP
    return tr.span(name, **attrs)


def eval_id_attrs(evals, total: int) -> Dict[str, Any]:
    """Correlation attrs for a batch span without materializing more ids
    than the span retains — callers may hold million-eval batches.
    ``evals`` is any iterable of objects with ``.id``; ``total`` is the
    full batch size."""
    ids = [ev.id for ev, _ in zip(evals, range(MAX_EVAL_IDS_PER_SPAN))]
    out: Dict[str, Any] = {"eval_ids": ids}
    if total > len(ids):
        out["eval_ids_elided"] = total - len(ids)
    return out


def event(name: str, **attrs: Any) -> None:
    tr = TRACER
    if tr is not None:
        tr.event(name, **attrs)


def record(name: str, start: float, end: float, **attrs: Any) -> None:
    tr = TRACER
    if tr is not None:
        tr.record(name, start, end, **attrs)


def trace_for_eval(eval_id: str) -> List[Dict[str, Any]]:
    tr = TRACER
    return tr.trace_for_eval(eval_id) if tr is not None else []


def recent(n: int = 100) -> List[Dict[str, Any]]:
    tr = TRACER
    return tr.recent(n) if tr is not None else []


def mark(eval_id: str, **attrs: Any) -> None:
    tr = TRACER
    if tr is not None:
        tr.mark(eval_id, **attrs)


def close_mark(eval_id: str, name: str = "eval.e2e", **attrs: Any) -> None:
    tr = TRACER
    if tr is not None:
        tr.close_mark(eval_id, name, **attrs)


def note_fault(point: str, rule_index: int, action: str) -> None:
    """Called by fault.FaultPlane.fire on every rule fire: the tracing
    twin of the plane's own trace(), attached to the current span so a
    chaos-shaped eval's timeline shows which injection hit it."""
    tr = TRACER
    if tr is not None:
        tr.event("fault.fire", point=point, rule=rule_index,
                 action=action)
