"""Jittered exponential backoff, shared by every retry/poll loop.

Fixed-interval sleeps synchronize retries into thundering herds: every
worker that lost the broker wakes on the same 50ms boundary, every
client whose server died re-registers on the same 15s boundary.  This
module is the one place retry cadence lives:

- ``Backoff``    — stateful delay generator (full jitter, capped).
- ``retry``      — call a function with bounded, backed-off retries.
- ``wait_until`` — poll a predicate with a ramping interval (replaces
                   fixed ``time.sleep(0.005)`` spin loops: first checks
                   are fast for latency, later ones coarse for CPU).

Determinism: pass ``rng=random.Random(seed)`` and a fake ``sleep`` to
make schedules reproducible in tests.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["Backoff", "retry", "wait_until"]


class Backoff:
    """Exponential backoff with full jitter (the AWS-style scheme: each
    delay is uniform in ``[floor_n, cap_n]`` where ``cap_n`` doubles per
    attempt and ``floor_n`` never drops below ``base/10`` — a jittered
    near-zero draw must not turn a backoff loop into a spin loop).
    ``jitter=0`` degrades to plain exponential for tests that want exact
    schedules."""

    def __init__(self, base: float = 0.05, factor: float = 2.0,
                 max_delay: float = 5.0, jitter: float = 1.0,
                 rng: Optional[random.Random] = None):
        if base <= 0:
            raise ValueError("base must be positive")
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self.rng = rng or random.Random()
        self.attempt = 0

    def next_delay(self) -> float:
        """The delay to sleep before the next retry; advances state.
        The exponent is clamped: a long-idle loop that calls this for
        hours must keep getting the cap, not an OverflowError once
        ``factor ** attempt`` leaves float range (found by the ISSUE 12
        chaos soak — the overflow silently killed idle worker threads
        mid-run)."""
        ceiling = min(self.max_delay,
                      self.base * (self.factor ** min(self.attempt, 64)))
        self.attempt += 1
        if self.jitter <= 0:
            return ceiling
        # Full jitter over [floor, ceiling].  The floor is clamped to
        # base/10 even at jitter=1.0 so a near-zero draw can't hot-spin
        # a retry loop against a persistently failing dependency.
        floor = min(ceiling, self.base * max(0.1, 1.0 - self.jitter))
        return floor + self.rng.random() * (ceiling - floor)

    def reset(self) -> None:
        self.attempt = 0


def retry(fn: Callable, retries: int = 3,
          retry_on: Tuple[Type[BaseException], ...] = (Exception,),
          backoff: Optional[Backoff] = None,
          sleep: Callable[[float], None] = time.sleep,
          on_retry: Optional[Callable[[BaseException, int], None]] = None):
    """Call ``fn()`` with up to ``retries`` retried failures (so at most
    ``retries + 1`` calls).  ``on_retry(exc, attempt)`` observes each
    failure before the backed-off sleep; the final failure re-raises."""
    bo = backoff or Backoff()
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if attempt >= retries:
                raise
            if on_retry is not None:
                on_retry(e, attempt)
            sleep(bo.next_delay())
            attempt += 1


def wait_until(predicate: Callable[[], bool], timeout: float,
               initial: float = 0.0005, max_interval: float = 0.02,
               factor: float = 1.5,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic) -> bool:
    """Poll ``predicate`` until true or ``timeout`` elapses; returns the
    final predicate value.  The interval ramps ``initial → max_interval``
    so hot waits (raft catch-up is usually sub-millisecond away) stay
    low-latency without pinning a core when the wait drags."""
    if predicate():
        return True
    deadline = clock() + timeout
    interval = initial
    while clock() < deadline:
        sleep(min(interval, max(0.0, deadline - clock())))
        if predicate():
            return True
        interval = min(interval * factor, max_interval)
    return predicate()
