"""Env-var kill-switch flags, one parser for every NOMAD_TPU_* knob.

The codebase grew several inline copies of the ``.strip().lower() not in
("0", "false", "no")`` idiom with subtly different empty-string
semantics.  This is the one place that decides: an UNSET or EMPTY value
means the default; otherwise anything except 0/false/no is true.
"""
from __future__ import annotations

import os

_FALSY = ("0", "false", "no")


def env_flag(name: str, default: bool) -> bool:
    """Boolean env knob, re-read on every call (runtime kill-switch —
    flipping the variable takes effect on the next batch, never cached
    at import)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    raw = raw.strip().lower()
    if raw == "":
        return default
    return raw not in _FALSY
