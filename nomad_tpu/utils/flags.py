"""Deprecated shim — boolean knob parsing lives in utils/knobs.py.

This module used to hold the one boolean env parser; the ISSUE 15 knob
registry subsumed it (every NOMAD_TPU_* name must now be declared in
``utils/knobs.py``, and reads are registry-checked).  ``env_flag``
remains as a delegate for any straggler import.
"""
from __future__ import annotations

from . import knobs


def env_flag(name: str, default: bool) -> bool:
    """Boolean env knob, re-read on every call (runtime kill-switch —
    flipping the variable takes effect on the next batch, never cached
    at import).  Registry-checked: reading an undeclared name raises."""
    return knobs.get_bool(name, default)
