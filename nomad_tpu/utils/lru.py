"""Small LRU cache for compiled-program / device-buffer caches.

The batch scheduler keeps several keyed caches of expensive artifacts —
compiled sharded-fused programs (`parallel/sharded._FUSED_MESH_CACHE`),
finalized static cluster tensors, device-resident static buffers, and
the per-mesh donated delta-apply programs.  A long-lived server that
sees many mesh/meta shapes must not grow these without limit, and the
old ad-hoc ``while len > N: pop oldest`` bound was FIFO (a hot entry
re-fetched every batch could still be evicted by churn).  This class is
the one touch-on-hit LRU they all share; every eviction increments the
module counter ``EVICTIONS``, surfaced as the
``batch.program_cache_evictions`` gauge so operators can see compiled
programs being recycled (a high rate at steady state means the cap is
too small for the workload's shape diversity).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

# Process-lifetime eviction count across every LRU instance (telemetry
# gauge `batch.program_cache_evictions`).
EVICTIONS = 0


class LRU:
    """Bounded mapping with touch-on-hit recency and eviction counting.

    Not thread-safe by itself — callers that race (batch_sched's module
    caches are touched from scheduler threads) rely on the GIL for the
    individual OrderedDict operations, the same contract the dicts it
    replaces had."""

    __slots__ = ("cap", "_d", "evictions", "on_evict")

    def __init__(self, cap: int,
                 on_evict: Optional[Callable] = None) -> None:
        assert cap > 0
        self.cap = cap
        self._d: OrderedDict = OrderedDict()
        self.evictions = 0
        self.on_evict = on_evict

    def get(self, key, default=None):
        # Single read first: a concurrent put() may evict key between
        # any two steps here, so the lookup must be the one op that
        # decides hit-vs-miss (the recency touch tolerates the race).
        try:
            v = self._d[key]
        except KeyError:
            return default
        try:
            self._d.move_to_end(key)
        except KeyError:
            pass
        return v

    def put(self, key, value) -> None:
        global EVICTIONS
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.cap:
            _, old = self._d.popitem(last=False)
            self.evictions += 1
            EVICTIONS += 1
            if self.on_evict is not None:
                self.on_evict(old)

    def pop(self, key, default=None):
        return self._d.pop(key, default)

    def clear(self) -> None:
        self._d.clear()

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)
