"""Rule family 4: the env-knob registry.

Every ``NOMAD_TPU_*`` environment variable is declared once in
``utils/knobs.py`` and read only through its accessors.  Three checks:

- **knob-env-read** — an ``os.environ.get`` / ``os.environ[...]`` /
  ``os.getenv`` *read* of a ``NOMAD_TPU_*`` name anywhere outside
  ``utils/knobs.py`` (writes — arming a drill, spawning a child with a
  knob set — are fine; interpreting a knob's value ad hoc is not).
  Names are resolved through module-level string constants
  (``CHILD_ENV = "NOMAD_TPU_BENCH_CHILD"``) so indirection cannot
  launder a read.
- **knob-unregistered** — any ``NOMAD_TPU_*`` token appearing in a
  Python source (string, comment, knobs accessor argument) that is not
  declared in the registry.  Wildcard doc mentions
  (``NOMAD_TPU_BREAKER_*``, ``NOMAD_TPU_RAFT_{...}_S``) pass via a
  prefix rule: a token that is a strict prefix of registered knobs is
  documentation, not a knob.
- **knob-readme-drift** — the README env-knob table between the
  ``knob-table`` markers must equal ``knobs.render_readme_table()``
  byte-for-byte (regenerate with ``--write-knob-table``).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional

from . import SourceFile, Violation, expr_text
from .guardrules import _load_by_path, registry_missing

RULE_READ = "knob-env-read"
RULE_UNREG = "knob-unregistered"
RULE_DRIFT = "knob-readme-drift"

KNOBS_PATH = "nomad_tpu/utils/knobs.py"
KNOB_RE = re.compile(r"NOMAD_TPU_[A-Z0-9_]+")

_ACCESSORS = {"get_bool", "get_int", "get_float", "get_str", "raw",
              "lookup"}


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant) and isinstance(
                node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
    return out


def _resolve_key(node: ast.expr,
                 consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _check_env_reads(sf: SourceFile, consts: Dict[str, str],
                     violations: List[Violation]) -> None:
    for fn_node in ast.walk(sf.tree):
        if not isinstance(fn_node, ast.Call):
            continue
        text = expr_text(fn_node.func) or ""
        key_node = None
        if text in ("os.environ.get", "environ.get", "os.getenv",
                    "getenv"):
            if fn_node.args:
                key_node = fn_node.args[0]
        if key_node is None:
            continue
        key = _resolve_key(key_node, consts)
        if key is None or not key.startswith("NOMAD_TPU_"):
            continue
        qual = _enclosing_name(sf.tree, fn_node)
        violations.append(Violation(
            rule=RULE_READ, path=sf.path, line=fn_node.lineno,
            qualname=qual, detail=key,
            message=f"ad-hoc env read of {key} — go through "
                    f"utils/knobs.py (get_bool/get_int/get_float/"
                    f"get_str, or raw() for save/restore)"))
    # Subscript loads in Load context (os.environ[...] as a read).
    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and expr_text(node.value) in ("os.environ", "environ")):
            key = _resolve_key(node.slice, consts)
            if key and key.startswith("NOMAD_TPU_"):
                violations.append(Violation(
                    rule=RULE_READ, path=sf.path, line=node.lineno,
                    qualname=_enclosing_name(sf.tree, node),
                    detail=f"subscript:{key}",
                    message=f"ad-hoc env read of {key} via "
                            f"os.environ[...] — go through "
                            f"utils/knobs.py"))


def _enclosing_name(tree: ast.Module, target: ast.AST) -> str:
    best = ""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (node.lineno <= target.lineno
                    <= (node.end_lineno or node.lineno)):
                best = node.name
    return best


def _prefix_of_registered(token: str, registered) -> bool:
    stripped = token.rstrip("_")
    for name in registered:
        if name != token and (name.startswith(token)
                              or name.startswith(stripped + "_")
                              or name == stripped):
            return True
    return False


def check(root: str, files: List[SourceFile]) -> List[Violation]:
    violations: List[Violation] = []
    missing = registry_missing(root, KNOBS_PATH, RULE_READ)
    if missing is not None:
        return [missing]
    knobs = _load_by_path(root, KNOBS_PATH, "_analysis_knobs2")
    registered = {k.name for k in knobs.registered()}

    for sf in files:
        consts = _module_str_constants(sf.tree)
        if sf.path != KNOBS_PATH:
            _check_env_reads(sf, consts, violations)
        # Unregistered tokens anywhere in the source (incl. comments).
        seen = set()
        for lineno, line in enumerate(sf.lines, 1):
            for match in KNOB_RE.finditer(line):
                token = match.group(0).rstrip("_")
                if token in registered or token in seen:
                    continue
                if _prefix_of_registered(match.group(0), registered):
                    continue
                seen.add(token)
                violations.append(Violation(
                    rule=RULE_UNREG, path=sf.path, line=lineno,
                    detail=token,
                    message=f"{token} is not declared in "
                            f"utils/knobs.py — register it (name, "
                            f"type, default, doc) before use"))

    # README drift.
    readme = os.path.join(root, "README.md")
    expected = knobs.render_readme_table()
    drift = None
    if not os.path.exists(readme):
        drift = "README.md missing"
    else:
        with open(readme, "r", encoding="utf-8") as fh:
            text = fh.read()
        begin, end = knobs.TABLE_BEGIN, knobs.TABLE_END
        if begin not in text or end not in text:
            drift = ("README.md has no knob-table markers — run "
                     "python -m nomad_tpu.analysis --write-knob-table")
        else:
            start = text.index(begin)
            stop = text.index(end) + len(end)
            if text[start:stop] != expected:
                drift = ("README knob table out of sync with "
                         "utils/knobs.py — regenerate with "
                         "python -m nomad_tpu.analysis "
                         "--write-knob-table")
    if drift is not None:
        violations.append(Violation(
            rule=RULE_DRIFT, path="README.md", line=1,
            detail="knob-table", message=drift))
    return violations
