"""Rule family 1: lock discipline.

Reconstructs lock regions per module from the AST and enforces the two
invariants whose violations produced the repo's worst bugs:

- **lock-blocking** — no blocking operation inside a lock region.
  fsync under the raft log lock made WAL group-commit structurally
  impossible (PR 9); the FileLog snapshot sequencer drain deadlocked
  under the log lock (PR 10).  Blocking means: file durability
  (fsync/fdatasync), socket traffic (sendall/recv/connect/accept),
  device synchronization (jax.device_get / block_until_ready),
  subprocess execution, and time.sleep.  ``Condition.wait`` is NOT
  blocking in this sense — it releases the lock it waits on.
- **lock-order** — the static acquisition graph (lock A held while
  acquiring lock B) must be acyclic.  Lock identity is
  ``module:owner.attr`` resolved from ``threading.Lock()`` /
  ``RLock()`` / ``Condition()`` assignment sites; ``with`` regions
  nest the graph, and imperative ``X.acquire()`` sites feed it as
  edge targets.  Dynamic cross-module orders that static names cannot
  see are owned by the runtime sanitizer (``utils/lockcheck.py``).

Both rules propagate one call level *within a module* (to a fixpoint):
a function that fsyncs is itself blocking, and calling it under a lock
is flagged at the call site — helpers cannot launder a blocking call.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import SourceFile, Violation, expr_text

RULE_BLOCKING = "lock-blocking"
RULE_ORDER = "lock-order"

BLOCKING_ATTRS = {
    "fsync": "os.fsync", "fdatasync": "os.fdatasync",
    "sendall": "socket send", "recv": "socket recv",
    "recv_into": "socket recv", "connect": "socket connect",
    "accept": "socket accept",
    "device_get": "jax.device_get (host sync)",
    "block_until_ready": "jax host sync",
    "sleep": "time.sleep",
    "check_output": "subprocess", "check_call": "subprocess",
    "communicate": "subprocess wait",
    "urlopen": "network request",
}
# Only blocking when called on the named module object.
BLOCKING_QUALIFIED = {
    ("subprocess", "run"): "subprocess",
    ("subprocess", "Popen"): "subprocess spawn",
    ("select", "select"): "select",
}
# Bare-name calls (``sleep()`` after ``from time import sleep``) count
# only for the unambiguous names.
BARE_BLOCKING = {"sleep", "fsync", "fdatasync", "urlopen"}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _call_name(node: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    fn = node.func
    if isinstance(fn, ast.Name):
        return None, fn.id
    if isinstance(fn, ast.Attribute):
        return expr_text(fn.value), fn.attr
    return None, None


def _blocking_kind(node: ast.Call) -> Optional[str]:
    base, attr = _call_name(node)
    if attr is None:
        return None
    if (base, attr) in BLOCKING_QUALIFIED:
        return BLOCKING_QUALIFIED[(base, attr)]
    if attr in BLOCKING_ATTRS:
        if base is None and attr not in BARE_BLOCKING:
            return None
        return BLOCKING_ATTRS[attr]
    return None


def _is_lock_ctor(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTORS:
        return expr_text(fn.value) == "threading"
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        return True
    return False


def _looks_lockish(name: str) -> bool:
    low = name.lower().lstrip("_")
    return (low.endswith("lock") or low.endswith("cond")
            or low.endswith("_cv") or low in ("cv", "l", "mu"))


class _FuncInfo:
    def __init__(self, qualname: str) -> None:
        self.qualname = qualname
        self.blocking: Set[str] = set()   # "callee:kind" tags
        self.acquires: Set[str] = set()   # lock ids taken anywhere
        self.calls: Set[str] = set()      # same-module calls, unlocked


class _FileLockPass:
    def __init__(self, sf: SourceFile) -> None:
        self.sf = sf
        # Dotted module path, not the basename — every package's
        # __init__.py would otherwise share one "__init__" namespace
        # and same-named locks in different packages would merge into
        # one lock-order-graph node.
        mod = sf.path[:-3] if sf.path.endswith(".py") else sf.path
        mod = mod.replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[:-len(".__init__")]
        self.module = mod
        self.known_locks: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        self.known_locks.add(tgt.attr)
                    elif isinstance(tgt, ast.Name):
                        self.known_locks.add(tgt.id)
        self.funcs: Dict[str, _FuncInfo] = {}
        self.violations: List[Violation] = []
        # (src, dst) -> (path, line, qualname) witness
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        # same-module calls made while holding locks, resolved later:
        # (callee, line, qualname, held-ids)
        self.held_calls: List[Tuple[str, int, str, Tuple[str, ...]]] = []

    def _lock_id(self, expr: ast.expr) -> Optional[str]:
        text = expr_text(expr)
        if text is None:
            return None
        parts = text.split(".")
        attr = parts[-1]
        if attr not in self.known_locks and not _looks_lockish(attr):
            return None
        owner = ".".join(p for p in parts[:-1] if p != "self")
        return f"{self.module}:{owner + '.' if owner else ''}{attr}"

    # -- recursive region walk ---------------------------------------------

    def _visit(self, node: ast.AST, info: _FuncInfo,
               held: Tuple[str, ...]) -> None:
        if isinstance(node, _SCOPE_NODES):
            return  # separate scope; analyzed on its own
        if isinstance(node, ast.With):
            lock_ids: List[str] = []
            for item in node.items:
                self._visit(item.context_expr, info, held)
                lid = self._lock_id(item.context_expr)
                if lid is not None:
                    lock_ids.append(lid)
            for lid in lock_ids:
                info.acquires.add(lid)
                for held_id in held:
                    if held_id != lid:
                        self.edges.setdefault(
                            (held_id, lid),
                            (self.sf.path, node.lineno, info.qualname))
            inner = held + tuple(l for l in lock_ids if l not in held)
            for stmt in node.body:
                self._visit(stmt, info, inner)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, info, held)
            for child in ast.iter_child_nodes(node):
                self._visit(child, info, held)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, info, held)

    def _visit_call(self, node: ast.Call, info: _FuncInfo,
                    held: Tuple[str, ...]) -> None:
        base, attr = _call_name(node)
        if attr == "acquire" and isinstance(node.func, ast.Attribute):
            lid = self._lock_id(node.func.value)
            if lid is not None:
                info.acquires.add(lid)
                for held_id in held:
                    if held_id != lid:
                        self.edges.setdefault(
                            (held_id, lid),
                            (self.sf.path, node.lineno, info.qualname))
            return
        kind = _blocking_kind(node)
        if kind is not None:
            if held:
                lock_names = ", ".join(
                    h.split(":", 1)[1] for h in held)
                self.violations.append(Violation(
                    rule=RULE_BLOCKING, path=self.sf.path,
                    line=node.lineno, qualname=info.qualname,
                    detail=f"{attr}:under:{lock_names}",
                    message=f"blocking call {attr} ({kind}) inside "
                            f"lock region [{lock_names}] — hoist it "
                            f"out of the lock or allowlist with a "
                            f"reason"))
            else:
                info.blocking.add(f"{attr}:{kind}")
            return
        # Same-module call resolution: bare names and self-methods
        # only.  An attribute call whose base does not resolve to text
        # (``rx["chunks"].append``) is a foreign object's method, not
        # this module's function of the same name.
        if attr is None:
            return
        if isinstance(node.func, ast.Name) or base == "self":
            if held:
                self.held_calls.append(
                    (attr, node.lineno, info.qualname, held))
            else:
                info.calls.add(attr)

    # -- driver ------------------------------------------------------------

    def run(self) -> None:
        for node in ast.walk(self.sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FuncInfo(node.name)
                for stmt in node.body:
                    self._visit(stmt, info, held=())
                # Last definition wins on name collision across
                # classes — acceptable for a per-module heuristic.
                self.funcs[node.name] = info
        # Fixpoint: calling a blocking same-module function (outside
        # locks) makes the caller blocking too.
        changed = True
        while changed:
            changed = False
            for info in self.funcs.values():
                for callee in list(info.calls):
                    sub = self.funcs.get(callee)
                    if sub is None or sub is info:
                        continue
                    for tag in sub.blocking:
                        root_call = tag.split(":", 1)[0]
                        merged = f"{callee}->{tag}" \
                            if "->" not in tag else tag
                        if merged not in info.blocking:
                            info.blocking.add(merged)
                            changed = True
                    for lid in sub.acquires:
                        if lid not in info.acquires:
                            info.acquires.add(lid)
                            changed = True
        # Held-region same-module calls: blocking callees flag at the
        # call site; lock-acquiring callees feed the order graph.
        for attr, lineno, qualname, held in self.held_calls:
            sub = self.funcs.get(attr)
            if sub is None:
                continue
            lock_names = ", ".join(h.split(":", 1)[1] for h in held)
            for tag in sorted(sub.blocking):
                kind = tag.rsplit(":", 1)[-1]
                self.violations.append(Violation(
                    rule=RULE_BLOCKING, path=self.sf.path, line=lineno,
                    qualname=qualname,
                    detail=f"{attr}[{tag.split(':')[0]}]:under:"
                           f"{lock_names}",
                    message=f"call to {attr}() inside lock region "
                            f"[{lock_names}] reaches blocking {kind} "
                            f"— hoist or allowlist with a reason"))
            for acquired in sorted(sub.acquires):
                for held_id in held:
                    if held_id != acquired:
                        self.edges.setdefault(
                            (held_id, acquired),
                            (self.sf.path, lineno, qualname))


def _find_cycle(edges: Dict[Tuple[str, str], Tuple[str, int, str]]
                ) -> Optional[List[Tuple[str, str]]]:
    # One cycle finder for the static pass and the runtime sanitizer:
    # the iterative DFS lives in utils/lockcheck (pure graph search, no
    # sanitizer state).
    from ..utils.lockcheck import cycle_in_edges

    return cycle_in_edges(edges)


def check(root: str, files: List[SourceFile]) -> List[Violation]:
    violations: List[Violation] = []
    all_edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for sf in files:
        fp = _FileLockPass(sf)
        fp.run()
        violations.extend(fp.violations)
        for edge, where in fp.edges.items():
            all_edges.setdefault(edge, where)
    cycle = _find_cycle(all_edges)
    if cycle is not None:
        witness = []
        for a, b in cycle:
            path, line, qual = all_edges[(a, b)]
            witness.append(f"{a} -> {b} at {path}:{line} ({qual})")
        path0, line0, qual0 = all_edges[cycle[0]]
        chain = " -> ".join(a for a, _ in cycle) + f" -> {cycle[-1][1]}"
        violations.append(Violation(
            rule=RULE_ORDER, path=path0, line=line0, qualname=qual0,
            detail=f"cycle:{chain}",
            message="lock-order cycle: " + "; ".join(witness)))
    return violations
