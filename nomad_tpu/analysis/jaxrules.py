"""Rule family 2: JAX device discipline (hot-path modules).

Three invariants over ``ops/`` and ``parallel/``:

- **jax-donated-reuse** — after calling a jitted function created with
  ``donate_argnums``, the buffer passed at a donated position is dead
  (XLA aliased it into the output); reading the old variable again in
  the same function is a use-after-donation.  Detected in-module: jit
  objects built with ``jax.jit(..., donate_argnums=...)`` (including
  ``functools.partial(jax.jit, donate_argnums=...)`` decorators), call
  sites passing plain names at donated positions, and any later load
  of that name without an intervening rebind.
- **jax-host-sync** — ``jax.device_get`` / ``.block_until_ready()``
  force a device→host sync; in the hot-path modules every such call
  must be one of the sanctioned single-fetch sites (allowlisted with
  a reason) — anything else is a stealth second fetch, the exact
  regression class the one-dispatch/one-fetch contract guards.
- **jax-note-signature** — every module that builds a jit program must
  register invocation signatures with ``kernels.note_signature`` (the
  compile-audit seam); a jit call site in a module that never calls
  ``note_signature`` is a compile-audit escape: new program shapes
  would not show up in the ``batch.compiles`` gauge or the
  ``--check`` compile-budget ceiling.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import SourceFile, Violation, expr_text

RULE_DONATED = "jax-donated-reuse"
RULE_HOSTSYNC = "jax-host-sync"
RULE_NOTESIG = "jax-note-signature"

HOT_PREFIXES = ("nomad_tpu/ops/", "nomad_tpu/parallel/")


def _is_jit_call(node: ast.Call) -> bool:
    """``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    text = expr_text(node.func)
    if text in ("jax.jit", "jit"):
        return True
    if text in ("functools.partial", "partial") and node.args:
        return expr_text(node.args[0]) in ("jax.jit", "jit")
    return False


def _donate_argnums(node: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            try:
                val = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return ()
            if isinstance(val, int):
                return (val,)
            return tuple(int(v) for v in val)
    return None


class _DonatedCallables(ast.NodeVisitor):
    """Names in a module bound to donated jit programs: assignments
    ``f = jax.jit(g, donate_argnums=...)`` and functions decorated with
    ``functools.partial(jax.jit, donate_argnums=...)``."""

    def __init__(self) -> None:
        self.donated: Dict[str, Tuple[int, ...]] = {}

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call) and _is_jit_call(node.value):
            nums = _donate_argnums(node.value)
            if nums:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.donated[tgt.id] = nums
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and _is_jit_call(dec):
                nums = _donate_argnums(dec)
                if nums:
                    self.donated[node.name] = nums
        self.generic_visit(node)


def _check_donated_reuse(sf: SourceFile,
                         violations: List[Violation]) -> None:
    finder = _DonatedCallables()
    finder.visit(sf.tree)
    # Local ``f = jax.jit(..., donate_argnums=...)`` inside functions
    # are caught by the same visitor (it walks the whole module).
    if not finder.donated:
        return
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # Events ordered by (line, kind rank): a donation lands at the
        # call's END line and precedes a same-line rebind (evaluation
        # order of ``buf = _apply(buf, ...)``); the call's own argument
        # loads are skipped by node identity.
        events: List[Tuple[int, int, str, str]] = []
        arg_nodes = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = expr_text(node.func)
                nums = finder.donated.get(callee or "")
                if nums:
                    for idx in nums:
                        if idx < len(node.args) and isinstance(
                                node.args[idx], ast.Name):
                            arg_nodes.add(id(node.args[idx]))
                            events.append((node.end_lineno or
                                           node.lineno, 0, "donate",
                                           node.args[idx].id))
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    events.append((node.lineno, 1, "bind", node.id))
                elif (isinstance(node.ctx, ast.Load)
                        and id(node) not in arg_nodes):
                    events.append((node.lineno, 2, "load", node.id))
        events.sort(key=lambda e: (e[0], e[1]))
        dead: Dict[str, int] = {}
        for line, _rank, kind, name in events:
            if kind == "donate":
                dead[name] = line
            elif kind == "bind":
                dead.pop(name, None)
            elif kind == "load" and name in dead \
                    and line > dead[name]:
                violations.append(Violation(
                    rule=RULE_DONATED, path=sf.path, line=line,
                    qualname=fn.name,
                    detail=f"{name}:donated-at:{dead[name] - fn.lineno}",
                    message=f"{name!r} was passed at a donated "
                            f"position on line {dead[name]} and read "
                            f"again here — the buffer is dead after "
                            f"donation (use the aliased result, or "
                            f"rebind before reuse)"))
                dead.pop(name)  # one report per donation


def _check_host_sync(sf: SourceFile,
                     violations: List[Violation]) -> None:
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            text = expr_text(node.func) or ""
            attr = text.rsplit(".", 1)[-1]
            if text == "jax.device_get" or attr == "block_until_ready":
                violations.append(Violation(
                    rule=RULE_HOSTSYNC, path=sf.path, line=node.lineno,
                    qualname=fn.name,
                    detail=f"{attr}",
                    message=f"host-sync call {attr} in hot-path "
                            f"module — every device→host sync must be "
                            f"a sanctioned single-fetch site "
                            f"(allowlist with a reason)"))


def _enclosing_func(tree: ast.Module, target: ast.AST) -> str:
    best = ""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (node.lineno <= target.lineno
                    <= (node.end_lineno or node.lineno)):
                best = node.name
    return best


def _check_note_signature(sf: SourceFile,
                          violations: List[Violation]) -> None:
    has_note = False
    jit_sites: List[Tuple[int, str]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            text = expr_text(node.func) or ""
            if text.rsplit(".", 1)[-1] == "note_signature":
                has_note = True
            elif _is_jit_call(node):
                qual = _enclosing_func(sf.tree, node)
                jit_sites.append((node.lineno, qual))
    if jit_sites and not has_note:
        seen = set()
        for line, qual in jit_sites:
            # Keyed by enclosing function, not line number — allowlist
            # keys must survive line drift (one key per function, not
            # per call site).
            detail = f"jit-in:{qual or '<module>'}"
            if detail in seen:
                continue
            seen.add(detail)
            violations.append(Violation(
                rule=RULE_NOTESIG, path=sf.path, line=line,
                qualname=qual, detail=detail,
                message="module builds a jit program but never calls "
                        "kernels.note_signature — compile-audit "
                        "escape: new program shapes will not show in "
                        "batch.compiles or the --check compile "
                        "budget"))


def check(root: str, files: List[SourceFile]) -> List[Violation]:
    violations: List[Violation] = []
    for sf in files:
        if not sf.path.startswith(HOT_PREFIXES):
            continue
        _check_donated_reuse(sf, violations)
        _check_host_sync(sf, violations)
        _check_note_signature(sf, violations)
    return violations
