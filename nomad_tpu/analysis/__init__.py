"""Invariant analysis plane: AST lint for the repo's own disciplines.

``python -m nomad_tpu.analysis --check`` runs four rule families over
the whole non-vendor tree (the ``nomad_tpu`` package plus the root
``bench.py`` / ``__graft_entry__.py`` drivers; tests are exempt — they
deliberately arm knobs and hold locks in shapes production code must
not):

- **lock-discipline** (``lockrules``) — reconstructs ``with <lock>:``
  regions per module, flags blocking operations held under them
  (fsync, socket send/recv, ``jax.device_get``/``block_until_ready``,
  subprocess, ``time.sleep`` — the exact PR 9 fsync-under-lock and
  PR 10 drain-under-lock bug classes) and builds the static lock-order
  graph, failing on cycles;
- **jax-discipline** (``jaxrules``) — donated-buffer reuse after a
  ``donate_argnums`` call site, host-sync calls in the hot-path
  modules (``ops/``, ``parallel/``), and jitted entry points in
  modules that never register with ``kernels.note_signature``
  (compile-audit escapes);
- **guard-coverage** (``guardrules``) — every native twin, columnar
  mirror, and resident device mirror must be paired with a registered
  differential guard, a breaker feed, and an env kill-switch, checked
  structurally against ``ops/guards.py``;
- **knob-registry** (``knobrules``) — every ``NOMAD_TPU_*`` read goes
  through ``utils/knobs.py``; ad-hoc ``os.environ`` reads, undeclared
  knob names, and README-table drift all fail.

Suppression is by **justified allowlist** (``allowlist.txt`` next to
this file): one line per violation key with a written reason; stale
entries (matching nothing) fail the pass so the file cannot rot.
Violation keys are stable across line-number drift:
``rule path::qualname::detail``.
"""
from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Violation", "SourceFile", "Allowlist", "repo_root",
    "iter_source_files", "load_tree", "run_checks", "RULE_FAMILIES",
    "expr_text",
]

RULE_FAMILIES = ("lock-discipline", "jax-discipline",
                 "guard-coverage", "knob-registry")


def expr_text(node: ast.expr) -> Optional[str]:
    """Dotted text of a Name/Attribute chain (``self._lock``,
    ``jax.device_get``) or None for anything dynamic — the shared
    resolver every rule family names expressions with."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = expr_text(node.value)
        return f"{base}.{node.attr}" if base else None
    return None

_HERE = os.path.dirname(os.path.abspath(__file__))


def repo_root() -> str:
    # nomad_tpu/analysis/ -> nomad_tpu/ -> repo root
    return os.path.dirname(os.path.dirname(_HERE))


@dataclass
class Violation:
    rule: str
    path: str          # repo-relative
    line: int
    detail: str        # stable discriminator within (rule, path)
    message: str
    qualname: str = ""

    @property
    def key(self) -> str:
        q = self.qualname or "<module>"
        return f"{self.rule} {self.path}::{q}::{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message}"
                f"\n    key: {self.key}")


@dataclass
class SourceFile:
    path: str           # repo-relative, forward slashes
    abspath: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()


class Allowlist:
    """``allowlist.txt``: ``<key-pattern>  # <reason>`` lines.  The key
    pattern is fnmatch-matched against violation keys; every entry must
    carry a reason and must match at least one violation (stale entries
    are themselves violations, so suppressions cannot outlive the code
    they excuse)."""

    def __init__(self, path: str):
        self.path = path
        self.entries: List[Tuple[str, str, int]] = []  # pattern, reason, line
        self.used: Dict[int, int] = {}
        self.malformed: List[Tuple[int, str]] = []
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as fh:
                for lineno, raw in enumerate(fh, 1):
                    line = raw.rstrip("\n")
                    if not line.strip() or line.lstrip().startswith("#"):
                        continue
                    if "#" not in line:
                        self.malformed.append(
                            (lineno, "entry has no '# reason' part"))
                        continue
                    pattern, reason = line.split("#", 1)
                    pattern = pattern.strip()
                    reason = reason.strip()
                    if not pattern or not reason:
                        self.malformed.append(
                            (lineno, "empty pattern or empty reason"))
                        continue
                    self.entries.append((pattern, reason, lineno))

    def suppresses(self, violation: Violation) -> bool:
        hit = False
        for i, (pattern, _reason, _ln) in enumerate(self.entries):
            if (violation.key == pattern
                    or fnmatch.fnmatchcase(violation.key, pattern)):
                self.used[i] = self.used.get(i, 0) + 1
                hit = True
        return hit

    def stale_entries(self) -> List[Tuple[str, int]]:
        return [(pattern, ln)
                for i, (pattern, _r, ln) in enumerate(self.entries)
                if i not in self.used]


DEFAULT_ALLOWLIST = os.path.join(_HERE, "allowlist.txt")

EXCLUDE_DIRS = {"__pycache__", ".git", "tests", ".claude"}


def iter_source_files(root: Optional[str] = None) -> List[str]:
    """Repo-relative paths of every non-vendor, non-test Python source."""
    root = root or repo_root()
    out: List[str] = []
    pkg = os.path.join(root, "nomad_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in EXCLUDE_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.relpath(
                    os.path.join(dirpath, fn), root).replace(os.sep, "/"))
    for fn in ("bench.py", "__graft_entry__.py"):
        if os.path.exists(os.path.join(root, fn)):
            out.append(fn)
    return out


def load_tree(root: Optional[str] = None,
              paths: Optional[List[str]] = None) -> List[SourceFile]:
    root = root or repo_root()
    files: List[SourceFile] = []
    for rel in (paths if paths is not None else iter_source_files(root)):
        abspath = os.path.join(root, rel)
        with open(abspath, "r", encoding="utf-8") as fh:
            source = fh.read()
        files.append(SourceFile(
            path=rel, abspath=abspath, source=source,
            tree=ast.parse(source, filename=rel)))
    return files


def run_checks(root: Optional[str] = None,
               allowlist_path: Optional[str] = None,
               rules: Optional[List[str]] = None,
               ) -> Tuple[List[Violation], List[Violation]]:
    """Run every rule family; returns ``(active, suppressed)``.
    Malformed/stale allowlist entries surface as active ``allowlist``
    violations."""
    from . import guardrules, jaxrules, knobrules, lockrules

    root = root or repo_root()
    if rules:
        unknown = sorted(set(rules) - set(RULE_FAMILIES))
        if unknown:
            # An unknown family name must not run zero rules and report
            # a vacuous "clean".
            raise ValueError(
                f"unknown rule family {unknown} — choose from "
                f"{list(RULE_FAMILIES)}")
    files = load_tree(root)
    all_violations: List[Violation] = []
    families = {
        "lock-discipline": lockrules.check,
        "jax-discipline": jaxrules.check,
        "guard-coverage": guardrules.check,
        "knob-registry": knobrules.check,
    }
    for name, fn in families.items():
        if rules and name not in rules:
            continue
        all_violations.extend(fn(root, files))

    allow = Allowlist(allowlist_path or DEFAULT_ALLOWLIST)
    active: List[Violation] = []
    suppressed: List[Violation] = []
    for v in all_violations:
        (suppressed if allow.suppresses(v) else active).append(v)
    rel_allow = os.path.relpath(allow.path, root).replace(os.sep, "/")
    for lineno, why in allow.malformed:
        active.append(Violation(
            rule="allowlist", path=rel_allow, line=lineno,
            detail=f"malformed:{lineno}",
            message=f"malformed allowlist entry: {why}"))
    if rules is None:  # stale detection only meaningful on a full run
        for pattern, lineno in allow.stale_entries():
            active.append(Violation(
                rule="allowlist", path=rel_allow, line=lineno,
                detail=f"stale:{pattern}",
                message=f"stale allowlist entry matches nothing: "
                        f"{pattern!r} — delete it or fix the pattern"))
    active.sort(key=lambda v: (v.path, v.line, v.rule))
    suppressed.sort(key=lambda v: (v.path, v.line, v.rule))
    return active, suppressed
