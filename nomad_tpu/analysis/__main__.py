"""``python -m nomad_tpu.analysis`` — run the invariant lint.

Modes:
  --check              run all rule families; exit 1 on any
                       unsuppressed violation (the default)
  --list               also print suppressed (allowlisted) findings
  --rule NAME          restrict to one family (repeatable):
                       lock-discipline / jax-discipline /
                       guard-coverage / knob-registry
  --write-knob-table   regenerate the README env-knob table between
                       the knob-table markers, then exit
"""
from __future__ import annotations

import argparse
import sys

from . import RULE_FAMILIES, repo_root, run_checks


def _write_knob_table(root: str) -> int:
    import os

    from .guardrules import _load_by_path

    knobs = _load_by_path(root, "nomad_tpu/utils/knobs.py",
                          "_analysis_knobs_w")
    readme = os.path.join(root, "README.md")
    with open(readme, "r", encoding="utf-8") as fh:
        text = fh.read()
    table = knobs.render_readme_table()
    begin, end = knobs.TABLE_BEGIN, knobs.TABLE_END
    if begin in text and end in text:
        start = text.index(begin)
        stop = text.index(end) + len(end)
        text = text[:start] + table + text[stop:]
    else:
        print("README.md has no knob-table markers; add them where "
              "the table belongs (see utils/knobs.py TABLE_BEGIN)",
              file=sys.stderr)
        return 1
    with open(readme, "w", encoding="utf-8") as fh:
        fh.write(text)
    n = sum(1 for _ in knobs.registered())
    print(f"README knob table regenerated ({n} knobs)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.analysis")
    # Checking is the only mode; --check is accepted so gate scripts
    # and docs can spell the intent explicitly.
    parser.add_argument("--check", action="store_true", default=False)
    parser.add_argument("--list", action="store_true", default=False)
    parser.add_argument("--rule", action="append", default=None,
                        choices=list(RULE_FAMILIES))
    parser.add_argument("--write-knob-table", action="store_true")
    parser.add_argument("--root", default=None)
    args = parser.parse_args(argv)

    root = args.root or repo_root()
    if args.write_knob_table:
        return _write_knob_table(root)

    active, suppressed = run_checks(root, rules=args.rule)
    if args.list and suppressed:
        print(f"-- {len(suppressed)} allowlisted finding(s) --")
        for v in suppressed:
            print("  " + v.render().replace("\n", "\n  "))
    if active:
        print(f"-- {len(active)} violation(s) --")
        for v in active:
            print(v.render())
        print(f"\nFAIL: {len(active)} violation(s) "
              f"({len(suppressed)} allowlisted). Fix them or add a "
              f"justified entry to nomad_tpu/analysis/allowlist.txt")
        return 1
    print(f"analysis: clean ({len(suppressed)} allowlisted finding(s) "
          f"across the tree)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
