"""Rule family 3: guard coverage, checked against ``ops/guards.py``.

The registry (:mod:`nomad_tpu.ops.guards`) declares every fast-path /
reference-path pair; this rule family verifies the declarations are
*true of the tree*:

- every ``native/*.cc`` source is claimed by exactly one registry
  entry (an unclaimed twin is unguarded native code);
- each entry's module defines the named guard symbol;
- entries claiming a breaker feed actually contain one (a
  ``.record(False)`` call or a ``_note_mismatch`` helper);
- every kill-switch and guard-cadence knob an entry names is declared
  in ``utils/knobs.py``;
- a waiver (guard requirement explicitly not met) must carry a
  written justification.

The registry module is loaded by file path, not import, so the pass
never drags in jax.
"""
from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional

from . import SourceFile, Violation

RULE = "guard-coverage"

GUARDS_PATH = "nomad_tpu/ops/guards.py"
KNOBS_PATH = "nomad_tpu/utils/knobs.py"
NATIVE_DIR = "nomad_tpu/native"


def registry_missing(root: str, rel: str, rule: str) -> Optional["Violation"]:
    """A tree without its registry file is a structural violation, not a
    crash — --root fixture trees get a diagnostic instead of a
    FileNotFoundError traceback."""
    if os.path.exists(os.path.join(root, rel)):
        return None
    return Violation(
        rule=rule, path=rel, line=1, detail="registry-missing",
        message=f"tree has no {rel} — the registry this rule family "
                f"checks against is required")


def _load_by_path(root: str, rel: str, name: str):
    import hashlib
    import sys

    # Cache key carries the resolved path: two runs against different
    # roots (tests, --root) must not see each other's registries.
    abspath = os.path.abspath(os.path.join(root, rel))
    name = (f"{name}_"
            f"{hashlib.sha256(abspath.encode()).hexdigest()[:12]}")
    cached = sys.modules.get(name)
    if cached is not None:
        return cached
    spec = importlib.util.spec_from_file_location(name, abspath)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves cls.__module__ through sys.modules.
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(name, None)
        raise
    return mod


def _module_rel_path(dotted: str) -> str:
    return dotted.replace(".", "/") + ".py"


def _module_symbols(sf: SourceFile) -> Dict[str, int]:
    """Top-level defs/assignments of a module -> line."""
    out: Dict[str, int] = {}
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out[node.name] = node.lineno
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.lineno
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            out[node.target.id] = node.lineno
    return out


def _has_breaker_feed(sf: SourceFile) -> bool:
    src = sf.source
    return (".record(False)" in src or "_note_mismatch" in src
            or "breaker.record" in src)


def check(root: str, files: List[SourceFile]) -> List[Violation]:
    violations: List[Violation] = []
    by_path = {sf.path: sf for sf in files}

    missing = [v for v in (registry_missing(root, GUARDS_PATH, RULE),
                           registry_missing(root, KNOBS_PATH, RULE))
               if v is not None]
    if missing:
        return missing
    try:
        guards = _load_by_path(root, GUARDS_PATH, "_analysis_guards")
        knobs = _load_by_path(root, KNOBS_PATH, "_analysis_knobs")
    except Exception as exc:  # registry must at least load
        violations.append(Violation(
            rule=RULE, path=GUARDS_PATH, line=1,
            detail="registry-load",
            message=f"guard/knob registry failed to load: {exc!r}"))
        return violations
    registered_knobs = {k.name for k in knobs.registered()}

    # 1. every .cc claimed, nothing claimed that doesn't exist
    # (a fixture tree without native/ has nothing to claim; phantom
    # registry entries still fire below)
    native_dir = os.path.join(root, NATIVE_DIR)
    cc_files = sorted(fn for fn in (
        os.listdir(native_dir) if os.path.isdir(native_dir) else ())
        if fn.endswith(".cc"))
    claimed = guards.native_sources()
    for fn in cc_files:
        if fn not in claimed:
            violations.append(Violation(
                rule=RULE, path=f"{NATIVE_DIR}/{fn}", line=1,
                detail="unclaimed-native-source",
                message=f"native source {fn} has no ops/guards.py "
                        f"registry entry — every native twin needs a "
                        f"declared guard/breaker/kill-switch pairing"))
    for fn in claimed:
        if fn not in cc_files:
            violations.append(Violation(
                rule=RULE, path=GUARDS_PATH, line=1,
                detail=f"phantom-native-source:{fn}",
                message=f"registry claims native source {fn} which "
                        f"does not exist in {NATIVE_DIR}/"))

    # 2. per-entry structural checks
    seen_names = set()
    for entry in guards.REGISTRY:
        if entry.name in seen_names:
            violations.append(Violation(
                rule=RULE, path=GUARDS_PATH, line=1,
                detail=f"dup-entry:{entry.name}",
                message=f"duplicate registry entry {entry.name}"))
            continue
        seen_names.add(entry.name)

        mod_rel = _module_rel_path(entry.module)
        sf = by_path.get(mod_rel)
        if sf is None:
            violations.append(Violation(
                rule=RULE, path=GUARDS_PATH, line=1,
                detail=f"{entry.name}:missing-module",
                message=f"registry entry {entry.name} names module "
                        f"{entry.module} which is not in the tree"))
            continue

        if entry.guard_symbol is not None:
            if entry.guard_symbol not in _module_symbols(sf):
                violations.append(Violation(
                    rule=RULE, path=mod_rel, line=1,
                    detail=f"{entry.name}:missing-guard-symbol",
                    message=f"registry entry {entry.name} names guard "
                            f"symbol {entry.guard_symbol!r} which "
                            f"{entry.module} does not define"))
        elif not entry.waiver.strip():
            violations.append(Violation(
                rule=RULE, path=GUARDS_PATH, line=1,
                detail=f"{entry.name}:unjustified-no-guard",
                message=f"registry entry {entry.name} has no guard "
                        f"symbol and no written waiver — every twin "
                        f"is guarded or carries a justification"))

        if entry.breaker_feed and not _has_breaker_feed(sf):
            violations.append(Violation(
                rule=RULE, path=mod_rel, line=1,
                detail=f"{entry.name}:missing-breaker-feed",
                message=f"registry entry {entry.name} claims a "
                        f"breaker feed but {entry.module} contains "
                        f"no .record(False)/_note_mismatch call"))
        if not entry.breaker_feed and not entry.waiver.strip():
            violations.append(Violation(
                rule=RULE, path=GUARDS_PATH, line=1,
                detail=f"{entry.name}:unjustified-no-breaker",
                message=f"registry entry {entry.name} opts out of the "
                        f"breaker feed without a written waiver"))

        if not entry.kill_switches:
            violations.append(Violation(
                rule=RULE, path=GUARDS_PATH, line=1,
                detail=f"{entry.name}:no-kill-switch",
                message=f"registry entry {entry.name} declares no env "
                        f"kill-switch"))
        for knob_name in entry.kill_switches:
            if knob_name not in registered_knobs:
                violations.append(Violation(
                    rule=RULE, path=GUARDS_PATH, line=1,
                    detail=f"{entry.name}:unknown-kill:{knob_name}",
                    message=f"kill-switch {knob_name} is not declared "
                            f"in utils/knobs.py"))
        if (entry.guard_every_knob is not None
                and entry.guard_every_knob not in registered_knobs):
            violations.append(Violation(
                rule=RULE, path=GUARDS_PATH, line=1,
                detail=f"{entry.name}:unknown-cadence:"
                       f"{entry.guard_every_knob}",
                message=f"guard-cadence knob {entry.guard_every_knob} "
                        f"is not declared in utils/knobs.py"))
    return violations
