"""Task service/check registration against the catalog (reference:
command/agent/consul/client.go:87 ServiceClient; script checks via
DriverHandle exec, consul/script.go)."""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from ..structs import structs as s
from .catalog import (
    CHECK_CRITICAL,
    CHECK_PASSING,
    CatalogCheck,
    CatalogEntry,
    ServiceCatalog,
)


def make_task_service_id(alloc_id: str, task: str, svc_name: str) -> str:
    """(consul/client.go makeTaskServiceID convention)."""
    return f"_nomad-task-{alloc_id}-{task}-{svc_name}"


class ServiceClient:
    """Registers task services + checks, runs the check loops, and keeps
    the catalog in sync with task lifecycles."""

    def __init__(self, catalog: ServiceCatalog,
                 logger: Optional[logging.Logger] = None):
        self.catalog = catalog
        self.logger = logger or logging.getLogger("nomad_tpu.consul")
        self._l = threading.Lock()
        # check runner state: (service_id, check_id) -> spec dict
        self._checks: Dict[tuple, Dict] = {}
        self._by_task: Dict[tuple, List[str]] = {}  # (alloc, task) -> ids
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._check_loop,
                                        name="consul-checks", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- agent self-registration (agent.go:492) ------------------------

    def register_agent(self, role: str, address: str, port: int,
                       tags: Optional[List[str]] = None) -> None:
        """Register the agent itself: 'nomad' for servers (rpc port),
        'nomad-client' for clients (http port)."""
        name = "nomad" if role == "server" else "nomad-client"
        entry = CatalogEntry(
            id=f"_nomad-{role}-{address}-{port}",
            name=name, tags=tags or [role],
            address=address, port=port)
        self.catalog.register(entry)

    # -- task services (consul/client.go RegisterTask) -----------------

    def register_task(self, alloc: s.Allocation, task: s.Task,
                      address: str = "",
                      exec_fn: Optional[Callable] = None) -> List[str]:
        """Register every service of ``task``; ports resolve through the
        alloc's network offer port labels (client.go resolve via
        task resources).  ``exec_fn(cmd, args) -> (output, exit_code)``
        (the DriverHandle.exec_cmd shape) runs script checks inside the
        task (consul/script.go)."""
        ids: List[str] = []
        tr = alloc.task_resources.get(task.name)
        labels: Dict[str, int] = {}
        ip = address
        if tr is not None and tr.networks:
            offer = tr.networks[0]
            labels = offer.port_labels()
            ip = offer.ip or ip
        for svc in task.services or []:
            sid = make_task_service_id(alloc.id, task.name, svc.name)
            checks = []
            for i, chk in enumerate(svc.checks or []):
                cid = f"{sid}-check{i}"
                checks.append(CatalogCheck(
                    id=cid, name=chk.name or f"service: {svc.name} check",
                    type=chk.type,
                    status=chk.initial_status or CHECK_PASSING))
                with self._l:
                    self._checks[(sid, cid)] = {
                        "check": chk, "exec_fn": exec_fn,
                        "address": ip,
                        "port": labels.get(chk.port_label or svc.port_label, 0),
                        "next_run": time.monotonic() + chk.interval,
                    }
            entry = CatalogEntry(
                id=sid, name=svc.name, tags=list(svc.tags),
                address=ip, port=labels.get(svc.port_label, 0),
                checks=checks)
            self.catalog.register(entry)
            ids.append(sid)
        with self._l:
            self._by_task[(alloc.id, task.name)] = ids
        return ids

    def deregister_task(self, alloc_id: str, task_name: str) -> None:
        with self._l:
            ids = self._by_task.pop((alloc_id, task_name), [])
            for sid in ids:
                for key in [k for k in self._checks if k[0] == sid]:
                    del self._checks[key]
        for sid in ids:
            self.catalog.deregister(sid)

    # -- check execution (script/tcp/http; consul/script.go) -----------

    def _check_loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            due = []
            with self._l:
                for key, spec in self._checks.items():
                    if spec["next_run"] <= now:
                        spec["next_run"] = now + spec["check"].interval
                        due.append((key, dict(spec)))
            for (sid, cid), spec in due:
                status, output = self._run_check(spec)
                self.catalog.set_check_status(sid, cid, status, output)
            self._stop.wait(0.2)

    def _run_check(self, spec: Dict) -> tuple:
        chk: s.ServiceCheck = spec["check"]
        try:
            if chk.type == "script":
                exec_fn = spec.get("exec_fn")
                if exec_fn is None:
                    return CHECK_CRITICAL, "no exec available for script check"
                output, code = exec_fn(chk.command, chk.args)
                if isinstance(output, bytes):
                    output = output.decode("utf-8", "replace")
                return (CHECK_PASSING if code == 0 else CHECK_CRITICAL,
                        str(output)[:256])
            if chk.type == "tcp":
                with socket.create_connection(
                        (spec["address"] or "127.0.0.1", spec["port"]),
                        timeout=chk.timeout):
                    return CHECK_PASSING, "tcp connect ok"
            if chk.type == "http":
                import urllib.request
                proto = chk.protocol or "http"
                url = (f"{proto}://{spec['address'] or '127.0.0.1'}:"
                       f"{spec['port']}{chk.path or '/'}")
                with urllib.request.urlopen(url, timeout=chk.timeout) as r:
                    ok = 200 <= r.status < 300
                    return (CHECK_PASSING if ok else CHECK_CRITICAL,
                            f"HTTP {r.status}")
            # Consul rejects unknown check types at registration; never
            # report an un-runnable check as healthy.
            return CHECK_CRITICAL, f"unknown check type {chk.type!r}"
        except Exception as e:
            return CHECK_CRITICAL, str(e)
