from .catalog import CatalogEntry, ServiceCatalog
from .service_client import ServiceClient

__all__ = ["CatalogEntry", "ServiceCatalog", "ServiceClient"]
