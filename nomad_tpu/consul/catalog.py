"""Consul-shaped service catalog.

The reference delegates service registration to an external Consul agent
(command/agent/consul/client.go) and discovers servers through Consul's
catalog (client/client.go:2139 consulDiscovery).  This build ships an
internal catalog with the same shape: services keyed by ID with name/tags/
address/port and per-check health, queryable by service name — surfaced
over the agent HTTP API (/v1/catalog/...) so other agents can discover
through it exactly like a Consul endpoint.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

CHECK_PASSING = "passing"
CHECK_WARNING = "warning"
CHECK_CRITICAL = "critical"


@dataclass
class CatalogCheck:
    id: str = ""
    name: str = ""
    type: str = ""          # http | tcp | script | ttl
    status: str = CHECK_PASSING
    output: str = ""


@dataclass
class CatalogEntry:
    id: str = ""
    name: str = ""
    tags: List[str] = field(default_factory=list)
    address: str = ""
    port: int = 0
    checks: List[CatalogCheck] = field(default_factory=list)
    registered_at: float = field(default_factory=time.time)

    def healthy(self) -> bool:
        return all(c.status != CHECK_CRITICAL for c in self.checks)

    def to_wire(self) -> Dict:
        return {
            "ID": self.id, "Service": self.name, "Tags": list(self.tags),
            "Address": self.address, "Port": self.port,
            "Checks": [{"CheckID": c.id, "Name": c.name, "Type": c.type,
                        "Status": c.status, "Output": c.output}
                       for c in self.checks],
        }


class ServiceCatalog:
    """Thread-safe service registry + KV (the catalog and KV halves of
    Consul's API — the KV side feeds task templates exactly as
    consul-template reads Consul KV)."""

    def __init__(self) -> None:
        self._l = threading.Lock()
        self._entries: Dict[str, CatalogEntry] = {}
        self._kv: Dict[str, str] = {}
        self._kv_index = 0
        self._generation = 0  # bumps on ANY mutation (KV or services)

    # -- KV (consul-template's `key` function source) ------------------

    def kv_set(self, key: str, value: str) -> int:
        with self._l:
            self._kv[key] = value
            self._kv_index += 1
            self._generation += 1
            return self._kv_index

    def kv_get(self, key: str) -> Optional[str]:
        with self._l:
            return self._kv.get(key)

    def kv_delete(self, key: str) -> None:
        with self._l:
            self._kv.pop(key, None)
            self._kv_index += 1
            self._generation += 1

    def kv_list(self, prefix: str = "") -> Dict[str, str]:
        with self._l:
            return {k: v for k, v in self._kv.items()
                    if k.startswith(prefix)}

    def kv_index(self) -> int:
        """Monotonic modify index — template watchers poll it for change
        detection (Consul's X-Consul-Index role)."""
        with self._l:
            return self._kv_index

    def register(self, entry: CatalogEntry) -> None:
        with self._l:
            self._entries[entry.id] = entry
            self._generation += 1

    def deregister(self, service_id: str) -> None:
        with self._l:
            self._entries.pop(service_id, None)
            self._generation += 1

    def entry(self, service_id: str) -> Optional[CatalogEntry]:
        with self._l:
            return self._entries.get(service_id)

    def services(self) -> Dict[str, List[str]]:
        """name → union of tags (GET /v1/catalog/services shape)."""
        out: Dict[str, List[str]] = {}
        with self._l:
            for e in self._entries.values():
                tags = out.setdefault(e.name, [])
                for t in e.tags:
                    if t not in tags:
                        tags.append(t)
        return out

    def service(self, name: str, tag: str = "",
                healthy_only: bool = False) -> List[CatalogEntry]:
        with self._l:
            out = [e for e in self._entries.values() if e.name == name]
        if tag:
            out = [e for e in out if tag in e.tags]
        if healthy_only:
            out = [e for e in out if e.healthy()]
        return sorted(out, key=lambda e: e.id)

    def set_check_status(self, service_id: str, check_id: str,
                         status: str, output: str = "") -> None:
        with self._l:
            e = self._entries.get(service_id)
            if e is None:
                return
            for c in e.checks:
                if c.id == check_id:
                    if c.status != status:
                        self._generation += 1
                    c.status = status
                    c.output = output

    def generation(self) -> int:
        """Monotonic mutation counter across KV + services — template
        watchers poll it to short-circuit unchanged polls."""
        with self._l:
            return self._generation

    def ids(self) -> List[str]:
        with self._l:
            return sorted(self._entries)
