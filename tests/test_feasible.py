"""Feasibility checker unit tests (reference: scheduler/feasible_test.go)."""
import logging
import random

from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import (
    ConstraintChecker,
    DriverChecker,
    StaticIterator,
    check_constraint,
    resolve_constraint_target,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import structs as s


def ctx():
    store = StateStore()
    plan = s.Plan()
    return EvalContext(store, plan, logging.getLogger("test"), rng=random.Random(1))


class TestResolveTarget:
    def test_literal(self):
        node = mock.node()
        assert resolve_constraint_target("linux", node) == ("linux", True)

    def test_node_interpolations(self):
        node = mock.node()
        assert resolve_constraint_target("${node.unique.id}", node) == (node.id, True)
        assert resolve_constraint_target("${node.datacenter}", node) == ("dc1", True)
        assert resolve_constraint_target("${node.unique.name}", node) == ("foobar", True)
        assert resolve_constraint_target("${node.class}", node) == ("linux-medium-pci", True)

    def test_attr_meta(self):
        node = mock.node()
        assert resolve_constraint_target("${attr.kernel.name}", node) == ("linux", True)
        assert resolve_constraint_target("${meta.pci-dss}", node) == ("true", True)
        assert resolve_constraint_target("${attr.nope}", node) == (None, False)
        assert resolve_constraint_target("${meta.nope}", node) == (None, False)

    def test_unknown_interpolation(self):
        node = mock.node()
        assert resolve_constraint_target("${env.whatever}", node) == (None, False)


class TestCheckConstraint:
    def test_equality(self):
        c = ctx()
        assert check_constraint(c, "=", "a", "a")
        assert check_constraint(c, "==", "a", "a")
        assert check_constraint(c, "is", "a", "a")
        assert not check_constraint(c, "=", "a", "b")
        assert check_constraint(c, "!=", "a", "b")
        assert check_constraint(c, "not", "a", "b")

    def test_lexical(self):
        c = ctx()
        assert check_constraint(c, "<", "abc", "abd")
        assert check_constraint(c, "<=", "abc", "abc")
        assert check_constraint(c, ">", "b", "a")
        assert check_constraint(c, ">=", "b", "b")
        assert not check_constraint(c, "<", "b", "a")
        # non-strings fail
        assert not check_constraint(c, "<", None, "a")

    def test_version(self):
        c = ctx()
        assert check_constraint(c, s.CONSTRAINT_VERSION, "0.5.0", ">= 0.4, < 0.6")
        assert check_constraint(c, s.CONSTRAINT_VERSION, "1.2.3", "~> 1.2")
        assert not check_constraint(c, s.CONSTRAINT_VERSION, "2.0", "~> 1.2")
        assert not check_constraint(c, s.CONSTRAINT_VERSION, "garbage", ">= 1.0")
        assert not check_constraint(c, s.CONSTRAINT_VERSION, "1.0", "garbage >=")

    def test_regexp(self):
        c = ctx()
        assert check_constraint(c, s.CONSTRAINT_REGEX, "linux-4.9", r"^linux-\d")
        assert not check_constraint(c, s.CONSTRAINT_REGEX, "windows", r"^linux")
        assert not check_constraint(c, s.CONSTRAINT_REGEX, "x", "[invalid(")
        # cache reuse: second call hits the cache
        assert check_constraint(c, s.CONSTRAINT_REGEX, "linux-5", r"^linux-\d")
        assert len(c.cache.re_cache) == 3

    def test_set_contains(self):
        c = ctx()
        assert check_constraint(c, s.CONSTRAINT_SET_CONTAINS, "a,b,c", "a,c")
        assert check_constraint(c, s.CONSTRAINT_SET_CONTAINS, "a, b, c ", "b")
        assert not check_constraint(c, s.CONSTRAINT_SET_CONTAINS, "a,b", "a,d")

    def test_distinct_operands_pass_through(self):
        c = ctx()
        assert check_constraint(c, s.CONSTRAINT_DISTINCT_HOSTS, None, None)
        assert check_constraint(c, s.CONSTRAINT_DISTINCT_PROPERTY, "x", "y")

    def test_unknown_operand(self):
        assert not check_constraint(ctx(), "@@", "a", "a")


class TestDriverChecker:
    def test_has_driver(self):
        c = ctx()
        checker = DriverChecker(c, {"exec"})
        assert checker.feasible(mock.node())

    def test_missing_driver(self):
        c = ctx()
        checker = DriverChecker(c, {"docker"})
        node = mock.node()
        assert not checker.feasible(node)
        assert c.metrics.nodes_filtered == 1
        assert c.metrics.constraint_filtered["missing drivers"] == 1

    def test_disabled_driver(self):
        c = ctx()
        node = mock.node()
        node.attributes["driver.docker"] = "0"
        checker = DriverChecker(c, {"docker"})
        assert not checker.feasible(node)

    def test_invalid_driver_value(self):
        c = ctx()
        node = mock.node()
        node.attributes["driver.docker"] = "yes-ish"
        checker = DriverChecker(c, {"docker"})
        assert not checker.feasible(node)


class TestConstraintChecker:
    def test_passes_all(self):
        c = ctx()
        checker = ConstraintChecker(c, [
            s.Constraint("${attr.kernel.name}", "linux", "="),
            s.Constraint("${node.datacenter}", "dc1", "="),
        ])
        assert checker.feasible(mock.node())

    def test_fails_and_records_metric(self):
        c = ctx()
        constraint = s.Constraint("${attr.kernel.name}", "windows", "=")
        checker = ConstraintChecker(c, [constraint])
        assert not checker.feasible(mock.node())
        assert c.metrics.constraint_filtered[str(constraint)] == 1

    def test_missing_target_fails(self):
        c = ctx()
        checker = ConstraintChecker(c, [s.Constraint("${attr.gone}", "x", "!=")])
        assert not checker.feasible(mock.node())


class TestStaticIterator:
    def test_yields_all_then_none(self):
        c = ctx()
        nodes = [mock.node() for _ in range(3)]
        it = StaticIterator(c, nodes)
        seen = []
        while True:
            n = it.next_option()
            if n is None:
                break
            seen.append(n)
        assert seen == nodes
        assert c.metrics.nodes_evaluated == 3

    def test_reset_wraps_offset(self):
        c = ctx()
        nodes = [mock.node() for _ in range(3)]
        it = StaticIterator(c, nodes)
        first = it.next_option()
        it.reset()
        # after reset, continues from offset then wraps to serve all 3
        got = [it.next_option() for _ in range(3)]
        assert None not in got
        assert {n.id for n in got} == {n.id for n in nodes}
