"""Agent + HTTP API + SDK tests (reference: command/agent/*_endpoint_test.go,
api/*_test.go against an in-process agent)."""

import threading
import time

import pytest

import conftest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig, parse_config
from nomad_tpu.api import APIError, NomadAPI, QueryOptions
from nomad_tpu.structs import structs as s


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    cfg = conftest.dev_test_config()
    tmp = tmp_path_factory.mktemp("agent")
    cfg.client.alloc_dir = str(tmp / "allocs")
    cfg.client.state_dir = str(tmp / "state")
    a = Agent(cfg)
    a.start()
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return NomadAPI(agent.http.address)


def exec_job(count=1):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = count
    for t in tg.tasks:
        t.driver = "mock_driver"
        t.config = {"run_for": "20s"}
        t.resources = s.Resources(cpu=20, memory_mb=16)
        t.services = []
    return job


class TestJobEndpoints:
    def test_register_list_info(self, api):
        job = exec_job()
        resp, meta = api.jobs.register(job)
        assert resp["EvalID"]
        assert meta.last_index > 0

        jobs, meta = api.jobs.list()
        assert any(j["ID"] == job.id for j in jobs)
        assert meta.last_index > 0

        info, _ = api.jobs.info(job.id)
        assert info.id == job.id
        assert info.task_groups[0].tasks[0].driver == "mock_driver"

    def test_info_missing_404(self, api):
        with pytest.raises(APIError) as ei:
            api.jobs.info("does-not-exist")
        assert ei.value.code == 404

    def test_allocations_and_evaluations(self, api):
        job = exec_job()
        api.jobs.register(job)
        assert wait_until(lambda: len(api.jobs.allocations(job.id)[0]) == 1)
        allocs, _ = api.jobs.allocations(job.id)
        assert allocs[0]["JobID"] == job.id
        evals, _ = api.jobs.evaluations(job.id)
        assert evals[0]["JobID" if isinstance(evals[0], dict) else "job_id"] \
            == job.id if isinstance(evals[0], dict) else True

    def test_summary(self, api):
        job = exec_job()
        api.jobs.register(job)
        assert wait_until(lambda: len(api.jobs.allocations(job.id)[0]) == 1)
        summary, _ = api.jobs.summary(job.id)
        assert summary.job_id == job.id
        assert job.task_groups[0].name in summary.summary

    def test_plan(self, api):
        job = exec_job(count=2)
        resp, _ = api.jobs.plan(job)
        assert resp.diff is not None
        assert resp.annotations is not None
        tg = job.task_groups[0].name
        assert resp.annotations.desired_tg_updates[tg].place == 2

    def test_validate(self, api):
        job = exec_job()
        resp, _ = api.jobs.validate(job)
        assert resp["ValidationErrors"] == []
        bad = exec_job()
        bad.task_groups[0].tasks[0].driver = ""
        resp, _ = api.jobs.validate(bad)
        assert resp["ValidationErrors"]

    def test_deregister(self, api):
        job = exec_job()
        api.jobs.register(job)
        resp, _ = api.jobs.deregister(job.id)
        assert resp["EvalID"]
        with pytest.raises(APIError) as ei:
            api.jobs.info(job.id)
        assert ei.value.code == 404

    def test_evaluate(self, api):
        job = exec_job()
        api.jobs.register(job)
        resp, _ = api.jobs.evaluate(job.id)
        assert resp["EvalID"]

    def test_dispatch_parameterized(self, api):
        job = exec_job()
        job.parameterized_job = s.ParameterizedJobConfig(
            payload="required", meta_required=["who"])
        api.jobs.register(job)
        resp, _ = api.jobs.dispatch(job.id, payload=b"hello",
                                    meta={"who": "world"})
        child_id = resp["DispatchedJobID"]
        assert child_id.startswith(job.id + "/dispatch-")
        info, _ = api.jobs.info(child_id)
        assert info.parent_id == job.id
        assert info.meta["who"] == "world"

        with pytest.raises(APIError) as ei:
            api.jobs.dispatch(job.id, payload=b"x", meta={})
        assert ei.value.code == 400  # missing required meta


class TestNodeEndpoints:
    def test_node_list_info(self, api, agent):
        assert wait_until(lambda: len(api.nodes.list()[0]) >= 1)
        nodes, meta = api.nodes.list()
        node_id = nodes[0]["ID"]
        assert meta.last_index > 0
        node, _ = api.nodes.info(node_id)
        assert node.id == node_id
        assert node.status == s.NODE_STATUS_READY

    def test_node_allocations(self, api):
        nodes, _ = api.nodes.list()
        node_id = nodes[0]["ID"]
        allocs, _ = api.nodes.allocations(node_id)
        assert isinstance(allocs, list)

    def test_drain_and_evaluate(self, api, agent):
        nodes, _ = api.nodes.list()
        node_id = nodes[0]["ID"]
        resp, _ = api.nodes.toggle_drain(node_id, True)
        assert resp["NodeModifyIndex"] > 0
        node, _ = api.nodes.info(node_id)
        assert node.drain is True
        api.nodes.toggle_drain(node_id, False)
        resp, _ = api.nodes.force_evaluate(node_id)
        assert "EvalIDs" in resp


class TestAllocEvalEndpoints:
    def test_alloc_info(self, api):
        job = exec_job()
        api.jobs.register(job)
        assert wait_until(lambda: len(api.jobs.allocations(job.id)[0]) == 1)
        stub = api.jobs.allocations(job.id)[0][0]
        alloc, _ = api.allocations.info(stub["ID"])
        assert alloc.id == stub["ID"]
        assert alloc.job_id == job.id
        allocs, _ = api.allocations.list()
        assert any(a["ID"] == stub["ID"] for a in allocs)

    def test_eval_info_and_allocs(self, api):
        job = exec_job()
        resp, _ = api.jobs.register(job)
        eval_id = resp["EvalID"]
        ev, _ = api.evaluations.info(eval_id)
        assert ev.id == eval_id
        assert wait_until(
            lambda: len(api.evaluations.allocations(eval_id)[0]) == 1)
        evals, _ = api.evaluations.list()
        assert any(e.id == eval_id for e in evals)


class TestBlockingQueries:
    def test_job_list_blocks_until_change(self, api):
        _, meta = api.jobs.list()
        index = meta.last_index
        results = {}

        def poll():
            jobs, m = api.jobs.list(QueryOptions(wait_index=index,
                                                 wait_time=10.0))
            results["index"] = m.last_index

        t = threading.Thread(target=poll)
        t.start()
        time.sleep(0.3)
        assert t.is_alive()  # long-poll is holding
        api.jobs.register(exec_job())
        t.join(timeout=10)
        assert not t.is_alive()
        assert results["index"] > index

    def test_wait_timeout_returns(self, api):
        _, meta = api.jobs.list()
        start = time.monotonic()
        _, m2 = api.jobs.list(QueryOptions(wait_index=meta.last_index + 1000,
                                           wait_time=1.0))
        elapsed = time.monotonic() - start
        assert 0.9 <= elapsed < 5.0


class TestClientEndpoints:
    def test_client_stats(self, api):
        stats = api.agent.client_stats()
        assert "node_id" in stats

    def test_fs_and_logs(self, api):
        job = exec_job()
        # mock driver writes stdout messages
        job.task_groups[0].tasks[0].config = {
            "run_for": "20s", "stdout_string": "hello from task\n"}
        api.jobs.register(job)
        assert wait_until(lambda: len(api.jobs.allocations(job.id)[0]) == 1)
        alloc_id = api.jobs.allocations(job.id)[0][0]["ID"]
        assert wait_until(lambda: api.jobs.allocations(job.id)[0][0]
                          ["ClientStatus"] in ("running", "complete"))
        ls = api.agent.fs_list(alloc_id, "/")
        assert isinstance(ls, list)
        stats = api.agent.alloc_stats(alloc_id)
        assert "ResourceUsage" in stats

    def test_fs_unknown_alloc_404(self, api):
        with pytest.raises(APIError) as ei:
            api.agent.fs_list("00000000-0000-0000-0000-000000000000")
        assert ei.value.code == 404


class TestAgentSystemEndpoints:
    def test_agent_self(self, api):
        info = api.agent.self_info()
        assert info["config"]["Server"]["Enabled"] is True
        assert info["config"]["Client"]["Enabled"] is True
        assert info["stats"]["nomad"]

    def test_members(self, api):
        members = api.agent.members()
        assert len(members["Members"]) == 1
        assert members["Members"][0]["Status"] == "alive"

    def test_status(self, api):
        assert api.status.leader()
        assert len(api.status.peers()) == 1

    def test_regions(self, api):
        obj, _ = api.get("/v1/regions") if hasattr(api, "get") else (None, None)
        obj, _ = api._do("GET", "/v1/regions")
        assert obj == ["global"]

    def test_operator_raft_configuration(self, api):
        conf = api.operator.raft_get_configuration()
        assert conf["Servers"][0]["Leader"] is True

    def test_system_gc(self, api):
        api.system.garbage_collect()
        api.system.reconcile_summaries()

    def test_keyring_http(self, api, agent, tmp_path):
        """/v1/agent/keyring/{list,install,use,remove}
        (command/agent/http.go:158, agent_endpoint.go:166)."""
        import base64

        agent.config.data_dir = str(tmp_path)
        k1 = base64.b64encode(bytes(range(32))).decode()
        k2 = base64.b64encode(bytes(range(1, 33))).decode()
        resp, _ = api._do("PUT", "/v1/agent/keyring/install", {"Key": k1})
        assert resp["Keys"] == {k1: 1}
        assert resp["PrimaryKeys"] == {k1: 1}
        api._do("PUT", "/v1/agent/keyring/install", {"Key": k2})
        resp, _ = api._do("GET", "/v1/agent/keyring/list")
        assert set(resp["Keys"]) == {k1, k2}
        # The primary key is protected from removal.
        with pytest.raises(APIError) as ei:
            api._do("PUT", "/v1/agent/keyring/remove", {"Key": k1})
        assert ei.value.code == 400
        api._do("PUT", "/v1/agent/keyring/use", {"Key": k2})
        resp, _ = api._do("PUT", "/v1/agent/keyring/remove", {"Key": k1})
        assert resp["Keys"] == {k2: 1}
        assert resp["PrimaryKeys"] == {k2: 1}
        with pytest.raises(APIError) as ei:
            api._do("PUT", "/v1/agent/keyring/install", {"Key": "short"})
        assert ei.value.code == 400
        with pytest.raises(APIError) as ei:
            api._do("GET", "/v1/agent/keyring/bogus")
        assert ei.value.code == 404
        with pytest.raises(APIError) as ei:
            api._do("GET", "/v1/agent/keyring/install")
        assert ei.value.code == 405

    def test_unknown_url_404(self, api):
        with pytest.raises(APIError) as ei:
            api._do("GET", "/v1/bogus")
        assert ei.value.code == 404

    def test_method_not_allowed(self, api):
        with pytest.raises(APIError) as ei:
            api._do("DELETE", "/v1/nodes")
        assert ei.value.code == 405


class TestAgentConfigParse:
    def test_hcl_config(self):
        cfg = parse_config('''
region     = "euw"
datacenter = "dc7"
data_dir   = "/tmp/nomad"
ports {
  http = 5646
}
server {
  enabled        = true
  num_schedulers = 4
}
client {
  enabled = true
  servers = ["1.2.3.4:4647"]
  meta {
    rack = "r1"
  }
}
''')
        assert cfg.region == "euw"
        assert cfg.datacenter == "dc7"
        assert cfg.ports.http == 5646
        assert cfg.server.enabled is True
        assert cfg.server.num_schedulers == 4
        assert cfg.client.enabled is True
        assert cfg.client.servers == ["1.2.3.4:4647"]
        assert cfg.client.meta == {"rack": "r1"}

    def test_json_config(self):
        cfg = parse_config(
            '{"region": "ap", "ports": {"http": 7777},'
            ' "server": {"enabled": true}}')
        assert cfg.region == "ap"
        assert cfg.ports.http == 7777
        assert cfg.server.enabled is True

    def test_env_var_interpolation(self, monkeypatch):
        """config_parse.go: values expand ${VAR}/$VAR from the
        environment; unknown names stay verbatim (VERDICT r4 #8)."""
        monkeypatch.setenv("NOMAD_TEST_REGION", "apse")
        monkeypatch.setenv("NOMAD_TEST_DATA", "/srv/nomad")
        cfg = parse_config('''
region   = "${NOMAD_TEST_REGION}"
data_dir = "$NOMAD_TEST_DATA/agent"
client {
  enabled = true
  meta {
    placeholder = "${NOT_SET_ANYWHERE_XYZ}"
  }
}
''')
        assert cfg.region == "apse"
        assert cfg.data_dir == "/srv/nomad/agent"
        # Unknown names survive so runtime-interpolated strings pass
        # through the agent config unharmed.
        assert cfg.client.meta["placeholder"] == "${NOT_SET_ANYWHERE_XYZ}"

    def test_json_nested_values_expand(self, monkeypatch):
        """JSON configs expand env vars inside nested lists/maps the
        same as the HCL helpers."""
        monkeypatch.setenv("NOMAD_TEST_SRV", "10.1.2.3")
        monkeypatch.setenv("NOMAD_TEST_RACK", "r9")
        cfg = parse_config(
            '{"client": {"enabled": true,'
            ' "servers": ["${NOMAD_TEST_SRV}:4647"],'
            ' "meta": {"rack": "$NOMAD_TEST_RACK"}}}')
        assert cfg.client.servers == ["10.1.2.3:4647"]
        assert cfg.client.meta["rack"] == "r9"

    def test_env_value_cannot_inject_config(self, monkeypatch):
        """Expansion happens on parsed VALUES, never raw file bytes: a
        value full of quotes/newlines/braces lands verbatim in the
        field instead of corrupting or injecting config syntax."""
        evil = 'x" }\nserver { enabled = true }\nregion = "pwned'
        monkeypatch.setenv("NOMAD_TEST_EVIL", evil)
        cfg = parse_config('datacenter = "${NOMAD_TEST_EVIL}"')
        assert cfg.datacenter == evil
        assert cfg.server.enabled is False
        assert cfg.region == "global"

    def test_sockaddr_template_bind_addr(self):
        """config.go:787 parseSingleIPTemplate subset: bind_addr
        accepts go-sockaddr templates."""
        cfg = parse_config('bind_addr = "{{ GetInterfaceIP \\"lo\\" }}"')
        assert cfg.bind_addr == "127.0.0.1"
        # Plain addresses pass through untouched.
        assert parse_config('bind_addr = "0.0.0.0"').bind_addr == "0.0.0.0"
        with pytest.raises(ValueError):
            parse_config('bind_addr = "{{ GetMagicIP }}"')

    def test_sockaddr_template_advertise_and_addresses(self):
        """ADVICE r5 config.py:274: templates resolve in the
        advertise{} and addresses{} blocks too (config_parse.go runs
        parseSingleIPTemplate over all of them), in both the HCL and
        JSON paths — a templated advertise address must never pass
        through literally to bind/gossip time."""
        cfg = parse_config('''
addresses {
  http = "{{ GetInterfaceIP \\"lo\\" }}"
}
advertise {
  rpc  = "{{ GetInterfaceIP \\"lo\\" }}:4647"
  serf = "10.9.8.7:4648"
}
''')
        assert cfg.addresses.http == "127.0.0.1"
        assert cfg.advertise.rpc == "127.0.0.1:4647"
        assert cfg.advertise.serf == "10.9.8.7:4648"  # literal untouched
        jcfg = parse_config(
            '{"advertise": {"rpc": "{{ GetInterfaceIP \\"lo\\" }}:4647"},'
            ' "addresses": {"http": "{{ GetInterfaceIP \\"lo\\" }}"}}')
        assert jcfg.advertise.rpc == "127.0.0.1:4647"
        assert jcfg.addresses.http == "127.0.0.1"
        with pytest.raises(ValueError):
            parse_config('advertise { rpc = "{{ GetMagicIP }}:4647" }')

    def test_advertise_rpc_feeds_server_config(self):
        """An explicit advertise.rpc becomes the server's advertised RPC
        address (agent.go setupServer + config.go AdvertiseAddrs)."""
        from nomad_tpu.agent import Agent

        cfg = conftest.dev_test_config()
        cfg.client.enabled = False
        cfg.advertise.rpc = "127.0.0.1"  # port defaults from ports.rpc
        a = Agent(cfg)
        a.start()
        try:
            host = a.server.config.rpc_advertise.rsplit(":", 1)[0]
            assert host == "127.0.0.1"
        finally:
            a.shutdown()


class TestAgentMonitor:
    def test_monitor_streams_backlog_and_live_lines(self, agent):
        import json
        import threading
        import urllib.request

        agent.logger.info("before-monitor marker")
        lines = []
        lock = threading.Lock()

        def run():
            try:
                with urllib.request.urlopen(
                        agent.http.address + "/v1/agent/monitor",
                        timeout=30) as resp:
                    for raw in resp:
                        frame = json.loads(raw)
                        if frame.get("Data"):
                            import base64
                            with lock:
                                lines.append(
                                    base64.b64decode(frame["Data"]).decode())
            except Exception:
                pass

        t = threading.Thread(target=run, daemon=True)
        t.start()

        def text():
            with lock:
                return "".join(lines)

        deadline = time.time() + 10
        while time.time() < deadline and "before-monitor" not in text():
            time.sleep(0.05)
        assert "before-monitor marker" in text(), "backlog line not streamed"
        agent.logger.info("after-monitor marker")
        deadline = time.time() + 10
        while time.time() < deadline and "after-monitor" not in text():
            time.sleep(0.05)
        assert "after-monitor marker" in text(), "live line not streamed"


class TestBrokerStatsEndpoint:
    def test_broker_stats_shape(self, api):
        """/v1/broker/stats (ISSUE 7 satellite): the saturation surface
        the load harness polls, served over HTTP + SDK."""
        stats = api.system.broker_stats()
        for key in ("Enabled", "Pending", "MaxPending", "ByState",
                    "ByPriority", "DeliveryAttempts", "ShedTotal",
                    "CoalescedTotal", "AdmissionRejects",
                    "PlanQueueDepth", "BlockedEvals"):
            assert key in stats, key
        assert set(stats["ByState"]) == {"ready", "unacked", "deferred",
                                         "waiting", "failed"}

    def test_admission_nack_maps_to_429_with_retry_after(self, agent, api):
        """A saturated broker answers job submissions with 429 +
        Retry-After; the SDK surfaces both."""
        broker = agent.server.eval_broker
        prev = broker.max_pending
        broker.max_pending = 1
        # Deterministic saturation: plant one tracked pending eval (a
        # live worker would drain a real one before the assert).
        with broker._l:
            broker.evals["fake-saturation"] = 0
        try:
            with pytest.raises(APIError) as exc:
                api.jobs.register(exec_job())
            assert exc.value.code == 429
            assert exc.value.retry_after > 0
        finally:
            broker.max_pending = prev
            with broker._l:
                broker.evals.pop("fake-saturation", None)
