"""Differential tests: TPU batch scheduler vs CPU oracle
(SURVEY.md §4 item 5 — Go-oracle-vs-kernel on randomized cluster states).

Runs on the virtual CPU backend (conftest sets JAX_PLATFORMS=cpu)."""
import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops import batch_sched  # registers 'tpu-batch'
from nomad_tpu.ops import encode
from nomad_tpu.ops.kernels import batch_allocs_fit, feasibility_matrix, placement_rounds
from nomad_tpu.scheduler import Harness, new_scheduler, new_service_scheduler
from nomad_tpu.structs import structs as s
from nomad_tpu.structs.funcs import allocs_fit, score_fit

import jax
import jax.numpy as jnp


def reg_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def strip_networks(job):
    """Network offers stay host-side; the device kernel handles the 4 scalar
    dims. Bench/differential jobs use scalar resources only (configs (b))."""
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.networks = []
    return job


def make_cluster(h, n, seed=0, hetero=False):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node()
        node.resources.networks = []
        node.reserved.networks = []
        if hetero:
            node.resources.cpu = rng.choice([2000, 4000, 8000])
            node.resources.memory_mb = rng.choice([4096, 8192, 16384])
        if hetero and rng.random() < 0.3:
            node.attributes["kernel.name"] = "windows"
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)
        nodes.append(node)
    return nodes


class TestFeasibilityKernel:
    def _encode(self, nodes, specs):
        targets, literals = encode.collect_attr_targets(specs)
        ct = encode.encode_cluster(nodes, targets)
        encode.finalize_codebooks(ct, literals)
        st = encode.encode_specs(specs, ct, nodes)
        return ct, st

    def _feas(self, ct, st):
        return np.asarray(feasibility_matrix(
            jnp.asarray(ct.attr_values), jnp.asarray(ct.eligible),
            jnp.asarray(ct.dc_code), jnp.asarray(st.constraint_attr),
            jnp.asarray(st.constraint_op), jnp.asarray(st.constraint_rhs),
            jnp.asarray(st.dc_mask), jnp.asarray(st.precomp)))

    def test_matches_oracle_on_random_constraints(self):
        rng = random.Random(42)
        nodes = []
        for i in range(64):
            n = mock.node()
            n.attributes["kernel.name"] = rng.choice(["linux", "windows", "darwin"])
            n.attributes["cpu.arch"] = rng.choice(["amd64", "arm64"])
            n.attributes["os.version"] = rng.choice(["14.04", "16.04", "18.04"])
            n.meta["rack"] = f"r{rng.randrange(8)}"
            n.datacenter = rng.choice(["dc1", "dc2"])
            n.compute_class()
            nodes.append(n)

        constraint_pool = [
            s.Constraint("${attr.kernel.name}", "linux", "="),
            s.Constraint("${attr.kernel.name}", "windows", "!="),
            s.Constraint("${attr.cpu.arch}", "amd64", "="),
            s.Constraint("${attr.os.version}", "16.04", ">="),
            s.Constraint("${attr.os.version}", "18.04", "<"),
            s.Constraint("${meta.rack}", "r4", "<="),
            s.Constraint("${attr.nomad.version}", ">= 0.4", s.CONSTRAINT_VERSION),
            s.Constraint("${attr.kernel.name}", "lin.*", s.CONSTRAINT_REGEX),
            s.Constraint("${meta.rack}", "r1,r2,r3", s.CONSTRAINT_SET_CONTAINS),
            s.Constraint("${meta.missing-key}", "x", "="),
        ]

        specs = []
        for i in range(12):
            job = mock.job()
            strip_networks(job)
            job.datacenters = rng.choice([["dc1"], ["dc2"], ["dc1", "dc2"]])
            job.constraints = rng.sample(constraint_pool, rng.randrange(0, 4))
            tg = job.task_groups[0]
            tg.constraints = rng.sample(constraint_pool, rng.randrange(0, 2))
            specs.append(encode.build_spec(job, tg, batch_penalty=False))

        ct, st = self._encode(nodes, specs)
        feas = self._feas(ct, st)

        # Oracle: evaluate each (spec, node) with the scalar checkers.
        from nomad_tpu.scheduler.context import EvalContext
        from nomad_tpu.scheduler.feasible import check_constraint, resolve_constraint_target

        ctx = EvalContext(None, s.Plan())
        for u, sp in enumerate(specs):
            for i, node in enumerate(nodes):
                expect = node.ready() and node.datacenter in sp.datacenters
                if expect:
                    for driver in sp.drivers:
                        val = node.attributes.get(f"driver.{driver}")
                        if val is None or val not in ("1", "true", "True", "t", "T", "TRUE"):
                            expect = False
                if expect:
                    for con in sp.constraints:
                        if con.operand in (s.CONSTRAINT_DISTINCT_HOSTS,
                                           s.CONSTRAINT_DISTINCT_PROPERTY):
                            continue
                        lval, lok = resolve_constraint_target(con.ltarget, node)
                        rval, rok = resolve_constraint_target(con.rtarget, node)
                        if not (lok and rok and check_constraint(
                                ctx, con.operand, lval, rval)):
                            expect = False
                            break
                assert feas[u, i] == expect, (
                    f"spec {u} node {i}: kernel={feas[u, i]} oracle={expect} "
                    f"constraints={[str(c) for c in sp.constraints]} "
                    f"dcs={sp.datacenters} node_dc={node.datacenter}")

    def test_padding_rows_infeasible(self):
        nodes = [mock.node() for _ in range(3)]
        job = strip_networks(mock.job())
        specs = [encode.build_spec(job, job.task_groups[0], False)]
        ct, st = self._encode(nodes, specs)
        feas = self._feas(ct, st)
        assert feas[:, ct.n_real:].sum() == 0


class TestScoreParity:
    def test_device_score_matches_scalar(self):
        """score_fit on device must match the scalar oracle bit-for-bit-ish."""
        from nomad_tpu.ops.kernels import _score_fit

        rng = random.Random(7)
        for _ in range(50):
            cap_cpu, cap_mem = rng.randrange(1000, 8000), rng.randrange(1024, 16384)
            res_cpu, res_mem = rng.randrange(0, 400), rng.randrange(0, 512)
            used_cpu = rng.randrange(0, cap_cpu)
            used_mem = rng.randrange(0, cap_mem)
            ask_cpu, ask_mem = rng.randrange(0, 500), rng.randrange(0, 512)

            node = s.Node(resources=s.Resources(cpu=cap_cpu, memory_mb=cap_mem),
                          reserved=s.Resources(cpu=res_cpu, memory_mb=res_mem))
            util = s.Resources(cpu=used_cpu + ask_cpu + res_cpu,
                               memory_mb=used_mem + ask_mem + res_mem)
            expect = score_fit(node, util)

            used = jnp.asarray([[used_cpu + res_cpu, used_mem + res_mem, 0, 0]],
                               dtype=jnp.int32)
            denom = jnp.asarray([[cap_cpu - res_cpu, cap_mem - res_mem]],
                                dtype=jnp.float32)
            ask = jnp.asarray([ask_cpu, ask_mem, 0, 0], dtype=jnp.int32)
            got = float(_score_fit(used, ask, denom)[0])
            assert got == pytest.approx(expect, abs=1e-3), (
                f"cap=({cap_cpu},{cap_mem}) used=({used_cpu},{used_mem}) "
                f"ask=({ask_cpu},{ask_mem})")


@pytest.mark.slow
class TestBatchSchedulerDifferential:
    def test_places_all_when_capacity_sufficient(self):
        h = Harness()
        make_cluster(h, 20)
        job = strip_networks(mock.job())
        job.task_groups[0].count = 40
        h.state.upsert_job(h.next_index(), job)
        ev = reg_eval(job)
        sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
        sched.process(ev)

        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 40
        h.assert_eval_status(s.EVAL_STATUS_COMPLETE)

        # No node overcommitted: verify with the scalar oracle's allocs_fit.
        by_node = {}
        for a in allocs:
            by_node.setdefault(a.node_id, []).append(a)
        for node_id, node_allocs in by_node.items():
            node = h.state.node_by_id(None, node_id)
            fit, dim, _ = allocs_fit(node, node_allocs)
            assert fit, f"node {node_id} overcommitted: {dim}"

    def test_binpack_score_vs_oracle(self):
        """Aggregate bin-pack quality must be >= oracle - 0.5%
        (BASELINE.md regression budget)."""

        def run(factory_name, seed):
            h = Harness()
            make_cluster(h, 30, seed=seed, hetero=True)
            total_score = 0.0
            jobs = []
            for i in range(10):
                job = strip_networks(mock.job())
                job.task_groups[0].count = 8
                job.constraints = [s.Constraint("${attr.kernel.name}", "linux", "=")]
                h.state.upsert_job(h.next_index(), job)
                jobs.append(job)
            evals = [reg_eval(j) for j in jobs]
            if factory_name == "tpu-batch":
                sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
                sched.schedule_batch(evals)
            else:
                for ev in evals:
                    h.process(new_service_scheduler, ev)
            # Bin-pack quality = per-alloc final-state score (an alloc on a
            # tightly packed node scores high); this is the quantity the
            # reference's ScoreFit maximizes per placement.  Also count the
            # nodes touched — denser packing uses fewer.
            placed = 0
            weighted_score = 0.0
            nodes_used = 0
            for node in h.state.nodes(None):
                allocs = h.state.allocs_by_node_terminal(None, node.id, False)
                if not allocs:
                    continue
                fit, dim, util = allocs_fit(node, allocs)
                assert fit, f"overcommit: {dim}"
                weighted_score += score_fit(node, util) * len(allocs)
                placed += len(allocs)
                nodes_used += 1
            return placed, weighted_score / placed, nodes_used

        placed_oracle, score_oracle, nodes_oracle = run("oracle", seed=3)
        placed_tpu, score_tpu, nodes_tpu = run("tpu-batch", seed=3)
        assert placed_tpu == placed_oracle == 80
        # The kernel scans ALL nodes (the oracle samples log2 N candidates),
        # so per-alloc bin-pack score must not regress beyond the 0.5%
        # budget — in practice it improves.
        assert score_tpu >= score_oracle * 0.995, (
            f"binpack regression: tpu={score_tpu:.3f} oracle={score_oracle:.3f}")
        assert nodes_tpu <= nodes_oracle, (
            f"packing regression: tpu used {nodes_tpu} nodes, oracle {nodes_oracle}")

    def test_blocked_eval_on_exhaustion(self):
        h = Harness()
        n = mock.node()
        n.resources = s.Resources(cpu=1100, memory_mb=1024, disk_mb=20000, iops=100)
        n.reserved = None
        n.resources.networks = []
        n.compute_class()
        h.state.upsert_node(h.next_index(), n)
        job = strip_networks(mock.job())
        job.task_groups[0].count = 5  # only 2 fit (500 cpu each)
        h.state.upsert_job(h.next_index(), job)
        ev = reg_eval(job)
        sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
        sched.process(ev)

        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 2
        assert len(h.create_evals) == 1
        assert h.create_evals[0].status == s.EVAL_STATUS_BLOCKED
        update = h.evals[0]
        assert "web" in update.failed_tg_allocs
        m = update.failed_tg_allocs["web"]
        assert m.coalesced_failures == 2  # 3 unplaced, 1 recorded + 2 coalesced

    def test_distinct_hosts_on_device(self):
        h = Harness()
        make_cluster(h, 5)
        job = strip_networks(mock.job())
        job.constraints.append(s.Constraint(operand=s.CONSTRAINT_DISTINCT_HOSTS))
        job.task_groups[0].count = 5
        h.state.upsert_job(h.next_index(), job)
        sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
        sched.process(reg_eval(job))
        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 5
        assert len({a.node_id for a in allocs}) == 5

    def test_multi_eval_batch(self):
        """One device pass serves many evals; per-job serialization holds."""
        h = Harness()
        make_cluster(h, 10)
        jobs = []
        for _ in range(5):
            job = strip_networks(mock.job())
            job.task_groups[0].count = 4
            h.state.upsert_job(h.next_index(), job)
            jobs.append(job)
        evals = [reg_eval(j) for j in jobs]
        sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
        stats = sched.schedule_batch(evals)
        assert stats.num_evals == 5
        assert stats.num_asks == 20
        for job in jobs:
            assert len(h.state.allocs_by_job(None, job.id, True)) == 4
        # every eval got a status update
        assert len(h.evals) == 5
        assert all(e.status == s.EVAL_STATUS_COMPLETE for e in h.evals)


class TestBatchAllocsFit:
    def test_matches_scalar(self):
        cap = jnp.asarray([[1000, 1000, 1000, 100], [500, 500, 500, 50]], dtype=jnp.int32)
        used = jnp.asarray([[900, 1000, 10, 0], [501, 0, 0, 0]], dtype=jnp.int32)
        fit, dim = batch_allocs_fit(cap, used)
        assert fit.tolist() == [True, False]
        assert dim.tolist() == [-1, 0]  # cpu is dim 0


class TestAllocMetricParity:
    """Batch-path AllocMetric fields must match the oracle's on the same
    placement failure (VERDICT r1 next-round #8; structs.go:4074-4172)."""

    def _run(self, kind, seed=11):
        h = Harness()
        rng = random.Random(seed)
        # Mixed cluster: distinct user classes; some nodes filtered by a
        # kernel constraint, the rest too small for the ask.
        for i in range(12):
            n = mock.node()
            n.resources.networks = []
            n.reserved.networks = []
            n.node_class = "big" if i % 2 == 0 else "small"
            n.attributes["kernel.name"] = "linux" if i < 8 else "windows"
            # nodes share computed classes, so class-cache attribution
            # ("computed class ineligible") must match the oracle too
            n.resources.cpu = 500
            n.resources.memory_mb = 512
            n.compute_class()
            h.state.upsert_node(h.next_index(), n)
        job = strip_networks(mock.job())
        job.task_groups[0].count = 2
        job.constraints = [s.Constraint("${attr.kernel.name}", "linux", "=")]
        for t in job.task_groups[0].tasks:
            t.resources.cpu = 2000  # exceeds every node
            t.resources.memory_mb = 64
        h.state.upsert_job(h.next_index(), job)
        ev = reg_eval(job)
        if kind == "tpu-batch":
            sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
            sched.process(ev)
        else:
            h.process(new_service_scheduler, ev)
        updated = [e for e in h.evals if e.id == ev.id]
        assert updated and updated[-1].failed_tg_allocs, f"{kind}: no failure"
        return updated[-1].failed_tg_allocs["web"]

    def test_failure_forensics_match_oracle(self):
        oracle = self._run("oracle")
        batch = self._run("tpu-batch")
        assert batch.nodes_evaluated == oracle.nodes_evaluated
        assert batch.nodes_filtered == oracle.nodes_filtered
        assert batch.class_filtered == oracle.class_filtered
        assert batch.constraint_filtered == oracle.constraint_filtered
        assert batch.nodes_exhausted == oracle.nodes_exhausted
        assert batch.class_exhausted == oracle.class_exhausted
        assert batch.dimension_exhausted == oracle.dimension_exhausted

    def test_placed_alloc_carries_binpack_scores(self):
        h = Harness()
        make_cluster(h, 8)
        job = strip_networks(mock.job())
        job.task_groups[0].count = 3
        h.state.upsert_job(h.next_index(), job)
        sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
        sched.process(reg_eval(job))
        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 3
        for a in allocs:
            key = f"{a.node_id}.binpack"
            assert key in a.metrics.scores, "missing commit-time score"
            # score must equal the oracle's score_fit at commit state
            assert 0.0 <= a.metrics.scores[key] <= 18.0


@pytest.mark.slow
class TestEmptyCluster:
    def test_batch_schedules_with_zero_nodes(self):
        """A job registered before any node exists must produce a clean
        placement failure (blocked eval), not a crash in the vectorized
        forensics."""
        h = Harness()
        job = strip_networks(mock.job())
        job.task_groups[0].count = 2
        h.state.upsert_job(h.next_index(), job)
        ev = reg_eval(job)
        sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
        sched.process(ev)
        assert h.state.allocs_by_job(None, job.id, True) == []
        updated = [e for e in h.evals if e.id == ev.id]
        assert updated and updated[-1].failed_tg_allocs
        m = updated[-1].failed_tg_allocs["web"]
        assert m.nodes_evaluated == 0 and m.nodes_filtered == 0


class TestSelectTopK:
    """The radix-quantile select must be exact and identical across its
    backend-dispatched histogram forms (kernels._byte_histogram): the
    dense [256, N] compare (TPU) and the scatter-add (CPU) must give
    bit-identical masks, and both must match a stable argsort."""

    CASES = [
        ("uniform", lambda rng, n: rng.random(n).astype(np.float32), 0.9),
        ("heavy-ties", lambda rng, n: (np.round(
            rng.random(n).astype(np.float32) * 4) / 4), 0.5),
        ("all-equal", lambda rng, n: np.full(n, 1.25, np.float32), 1.0),
        ("negatives", lambda rng, n: rng.standard_normal(n).astype(
            np.float32), 0.7),
    ]

    @pytest.mark.parametrize("name,gen,p_ok", CASES)
    def test_hist_forms_identical_and_exact(self, name, gen, p_ok):
        from nomad_tpu.ops import kernels as K

        import zlib

        n = 4096
        rng = np.random.default_rng(zlib.crc32(name.encode()) & 0xFFFF)
        scores = gen(rng, n)
        ok = rng.random(n) < p_ok
        scored = np.where(ok, scores, K.NEG_INF).astype(np.float32)

        # One compiled program per histogram form (k is a traced
        # operand, so every k value reuses the same executable — a
        # fresh jit per (form, k) cost ~60s/case in compiles).
        compiled = {}

        def run(hist_fn, k):
            f = compiled.get(hist_fn)
            if f is None:
                orig = K._byte_histogram
                K._byte_histogram = hist_fn
                try:
                    f = jax.jit(
                        lambda s_, o_, k_: K._select_top_k(s_, o_, k_))
                    # Trace now, while the form is patched in.
                    f(jnp.asarray(scored), jnp.asarray(ok), jnp.int32(1))
                finally:
                    K._byte_histogram = orig
                compiled[hist_fn] = f
            return np.asarray(f(jnp.asarray(scored), jnp.asarray(ok),
                                jnp.int32(k)))

        for k_raw in (1, 37, 1000, n):
            # The kernel's contract (commit in placement_rounds) clamps
            # k to the feasible count before selecting.
            k = min(k_raw, int(ok.sum()))
            if k == 0:
                continue
            dense = run(K._byte_histogram_dense, k)
            scat = run(K._byte_histogram_scatter, k)
            assert (dense == scat).all(), f"{name} k={k}: forms diverge"
            # Exactness vs a stable argsort over (-score, node index).
            want = np.zeros(n, dtype=bool)
            order = np.lexsort((np.arange(n), -scored))
            take = [i for i in order if ok[i]][:k]
            want[take] = True
            assert (dense == want).all(), f"{name} k={k}: not exact"
