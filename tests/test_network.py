"""L0 tests: NetworkIndex port/bandwidth accounting
(reference: nomad/structs/network_test.go)."""
import random

from nomad_tpu import mock
from nomad_tpu.structs import structs as s
from nomad_tpu.structs.bitmap import Bitmap
from nomad_tpu.structs.network import (
    MAX_DYNAMIC_PORT,
    MIN_DYNAMIC_PORT,
    NetworkIndex,
)


class TestBitmap:
    def test_set_check_clear(self):
        b = Bitmap(256)
        assert not b.check(42)
        b.set(42)
        assert b.check(42)
        b.clear()
        assert not b.check(42)

    def test_indexes_in_range(self):
        b = Bitmap(64)
        b.set(5)
        b.set(10)
        assert b.indexes_in_range(True, 0, 63) == [5, 10]
        free = b.indexes_in_range(False, 4, 11)
        assert free == [4, 6, 7, 8, 9, 11]

    def test_copy_independent(self):
        b = Bitmap(64)
        b.set(1)
        c = b.copy()
        c.set(2)
        assert not b.check(2)
        assert c.check(1)


class TestNetworkIndex:
    def test_set_node(self):
        idx = NetworkIndex()
        collide = idx.set_node(mock.node())
        assert not collide
        assert idx.avail_bandwidth["eth0"] == 1000
        assert idx.used_bandwidth["eth0"] == 1
        assert idx.used_ports["192.168.0.100"].check(22)

    def test_add_reserved_collision(self):
        idx = NetworkIndex()
        net = s.NetworkResource(
            device="eth0", ip="10.0.0.1",
            reserved_ports=[s.Port("a", 8000)], mbits=10,
        )
        assert not idx.add_reserved(net)
        assert idx.add_reserved(net)  # same port again → collision

    def test_overcommitted(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        idx.add_reserved(s.NetworkResource(device="eth0", ip="10.0.0.1", mbits=2000))
        assert idx.overcommitted()

    def test_assign_network_reserved(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        ask = s.NetworkResource(mbits=50, reserved_ports=[s.Port("main", 8000)])
        offer, err = idx.assign_network(ask, random.Random(1))
        assert offer is not None, err
        assert offer.ip == "192.168.0.100"
        assert [p.value for p in offer.reserved_ports] == [8000]

    def test_assign_network_reserved_collision(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        ask = s.NetworkResource(mbits=50, reserved_ports=[s.Port("ssh", 22)])
        offer, err = idx.assign_network(ask, random.Random(1))
        assert offer is None
        assert err == "reserved port collision"

    def test_assign_network_dynamic(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        ask = s.NetworkResource(mbits=50, dynamic_ports=[s.Port("http"), s.Port("admin")])
        offer, err = idx.assign_network(ask, random.Random(1))
        assert offer is not None, err
        vals = [p.value for p in offer.dynamic_ports]
        assert len(set(vals)) == 2
        for v in vals:
            assert MIN_DYNAMIC_PORT <= v <= MAX_DYNAMIC_PORT

    def test_assign_network_bandwidth_exceeded(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        ask = s.NetworkResource(mbits=5000)
        offer, err = idx.assign_network(ask, random.Random(1))
        assert offer is None
        assert err == "bandwidth exceeded"

    def test_precise_fallback_when_ports_dense(self):
        """Occupy almost the whole dynamic range; precise scan still finds
        the free ports (network.go:288 getDynamicPortsPrecise)."""
        idx = NetworkIndex()
        node = mock.node()
        idx.set_node(node)
        used = idx.used_ports["192.168.0.100"]
        for port in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT + 1):
            if port not in (30100, 30101):
                used.set(port)
        ask = s.NetworkResource(mbits=1, dynamic_ports=[s.Port("a"), s.Port("b")])
        offer, err = idx.assign_network(ask, random.Random(1))
        assert offer is not None, err
        assert sorted(p.value for p in offer.dynamic_ports) == [30100, 30101]

    def test_add_allocs(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        a = mock.alloc()
        assert not idx.add_allocs([a])
        assert idx.used_ports["192.168.0.100"].check(5000)


class TestComputedClass:
    def test_same_attrs_same_class(self):
        n1, n2 = mock.node(), mock.node()
        assert n1.computed_class == n2.computed_class

    def test_unique_attrs_excluded(self):
        n1, n2 = mock.node(), mock.node()
        n2.attributes["unique.hostname"] = "different"
        n2.compute_class()
        assert n1.computed_class == n2.computed_class

    def test_non_unique_attr_changes_class(self):
        n1, n2 = mock.node(), mock.node()
        n2.attributes["kernel.name"] = "windows"
        n2.compute_class()
        assert n1.computed_class != n2.computed_class

    def test_meta_changes_class(self):
        n1, n2 = mock.node(), mock.node()
        n2.meta["database"] = "postgres"
        n2.compute_class()
        assert n1.computed_class != n2.computed_class

    def test_escaped_constraints(self):
        from nomad_tpu.structs.node_class import escaped_constraints

        c1 = s.Constraint("${attr.kernel.name}", "linux", "=")
        c2 = s.Constraint("${node.unique.id}", "x", "=")
        c3 = s.Constraint("${meta.unique.foo}", "y", "=")
        out = escaped_constraints([c1, c2, c3])
        assert out == [c2, c3]
