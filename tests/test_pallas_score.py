"""Differential tests for the pallas fused scoring kernel
(ops/pallas_score.py) against the jnp reference composition
(ops/kernels.py:_score_fit + fit/feas masks). Runs in interpret mode on
the CPU backend — identical semantics, no Mosaic.

Tier split (Pallas go/no-go follow-through, PR 6): the small-shape
interpret-mode parity tests in TestInterpretParityQuick run UNMARKED so
tier-1 exercises both pallas kernels on CPU every round; the heavy
multi-block/mesh differentials keep the ``slow`` mark."""
import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.ops.kernels import _score_fit
from nomad_tpu.ops.pallas_score import NEG_INF, masked_score_matrix

# Heavy integration/differential sweeps: quick tier skips THEM (the
# small-shape interpret parity class below stays tier-1).
slow = pytest.mark.slow


def _reference(feas, used, capacity, denom, ask):
    u = feas.shape[0]
    rows = []
    for i in range(u):
        cap_left = capacity - used
        fits = jnp.all(jnp.asarray(ask[i])[None, :] <= cap_left, axis=1)
        ok = jnp.asarray(feas[i]) & fits
        score = _score_fit(jnp.asarray(used), jnp.asarray(ask[i]),
                           jnp.asarray(denom))
        rows.append(jnp.where(ok, score, jnp.float32(NEG_INF)))
    return np.asarray(jnp.stack(rows))


def _mk(n, u, seed=0, zero_denom_frac=0.0):
    rng = np.random.default_rng(seed)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], np.int32), (n, 1))
    used = np.zeros((n, 4), np.int32)
    used[:, 0] = rng.integers(0, 4200, n)   # some nodes over-asked
    used[:, 1] = rng.integers(0, 8192, n)
    denom = capacity[:, :2].astype(np.float32)
    if zero_denom_frac:
        mask = rng.random(n) < zero_denom_frac
        denom[mask, 0] = 0.0
    feas = rng.random((u, n)) < 0.8
    ask = np.stack([
        np.array([rng.integers(100, 900), rng.integers(64, 1024), 150, 0],
                 np.int32) for _ in range(u)])
    return feas, used, capacity, denom, ask


@pytest.mark.parametrize("n,u,seed", [
    (512, 4, 0),     # exactly one node block
    (1024, 8, 1),    # multiple blocks
    (700, 3, 2),     # padded node axis (700 → 1024)
    (64, 1, 3),      # single small padded block
])
@slow
def test_matches_reference_composition(n, u, seed):
    feas, used, capacity, denom, ask = _mk(n, u, seed)
    out = np.asarray(masked_score_matrix(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask)))
    ref = _reference(feas, used, capacity, denom, ask)
    np.testing.assert_array_equal(out, ref)


@slow
def test_zero_denom_and_full_nodes():
    """Degenerate capacity (denom 0 → ScoreFit 0) and fully-used nodes
    (no fit → NEG_INF) follow the reference bit-for-bit."""
    feas, used, capacity, denom, ask = _mk(512, 4, 7, zero_denom_frac=0.3)
    used[:64] = capacity[:64]  # saturated nodes: nothing fits
    out = np.asarray(masked_score_matrix(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask)))
    ref = _reference(feas, used, capacity, denom, ask)
    np.testing.assert_array_equal(out, ref)
    assert np.all(out[:, :64] == NEG_INF)


@slow
def test_padded_columns_never_leak():
    """Padded node columns must not appear as feasible candidates."""
    feas, used, capacity, denom, ask = _mk(130, 2, 11)
    out = np.asarray(masked_score_matrix(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask)))
    assert out.shape == (2, 130)


@slow
def test_mesh_path_pallas_equals_xla():
    """sharded_candidate_scores with the pallas kernel produces the
    identical candidate table to the default XLA path on the 8-device
    mesh (pallas_call inside shard_map, interpret mode on CPU)."""
    import jax

    from nomad_tpu.parallel import make_node_mesh, sharded_candidate_scores

    assert len(jax.devices()) == 8
    mesh = make_node_mesh()
    feas, used, capacity, denom, ask = _mk(1024, 4, 21)
    args = (jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
            jnp.asarray(denom), jnp.asarray(ask))
    s_xla, i_xla = sharded_candidate_scores(mesh, *args, k=16,
                                            use_pallas=False)
    s_pl, i_pl = sharded_candidate_scores(mesh, *args, k=16,
                                          use_pallas=True)
    np.testing.assert_array_equal(np.asarray(s_xla), np.asarray(s_pl))
    np.testing.assert_array_equal(np.asarray(i_xla), np.asarray(i_pl))


# -- scored_rows: the COMPLETE commit-time scoring expression -------------

def _reference_scored_rows(feas, used, capacity, denom, ask, penalty,
                           coll, seed, u_offset=0, n_offset=0):
    from nomad_tpu.ops.kernels import tie_jitter

    u, n = feas.shape
    node_idx = jnp.arange(n_offset, n_offset + n, dtype=jnp.int32)
    rows = []
    for i in range(u):
        cap_left = capacity - used
        fits = jnp.all(jnp.asarray(ask[i])[None, :] <= cap_left, axis=1)
        ok = jnp.asarray(feas[i]) & fits
        score = _score_fit(jnp.asarray(used), jnp.asarray(ask[i]),
                           jnp.asarray(denom))
        score = score - penalty[i] * jnp.asarray(coll[i], jnp.float32)
        score = score + tie_jitter(jnp.uint32(seed),
                                   jnp.int32(u_offset + i), node_idx)
        rows.append(jnp.where(ok, score, jnp.float32(NEG_INF)))
    return np.asarray(jnp.stack(rows))


@pytest.mark.parametrize("n,u,seed,u_off,n_off", [
    (512, 4, 7, 0, 0),
    (1024, 8, 11, 0, 0),
    (700, 3, 13, 0, 0),       # padded node axis
    (512, 4, 17, 32, 2048),   # shard offsets: global-index jitter keying
])
@slow
def test_scored_rows_matches_commit_expression(n, u, seed, u_off, n_off):
    """scored_rows fuses fit+feas+ScoreFit+penalty+jitter; must be
    bit-identical to the placement loop's commit composition."""
    from nomad_tpu.ops.pallas_score import scored_rows

    feas, used, capacity, denom, ask = _mk(n, u, seed)
    rng = np.random.default_rng(seed + 1)
    penalty = rng.uniform(0.0, 25.0, u).astype(np.float32)
    coll = (rng.random((u, n)) < 0.1).astype(np.int32) * rng.integers(
        1, 4, (u, n)).astype(np.int32)
    got = np.asarray(scored_rows(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(penalty),
        jnp.asarray(coll), np.uint32(seed * 2654435761 % (2**32)),
        u_offset=u_off, n_offset=n_off, interpret=True))
    want = _reference_scored_rows(
        feas, used, capacity, denom, ask, penalty, coll,
        np.uint32(seed * 2654435761 % (2**32)), u_offset=u_off,
        n_offset=n_off)
    assert got.shape == want.shape
    # Bit-identical wherever the penalty term is inactive; where
    # collisions are nonzero the (score − pen·coll + jitter) chain may
    # FMA-fuse differently between program shapes — ulp-scale only,
    # orders of magnitude below the 1e-3 tie-jitter that decides ties.
    inactive = coll == 0
    assert (got[inactive] == want[inactive]).all()
    assert np.allclose(got, want, rtol=0, atol=1e-5), (
        f"max abs diff {np.abs(got - want).max()}")


@slow
def test_scored_rows_shard_offsets_tile_global_matrix():
    """Two shards computing their slices with u/n offsets must tile to
    exactly the single-chip full matrix (the multichip contract)."""
    from nomad_tpu.ops.pallas_score import scored_rows

    n, u, seed = 1024, 4, 23
    feas, used, capacity, denom, ask = _mk(n, u, seed)
    rng = np.random.default_rng(seed)
    penalty = rng.uniform(0.0, 25.0, u).astype(np.float32)
    coll = np.zeros((u, n), np.int32)
    kw = dict(interpret=True)
    full = np.asarray(scored_rows(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(penalty),
        jnp.asarray(coll), np.uint32(99), **kw))
    half = n // 2
    left = np.asarray(scored_rows(
        jnp.asarray(feas[:, :half]), jnp.asarray(used[:half]),
        jnp.asarray(capacity[:half]), jnp.asarray(denom[:half]),
        jnp.asarray(ask), jnp.asarray(penalty),
        jnp.asarray(coll[:, :half]), np.uint32(99), n_offset=0, **kw))
    right = np.asarray(scored_rows(
        jnp.asarray(feas[:, half:]), jnp.asarray(used[half:]),
        jnp.asarray(capacity[half:]), jnp.asarray(denom[half:]),
        jnp.asarray(ask), jnp.asarray(penalty),
        jnp.asarray(coll[:, half:]), np.uint32(99), n_offset=half, **kw))
    tiled = np.concatenate([left, right], axis=1)
    assert (tiled == full).all()


# -- tier-1 interpret-mode parity (Pallas go/no-go follow-through) ---------

class TestInterpretParityQuick:
    """Small-shape interpret-mode parity, UNMARKED so the quick tier
    (`pytest -m "not slow"`) exercises both pallas kernels on the CPU
    backend every round — the go/no-go decision's standing regression
    evidence (README "Pallas go/no-go")."""

    def test_masked_score_matrix_interpret_parity(self):
        feas, used, capacity, denom, ask = _mk(512, 2, 41,
                                               zero_denom_frac=0.2)
        used[:16] = capacity[:16]  # saturated nodes: NEG_INF lane
        out = np.asarray(masked_score_matrix(
            jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
            jnp.asarray(denom), jnp.asarray(ask), interpret=True))
        ref = _reference(feas, used, capacity, denom, ask)
        np.testing.assert_array_equal(out, ref)
        assert np.all(out[:, :16] == NEG_INF)

    def test_scored_rows_interpret_parity(self):
        from nomad_tpu.ops.pallas_score import scored_rows

        feas, used, capacity, denom, ask = _mk(512, 2, 43)
        rng = np.random.default_rng(43)
        penalty = rng.uniform(0.0, 25.0, 2).astype(np.float32)
        coll = np.zeros((2, 512), np.int32)  # penalty inactive: bit-exact
        got = np.asarray(scored_rows(
            jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
            jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(penalty),
            jnp.asarray(coll), np.uint32(77), interpret=True))
        want = _reference_scored_rows(
            feas, used, capacity, denom, ask, penalty, coll,
            np.uint32(77))
        np.testing.assert_array_equal(got, want)
