"""Cross-node sticky-disk migration (reference: client/client.go:1743
migrateRemoteAllocDir): a replacement allocation on another node pulls the
previous allocation's sticky data over the old node's HTTP fs surface."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent.agent import Agent
from nomad_tpu.agent.config import AgentConfig
from nomad_tpu.structs import structs as s

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def wait_until(pred, timeout=60.0, interval=0.05):
    # 60s default: liveness bound only — the full cluster round-trip
    # (register → eval → plan → client pull → runner start) competes with
    # the whole suite for 2 cores.
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster(tmp_path):
    scfg = AgentConfig()
    scfg.name = "mig-server"
    scfg.server.enabled = True
    scfg.ports.http = 0
    scfg.ports.rpc = 0
    server_agent = Agent(scfg)
    server_agent.start()
    rpc_addr = server_agent.server.config.rpc_advertise

    clients = []
    for i in (1, 2):
        ccfg = AgentConfig()
        ccfg.name = f"mig-client-{i}"
        ccfg.client.enabled = True
        ccfg.client.state_dir = str(tmp_path / f"c{i}-state")
        ccfg.client.alloc_dir = str(tmp_path / f"c{i}-allocs")
        ccfg.client.servers = [rpc_addr]
        ccfg.ports.http = 0
        a = Agent(ccfg)
        a.start()
        clients.append(a)
    yield server_agent, clients
    for a in clients:
        a.shutdown()
    server_agent.shutdown()


class TestRemoteMigration:
    def test_sticky_data_follows_alloc_across_nodes(self, cluster):
        server_agent, clients = cluster
        srv = server_agent.server
        assert wait_until(lambda: sum(
            1 for n in srv.state.nodes(None)
            if n.status == s.NODE_STATUS_READY) == 2, 40.0), \
            "clients never became ready"

        job = mock.job()
        job.id = job.name = "sticky-job"
        tg = job.task_groups[0]
        tg.count = 1
        tg.ephemeral_disk = s.EphemeralDisk(sticky=True, migrate=True,
                                            size_mb=50)
        tg.restart_policy = s.RestartPolicy(attempts=0, mode="fail")
        for t in tg.tasks:
            t.driver = "mock_driver"
            t.config = {"run_for": "120s"}
            t.resources.networks = []
            t.services = []
        srv.job_register(job)
        assert wait_until(lambda: any(
            a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
            for a in srv.job_allocations(job.id)))
        alloc1 = srv.job_allocations(job.id)[0]
        src_client = next(c for c in clients
                          if c.client.node.id == alloc1.node_id)
        dst_client = next(c for c in clients if c is not src_client)

        # The task writes state into its sticky local dir.
        runner1 = src_client.client.get_alloc_runner(alloc1.id)
        local_dir = runner1.alloc_dir.task_dirs["web"].local_dir
        with open(os.path.join(local_dir, "state.db"), "w") as fh:
            fh.write("precious sticky state")

        # Drain the node: the replacement lands on the other node with
        # previous_allocation set (migrate path, util.go evictAndPlace).
        srv.node_update_drain(alloc1.node_id, True)
        assert wait_until(lambda: any(
            a.id != alloc1.id and a.node_id == dst_client.client.node.id
            and a.previous_allocation == alloc1.id
            for a in srv.job_allocations(job.id)), 30.0), \
            "replacement with previous_allocation never appeared"
        alloc2 = next(a for a in srv.job_allocations(job.id)
                      if a.id != alloc1.id)

        # The new node's alloc dir receives the migrated sticky data.
        def migrated():
            runner2 = dst_client.client.get_alloc_runner(alloc2.id)
            if runner2 is None:
                return False
            td = runner2.alloc_dir.task_dirs.get("web")
            if td is None:
                return False  # runner exists, task dirs not built yet
            path = os.path.join(td.local_dir, "state.db")
            return os.path.exists(path) and \
                open(path).read() == "precious sticky state"

        assert wait_until(migrated, 40.0), "sticky data never migrated"
        assert wait_until(lambda: any(
            a.id == alloc2.id
            and a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
            for a in srv.job_allocations(job.id)), 30.0)
