"""rkt + lxc driver tests (reference: client/driver/rkt_test.go,
lxc_test.go — config validation, command assembly, fingerprint gating,
and a full start path against a stub binary)."""
import os
import stat

import pytest

from nomad_tpu import mock
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.driver.container_drivers import (
    LXC_ENABLE_OPTION,
    LxcDriver,
    RktDriver,
)
from nomad_tpu.client.driver.driver import (
    DriverContext,
    DriverError,
    ExecContext,
    validate_driver_config,
)
from nomad_tpu.client.driver.env import TaskEnv
from nomad_tpu.structs import structs as s

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


class FakeConfig:
    def __init__(self, options=None):
        self.options = options or {}


def mk_ctx(name, options=None):
    return DriverContext(driver_name=name, alloc_id="alloc12345",
                         config=FakeConfig(options))


def mk_exec_ctx(tmp_path, env=None):
    ad = AllocDir(str(tmp_path / "alloc-dir"))
    ad.build()
    td = ad.new_task_dir("web")
    td.build()
    return ExecContext(task_dir=td, task_env=env or TaskEnv())


def mk_task(driver, config):
    task = s.Task(name="web", driver=driver, config=config,
                  resources=s.Resources(cpu=500, memory_mb=256))
    return task


class TestRktDriver:
    def test_validate_config(self):
        validate_driver_config("rkt", {"image": "coreos.com/etcd:v2.0.4"})
        with pytest.raises(ValueError):
            validate_driver_config("rkt", {})
        with pytest.raises(ValueError):
            validate_driver_config("rkt", {"image": 123})

    def test_command_line_full_surface(self, tmp_path):
        """rkt.go:251-370: insecure default, task-dir mounts, net/dns,
        port map, isolators, --exec and trailing args."""
        d = RktDriver(mk_ctx("rkt"))
        env = TaskEnv(env_map={"NOMAD_TASK_NAME": "web"})
        ectx = mk_exec_ctx(tmp_path, env)
        task = mk_task("rkt", {
            "image": "example.com/app:1.0",
            "command": "/bin/serve",
            "args": ["--name", "${NOMAD_TASK_NAME}"],
            "dns_servers": ["8.8.8.8"],
            "dns_search_domains": ["example.com"],
            "net": ["host"],
            "port_map": {"http": "8080"},
            "volumes": ["/host/data:/data"],
            "no_overlay": True,
            "debug": True,
        })
        cmd, args = d.command_line(ectx, task)
        assert cmd == "rkt"
        joined = " ".join(args)
        # No trust prefix ⇒ verification off, exactly like rkt.go:270-279.
        assert "--insecure-options=all" in joined
        assert "--debug=true" in joined
        assert "run" in args
        assert "--no-overlay=true" in joined
        td = ectx.task_dir
        assert f"--volume=alloc,kind=host,source={td.shared_alloc_dir}" in args
        assert "--mount=volume=alloc,target=/alloc" in args
        assert "--mount=volume=local,target=/local" in args
        assert "--mount=volume=secrets,target=/secrets" in args
        assert "--volume=task-0,kind=host,source=/host/data" in args
        assert "--mount=volume=task-0,target=/data" in args
        assert "--net=host" in args
        assert "--dns=8.8.8.8" in args
        assert "--dns-search=example.com" in args
        assert "--port=http:8080" in args
        assert "--memory=256M" in args
        assert "--cpu=500m" in args
        assert "--exec=/bin/serve" in args
        # interpolated trailing args after the -- separator
        assert args[args.index("--"):] == ["--", "--name", "web"]
        # image comes before --exec
        assert args.index("example.com/app:1.0") < args.index("--exec=/bin/serve")

    def test_insecure_options_with_trust(self, tmp_path):
        d = RktDriver(mk_ctx("rkt"))
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("rkt", {"image": "img", "trust_prefix": "example.com",
                               "insecure_options": ["image"]})
        _, args = d.command_line(ectx, task)
        assert "--insecure-options=image" in args
        assert "--insecure-options=all" not in " ".join(args)

    def test_volumes_gated_by_client_option(self, tmp_path):
        d = RktDriver(mk_ctx("rkt", {"rkt.volumes.enabled": "false"}))
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("rkt", {"image": "img", "volumes": ["/a:/b"]})
        with pytest.raises(DriverError):
            d.command_line(ectx, task)

    def test_bad_volume_spec(self, tmp_path):
        d = RktDriver(mk_ctx("rkt"))
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("rkt", {"image": "img", "volumes": ["/only-host-path"]})
        with pytest.raises(DriverError):
            d.command_line(ectx, task)

    def test_fingerprint_absent(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PATH", str(tmp_path))
        d = RktDriver(mk_ctx("rkt"))
        node = mock.node()
        node.attributes["driver.rkt"] = "1"
        assert d.fingerprint(node) is False
        assert "driver.rkt" not in node.attributes

    def test_fingerprint_versions(self, tmp_path, monkeypatch):
        rkt = tmp_path / "rkt"
        rkt.write_text("#!/bin/sh\n"
                       "echo 'rkt Version: 1.29.0'\n"
                       "echo 'appc Version: 0.8.11'\n")
        rkt.chmod(rkt.stat().st_mode | stat.S_IEXEC)
        monkeypatch.setenv("PATH", str(tmp_path))
        d = RktDriver(mk_ctx("rkt"))
        node = mock.node()
        assert d.fingerprint(node) is True
        assert node.attributes["driver.rkt"] == "1"
        assert node.attributes["driver.rkt.version"] == "1.29.0"
        assert node.attributes["driver.rkt.appc.version"] == "0.8.11"

    def test_trust_failure_fails_start(self, tmp_path, monkeypatch):
        d = RktDriver(mk_ctx("rkt"))

        class Boom:
            returncode = 1
            stderr = b"no such prefix"

        monkeypatch.setattr(d, "_run_rkt_trust", lambda *a: Boom())
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("rkt", {"image": "img", "trust_prefix": "x.com"})
        with pytest.raises(DriverError, match="rkt trust failed"):
            d.start(ectx, task)

    def test_start_runs_stub_binary(self, tmp_path, monkeypatch):
        """Full start path: the assembled rkt argv runs under the
        supervisor against a stub binary, logs flow, exit collected."""
        stub = tmp_path / "bin" / "rkt"
        stub.parent.mkdir()
        stub.write_text("#!/bin/sh\necho rkt-ran-ok\nexit 0\n")
        stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
        monkeypatch.setenv(
            "PATH", f"{stub.parent}{os.pathsep}{os.environ['PATH']}")
        d = RktDriver(mk_ctx("rkt"))
        env = TaskEnv(env_map={"PATH": os.environ["PATH"]})
        ectx = mk_exec_ctx(tmp_path, env)
        task = mk_task("rkt", {"image": "img"})
        resp = d.start(ectx, task)
        assert resp.handle.wait_ch().wait(20.0)
        assert resp.handle.wait_result().exit_code == 0
        out = b"".join(
            open(os.path.join(ectx.task_dir.log_dir, f), "rb").read()
            for f in os.listdir(ectx.task_dir.log_dir) if ".stdout." in f)
        assert b"rkt-ran-ok" in out


class TestLxcDriver:
    def test_validate_config(self):
        validate_driver_config("lxc", {"template": "/usr/share/lxc/t"})
        with pytest.raises(ValueError):
            validate_driver_config("lxc", {})

    def test_create_args(self, tmp_path):
        """lxc.go:228-242 TemplateOptions → lxc-create args."""
        d = LxcDriver(mk_ctx("lxc"))
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("lxc", {
            "template": "download", "distro": "ubuntu", "release": "xenial",
            "arch": "amd64", "disable_gpg": True,
            "template_args": ["--extra", "1"],
        })
        args = d.create_args(ectx, task)
        name = d.container_name(ectx, task)
        assert name.startswith("web-alloc12345-")   # per-launch nonce
        assert args[:4] == ["-n", name, "-t", "download"]
        tail = args[args.index("--") + 1:]
        assert ("--dist", "ubuntu") == tuple(tail[0:2])
        assert ("--release", "xenial") == tuple(tail[2:4])
        assert ("--arch", "amd64") == tuple(tail[4:6])
        assert "--no-validate" in tail
        assert tail[-2:] == ["--extra", "1"]

    def test_command_line_mounts(self, tmp_path):
        """lxc.go:244-258: alloc/local/secrets bind mounts."""
        d = LxcDriver(mk_ctx("lxc"))
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("lxc", {"template": "t",
                               "volumes": ["/host/x:container/x"]})
        cmd, args = d.command_line(ectx, task)
        assert cmd == "lxc-start"
        assert args[:3] == ["-F", "-n", d.container_name(ectx, task)]
        joined = " ".join(args)
        td = ectx.task_dir
        assert f"lxc.mount.entry={td.shared_alloc_dir} alloc" in joined
        assert f"lxc.mount.entry={td.local_dir} local" in joined
        assert f"lxc.mount.entry={td.secrets_dir} secrets" in joined
        assert "lxc.mount.entry=/host/x container/x" in joined

    def test_absolute_container_volume_rejected(self, tmp_path):
        d = LxcDriver(mk_ctx("lxc"))
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("lxc", {"template": "t", "volumes": ["/a:/abs"]})
        with pytest.raises(DriverError):
            d.command_line(ectx, task)

    def test_fingerprint_needs_enable_option(self, tmp_path, monkeypatch):
        lxc = tmp_path / "lxc-start"
        lxc.write_text("#!/bin/sh\necho 2.0.8\n")
        lxc.chmod(lxc.stat().st_mode | stat.S_IEXEC)
        monkeypatch.setenv("PATH", str(tmp_path))
        node = mock.node()
        # present but not enabled → off (lxc.go lxcConfigOption)
        d = LxcDriver(mk_ctx("lxc"))
        assert d.fingerprint(node) is False
        d = LxcDriver(mk_ctx("lxc", {LXC_ENABLE_OPTION: "1"}))
        assert d.fingerprint(node) is True
        assert node.attributes["driver.lxc.version"] == "2.0.8"

    def test_create_failure_fails_start(self, tmp_path, monkeypatch):
        d = LxcDriver(mk_ctx("lxc"))

        class Boom:
            returncode = 1
            stderr = b"template not found"

        monkeypatch.setattr(d, "_run_lxc_create", lambda *a: Boom())
        ectx = mk_exec_ctx(tmp_path)
        task = mk_task("lxc", {"template": "nope"})
        with pytest.raises(DriverError, match="lxc-create failed"):
            d.start(ectx, task)

    def test_start_runs_stub_binary(self, tmp_path, monkeypatch):
        """Create pre-step + foreground start against stub binaries."""
        bindir = tmp_path / "bin"
        bindir.mkdir()
        created = tmp_path / "created"
        create = bindir / "lxc-create"
        create.write_text(f"#!/bin/sh\ntouch {created}\nexit 0\n")
        start = bindir / "lxc-start"
        start.write_text("#!/bin/sh\necho lxc-ran-ok\nexit 0\n")
        for f in (create, start):
            f.chmod(f.stat().st_mode | stat.S_IEXEC)
        monkeypatch.setenv(
            "PATH", f"{bindir}{os.pathsep}{os.environ['PATH']}")
        d = LxcDriver(mk_ctx("lxc"))
        env = TaskEnv(env_map={"PATH": os.environ["PATH"]})
        ectx = mk_exec_ctx(tmp_path, env)
        task = mk_task("lxc", {"template": "busybox"})
        resp = d.start(ectx, task)
        assert created.exists()
        assert resp.handle.wait_ch().wait(20.0)
        assert resp.handle.wait_result().exit_code == 0

    def test_kill_stops_and_destroys_container(self, tmp_path, monkeypatch):
        """Kill must take down the container itself, not just the
        lxc-start monitor (lxc.go:388 h.container.Stop()): after the
        grace period the handle force-stops (-k) and destroys."""
        import time

        bindir = tmp_path / "bin"
        bindir.mkdir()
        stopped = tmp_path / "stopped"
        destroyed = tmp_path / "destroyed"
        (bindir / "lxc-create").write_text("#!/bin/sh\nexit 0\n")
        (bindir / "lxc-start").write_text("#!/bin/sh\nsleep 30\n")
        (bindir / "lxc-stop").write_text(
            "#!/bin/sh\nprintf '%s ' \"$@\" > " + str(stopped) + "\nexit 0\n")
        (bindir / "lxc-destroy").write_text(
            "#!/bin/sh\nprintf '%s ' \"$@\" > " + str(destroyed) +
            "\nexit 0\n")
        for f in bindir.iterdir():
            f.chmod(f.stat().st_mode | stat.S_IEXEC)
        monkeypatch.setenv(
            "PATH", f"{bindir}{os.pathsep}{os.environ['PATH']}")
        d = LxcDriver(mk_ctx("lxc"))
        env = TaskEnv(env_map={"PATH": os.environ["PATH"]})
        ectx = mk_exec_ctx(tmp_path, env)
        task = mk_task("lxc", {"template": "busybox"})
        resp = d.start(ectx, task)
        name = d.container_name(ectx, task)
        assert resp.handle.container_name == name
        # Fresh task dir ⇒ no previous launch, so start() must not have
        # touched the teardown binaries: what lands in the markers below
        # is attributable to kill() alone.
        assert not stopped.exists() and not destroyed.exists()
        resp.handle.kill()
        assert resp.handle.wait_ch().wait(20.0)
        deadline = time.time() + 20.0
        while time.time() < deadline and not destroyed.exists():
            time.sleep(0.2)
        assert stopped.read_text().split() == ["-n", name, "-k"]
        assert destroyed.read_text().split() == ["-n", name, "-f"]

    def test_fingerprint_broken_binary_pops_attrs(self, tmp_path,
                                                  monkeypatch):
        """A present-but-broken binary must stop advertising the driver
        (ADVICE r4): previously only the absent branch popped attrs."""
        import subprocess as sp

        lxc = tmp_path / "lxc-start"
        lxc.write_text("#!/bin/sh\necho 2.0.8\n")
        lxc.chmod(lxc.stat().st_mode | stat.S_IEXEC)
        monkeypatch.setenv("PATH", str(tmp_path))
        node = mock.node()
        node.attributes["driver.lxc"] = "1"
        node.attributes["driver.lxc.version"] = "2.0.8"

        def boom(*a, **k):
            raise sp.SubprocessError("broken")

        monkeypatch.setattr(sp, "run", boom)
        d = LxcDriver(mk_ctx("lxc", {LXC_ENABLE_OPTION: "1"}))
        assert d.fingerprint(node) is False
        assert "driver.lxc" not in node.attributes
        assert "driver.lxc.version" not in node.attributes
