"""Columnar state-store tests (ISSUE 9 tentpole).

Differential coverage: the numpy node/usage mirrors maintained inside
the StateStore must produce static cluster buffers BIT-IDENTICAL to the
object-walk builder across randomized sequences of node registrations,
status/drain flips, alloc writes, slab commits, evictions, and deletes
— asserted by the built-in columnar guard armed at every encode.  Plus
snapshot copy-on-write isolation, the kill-switch, the breaker trip on
injected column corruption, the v2 binary FSM snapshot round-trip
(bit-identity against the legacy msgpack path, both directions), the
scale restore-time regression (slow), and the ``wal.fsync`` fault point
threaded into the chaos suite.
"""
import os
import random
import time

import numpy as np
import pytest

from nomad_tpu import fault, mock
from nomad_tpu.api.codec import to_wire
from nomad_tpu.ops import encode, resident
from nomad_tpu.ops.batch_sched import TPUBatchScheduler
from nomad_tpu.ops.breaker import KernelCircuitBreaker
from nomad_tpu.scheduler import Harness
from nomad_tpu.state import columnar
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import structs as s


def make_node(dc="dc1", status=s.NODE_STATUS_READY):
    node = mock.node()
    node.datacenter = dc
    node.status = status
    node.resources.networks = []
    node.reserved.networks = []
    node.compute_class()
    return node


def make_job(count, prio=50):
    job = mock.job()
    job.priority = prio
    job.task_groups[0].count = count
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.networks = []
    return job


def reg_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def assert_parity(store, attr_targets=(), literals=None):
    """Column-built static encode must match the object walk bit for
    bit (the guard's comparison, asserted directly)."""
    cols = store.columns()
    assert cols is not None, "columnar mirror unavailable"
    nodes = store.nodes(None)
    ct = encode.encode_cluster_static_columnar(cols, nodes,
                                               list(attr_targets))
    ref = encode.encode_cluster_static(nodes, list(attr_targets))
    encode.finalize_codebooks(ct, literals or {})
    encode.finalize_codebooks(ref, literals or {})
    bad = encode._static_mismatch(ct, ref)
    assert not bad, f"columnar static encode diverged: {bad}"
    return ct


def assert_usage_parity(store):
    """Column-derived live usage must match the full alloc-row walk."""
    cols = store.columns()
    assert cols is not None
    usage = store.column_usage(cols)[:cols.n]
    ref = np.zeros_like(usage)
    row_of = {nid: i for i, nid in enumerate(cols.node_ids[:cols.n])}
    for nid, row in store.alloc_rows(None):
        if row.terminal_status():
            continue
        i = row_of.get(nid)
        if i is None:
            continue
        ref[i] += np.array(s.alloc_usage_vec(row), dtype=np.int64)
    assert np.array_equal(usage, ref), "columnar usage diverged from walk"


@pytest.fixture(autouse=True)
def _fresh_columnar(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "1")
    monkeypatch.setenv("NOMAD_TPU_COLUMNAR_GUARD_EVERY", "1")
    columnar.reset_counters()
    resident.reset_counters()
    yield
    columnar.reset_counters()
    resident.reset_counters()


@pytest.mark.columnar
class TestColumnMirror:
    def test_incremental_writes_keep_parity(self):
        h = Harness()
        st = h.state
        for i in range(12):
            st.upsert_node(h.next_index(), make_node(dc=f"dc{i % 3}"))
        assert_parity(st)

        nodes = st.nodes(None)
        st.update_node_status(h.next_index(), nodes[3].id,
                              s.NODE_STATUS_DOWN)
        st.update_node_drain(h.next_index(), nodes[5].id, True)
        # Re-upsert with changed resources (same dc/class: in-place).
        upd = nodes[7].copy()
        upd.resources.cpu += 512
        st.upsert_node(h.next_index(), upd)
        assert_parity(st)

        # Alloc writes: usage matrix follows the delta feed.
        al = mock.alloc()
        al.node_id = nodes[0].id
        al.resources = s.Resources(cpu=100, memory_mb=64, disk_mb=10)
        st.upsert_allocs(h.next_index(), [al])
        proto = mock.alloc()
        proto.resources = s.Resources(cpu=7, memory_mb=5, disk_mb=3)
        slab = s.AllocSlab(
            proto=proto, ids=s.LazyUuids(40), names=s.LazyNames(40, "j.tg"),
            node_ids=[nodes[i % 12].id for i in range(40)], prev_ids=[])
        st.upsert_slabs(h.next_index(), [slab])
        assert_usage_parity(st)

        # Eviction frees usage.
        stop = st.alloc_by_id(None, al.id).copy()
        stop.desired_status = s.ALLOC_DESIRED_STATUS_EVICT
        st.upsert_allocs(h.next_index(), [stop])
        assert_usage_parity(st)

    def test_delete_and_dc_change_rebuild(self):
        h = Harness()
        st = h.state
        for i in range(8):
            st.upsert_node(h.next_index(), make_node(dc=f"dc{i % 2}"))
        assert_parity(st)
        nodes = st.nodes(None)
        st.delete_node(h.next_index(), nodes[0].id)
        # Mirror dropped; next columns() rebuilds and matches the walk
        # (whose first-seen codebook order changed with the delete).
        assert st._columns is None
        assert_parity(st)
        # Datacenter change on an existing node also rebuilds.
        moved = st.nodes(None)[1].copy()
        moved.datacenter = "dc-new"
        st.upsert_node(h.next_index(), moved)
        assert st._columns is None
        assert_parity(st)

    def test_node_registered_after_allocs_backfills(self):
        h = Harness()
        st = h.state
        node_a = make_node()
        st.upsert_node(h.next_index(), node_a)
        st.columns()  # warm the mirror
        late = make_node()
        al = mock.alloc()
        al.node_id = late.id
        al.resources = s.Resources(cpu=55, memory_mb=44, disk_mb=33)
        st.upsert_allocs(h.next_index(), [al])
        # Node arrives AFTER its alloc: the fresh row must backfill.
        st.upsert_node(h.next_index(), late)
        assert_usage_parity(st)

    def test_snapshot_copy_on_write_isolation(self):
        h = Harness()
        st = h.state
        for _ in range(6):
            st.upsert_node(h.next_index(), make_node())
        nodes = st.nodes(None)
        al = mock.alloc()
        al.node_id = nodes[0].id
        al.resources = s.Resources(cpu=10, memory_mb=10, disk_mb=10)
        st.upsert_allocs(h.next_index(), [al])

        snap = st.snapshot()
        scols = snap.columns()
        before_usage = snap.column_usage(scols).copy()
        before_elig = scols.eligible[:scols.n].copy()

        # Parent advances: usage, eligibility, and a new node.
        al2 = mock.alloc()
        al2.node_id = nodes[1].id
        al2.resources = s.Resources(cpu=99, memory_mb=9, disk_mb=9)
        st.upsert_allocs(h.next_index(), [al2])
        st.update_node_drain(h.next_index(), nodes[2].id, True)
        st.upsert_node(h.next_index(), make_node())

        # Snapshot view unchanged, parent view advanced, both match
        # their own object walks.
        assert np.array_equal(snap.column_usage(scols), before_usage)
        assert np.array_equal(scols.eligible[:scols.n], before_elig)
        assert_parity(snap)
        assert_parity(st)
        assert_usage_parity(snap)
        assert_usage_parity(st)

    def test_randomized_sequence_bit_identical(self):
        rng = random.Random(17)
        h = Harness()
        st = h.state
        node_pool = []
        for _ in range(6):
            node = make_node(dc=f"dc{rng.randrange(3)}")
            node_pool.append(node)
            st.upsert_node(h.next_index(), node)
        live = []
        for step in range(60):
            op = rng.randrange(6)
            if op == 0:
                node = make_node(dc=f"dc{rng.randrange(3)}")
                node_pool.append(node)
                st.upsert_node(h.next_index(), node)
            elif op == 1:
                nid = rng.choice(node_pool).id
                st.update_node_drain(h.next_index(), nid, rng.random() < .5)
            elif op == 2:
                nid = rng.choice(node_pool).id
                st.update_node_status(
                    h.next_index(),
                    nid, rng.choice([s.NODE_STATUS_READY,
                                     s.NODE_STATUS_DOWN]))
            elif op == 3:
                al = mock.alloc()
                al.node_id = rng.choice(node_pool).id
                al.resources = s.Resources(
                    cpu=rng.randrange(1, 200), memory_mb=rng.randrange(64),
                    disk_mb=rng.randrange(32))
                st.upsert_allocs(h.next_index(), [al])
                live.append(al.id)
            elif op == 4 and live:
                aid = live.pop(rng.randrange(len(live)))
                stop = st.alloc_by_id(None, aid).copy()
                stop.desired_status = s.ALLOC_DESIRED_STATUS_STOP
                st.upsert_allocs(h.next_index(), [stop])
            else:
                proto = mock.alloc()
                proto.resources = s.Resources(cpu=3, memory_mb=2, disk_mb=1)
                cnt = rng.randrange(1, 20)
                st.upsert_slabs(h.next_index(), [s.AllocSlab(
                    proto=proto, ids=s.LazyUuids(cnt),
                    names=s.LazyNames(cnt, "j.tg"),
                    node_ids=[rng.choice(node_pool).id
                              for _ in range(cnt)], prev_ids=[])])
            if step % 7 == 0:
                assert_parity(st)
                assert_usage_parity(st)
        assert_parity(st)
        assert_usage_parity(st)

    def test_snapshot_folds_owner_cursor_past_log_trim(self, monkeypatch):
        """The owner's usage cursor must not fall off the bounded delta
        log: snapshot() folds/rebuilds ON THE OWNER when the backlog
        grows or the trim floor passes the cursor, so per-batch views
        stay O(recent) instead of each paying a full row walk."""
        from nomad_tpu.state import state_store as ss_mod

        monkeypatch.setattr(ss_mod, "ALLOC_LOG_CAP", 64)
        monkeypatch.setattr(StateStore, "COL_FOLD_BACKLOG", 16)
        h = Harness()
        st = h.state
        node = make_node()
        st.upsert_node(h.next_index(), node)
        cols = st.columns()
        frozen = cols.usage_index
        # Push far more deltas than the cap: the log trims and its
        # floor rises past the frozen cursor.
        for _ in range(200):
            al = mock.alloc()
            al.node_id = node.id
            al.resources = s.Resources(cpu=1, memory_mb=1, disk_mb=1)
            st.upsert_allocs(h.next_index(), [al])
        assert st._alloc_log_floor > frozen
        snap = st.snapshot()
        # Owner cursor advanced (rebuild/fold happened owner-side)...
        assert st._columns.usage_index > frozen
        # ...and the view's usage is still exact.
        assert_usage_parity(snap)

    def test_kill_switch_disables_columnar(self, monkeypatch):
        h = Harness()
        st = h.state
        st.upsert_node(h.next_index(), make_node())
        assert st.columns() is not None
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "0")
        assert st.columns() is None
        ct = encode.build_cluster_static(st, st.nodes(None), [], {})
        assert not getattr(ct, "_columnar", False)
        assert st.persist()[:8] != StateStore.SNAP2_MAGIC
        # Maintenance continued while off: re-enabling stays correct.
        st.upsert_node(h.next_index(), make_node())
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "1")
        assert_parity(st)


@pytest.mark.columnar
class TestGuardAndScheduler:
    def test_scheduled_batch_uses_columnar_and_guard_passes(self):
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())
        job = make_job(3)
        h.state.upsert_job(h.next_index(), job)
        sched = TPUBatchScheduler(h.logger, h.snapshot(), h)
        sched.schedule_batch([reg_eval(job)])
        assert columnar.COLUMNAR_ENCODES >= 1
        assert columnar.GUARD_RUNS >= 1
        assert columnar.GUARD_MISMATCHES == 0
        placed = [a for a in h.state.allocs_by_job(None, job.id, True)
                  if not a.terminal_status()]
        assert len(placed) == 3

    def test_injected_corruption_trips_breaker_and_walk_carries(self):
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=3600.0)
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())
        job = make_job(2)
        h.state.upsert_job(h.next_index(), job)
        epoch_before = columnar.EPOCH
        with fault.scenario({"seed": 5, "faults": [
                {"point": "state.columns", "action": "corrupt",
                 "times": 1}]}):
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                                      breaker=brk)
            sched.schedule_batch([reg_eval(job)])
        assert columnar.GUARD_MISMATCHES == 1
        assert columnar.EPOCH == epoch_before + 1
        assert brk.state == "open"
        # The walk's buffers carried the batch: placements landed.
        placed = [a for a in h.state.allocs_by_job(None, job.id, True)
                  if not a.terminal_status()]
        assert len(placed) == 2
        # Epoch bump invalidated every container; rebuild restores parity.
        assert_parity(h.state)

    def test_columnar_on_off_identical_placements(self, monkeypatch):
        def run(flag):
            monkeypatch.setenv("NOMAD_TPU_COLUMNAR", flag)
            monkeypatch.setenv("NOMAD_TPU_RNG_SEED", "11")
            h = Harness()
            for i in range(8):
                node = make_node(dc=f"dc{i % 2}")
                node.id = f"fixed-node-{i:02d}"
                node.compute_class()
                h.state.upsert_node(h.next_index(), node)
            job = make_job(5)
            job.id = "fixed-job"
            h.state.upsert_job(h.next_index(), job)
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h)
            sched.schedule_batch([reg_eval(job)])
            return sorted(
                (a.node_id, a.task_group)
                for a in h.state.allocs_by_job(None, job.id, True)
                if not a.terminal_status())

        on = run("1")
        off = run("0")
        assert on == off and len(on) == 5


@pytest.mark.columnar
class TestBinarySnapshot:
    def _build_store(self):
        h = Harness()
        st = h.state
        nodes = [make_node(dc=f"dc{i % 3}") for i in range(10)]
        for node in nodes:
            st.upsert_node(h.next_index(), node)
        job = make_job(4)
        st.upsert_job(h.next_index(), job)
        ev = reg_eval(job)
        st.upsert_evals(h.next_index(), [ev])
        al = mock.alloc()
        al.node_id = nodes[0].id
        al.job = job
        al.job_id = job.id
        st.upsert_allocs(h.next_index(), [al])
        proto = mock.alloc()
        proto.job = job
        proto.job_id = job.id
        proto.resources = s.Resources(cpu=9, memory_mb=8, disk_mb=7)
        slab = s.AllocSlab(
            proto=proto, ids=s.LazyUuids(30), names=s.LazyNames(30, "j.tg"),
            node_ids=[nodes[i % 10].id for i in range(30)], prev_ids=[])
        st.upsert_slabs(h.next_index(), [slab])
        return h, st, slab

    @staticmethod
    def _dump(st):
        """Semantic table dump (wire form) for bit-identity compares —
        dict iteration order differs across restore paths by design."""
        st._materialize_pending()
        return {
            "nodes": {k: to_wire(v) for k, v in st.nodes_table.items()},
            "jobs": {k: to_wire(v) for k, v in st.jobs_table.items()},
            "evals": {k: to_wire(v) for k, v in st.evals_table.items()},
            "allocs": {k: to_wire(st._get_alloc(k))
                       for k in st.allocs_table},
            "summaries": {k: to_wire(v)
                          for k, v in st.job_summary_table.items()},
            "indexes": dict(st._indexes),
        }

    def test_roundtrip_bit_identity_both_directions(self, monkeypatch):
        _, st, slab = self._build_store()
        blob_v2 = st.persist()
        assert blob_v2[:8] == StateStore.SNAP2_MAGIC
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "0")
        blob_legacy = st.persist()
        assert blob_legacy[:8] != StateStore.SNAP2_MAGIC
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "1")

        ref = self._dump(st)
        from_v2 = StateStore.restore(blob_v2)
        from_legacy = StateStore.restore(blob_legacy)
        assert self._dump(from_v2) == ref
        assert self._dump(from_legacy) == ref
        # Cross-direction: a v2-restored store persists a legacy blob
        # that restores identically, and vice versa.
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "0")
        again_legacy = StateStore.restore(StateStore.restore(
            blob_v2).persist())
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "1")
        again_v2 = StateStore.restore(StateStore.restore(
            blob_legacy).persist())
        assert self._dump(again_legacy) == ref
        assert self._dump(again_v2) == ref

    def test_v2_restores_slabs_lazily(self):
        _, st, slab = self._build_store()
        restored = StateStore.restore(st.persist())
        # Slabs come back PENDING — no per-alloc table rows until read.
        assert restored._pending_slabs
        assert restored.alloc_by_id(None, slab.ids[7]) is not None
        assert not restored._pending_slabs

    def test_v2_restore_skips_dead_slab_slots(self):
        h, st, slab = self._build_store()
        # Client-update one slab slot (replaces the table entry) and
        # GC another via eval delete.
        victim = slab.ids[3]
        upd = st.alloc_by_id(None, victim).copy()
        upd.client_status = s.ALLOC_CLIENT_STATUS_FAILED
        st.update_allocs_from_client(h.next_index(), [upd])
        gone = slab.ids[4]
        st.delete_eval(h.next_index(), [], [gone])
        ref = self._dump(st)
        restored = StateStore.restore(st.persist())
        assert self._dump(restored) == ref
        assert restored.alloc_by_id(None, gone) is None
        assert restored.alloc_by_id(
            None, victim).client_status == s.ALLOC_CLIENT_STATUS_FAILED

    def test_fsm_snapshot_restore_roundtrip(self):
        from nomad_tpu.server.fsm import FSM

        _, st, _ = self._build_store()
        fsm = FSM(state=st)
        blob = fsm.snapshot()
        fsm2 = FSM()
        fsm2.restore(blob)
        assert self._dump(fsm2.state) == self._dump(st)
        # Restored store encodes through the warm columns immediately.
        assert fsm2.state._columns is not None
        assert_parity(fsm2.state)
        assert_usage_parity(fsm2.state)

    def test_restored_store_keeps_scheduling(self):
        h, st, _ = self._build_store()
        restored = StateStore.restore(st.persist())
        h.state = restored
        job = make_job(2)
        restored.upsert_job(h.next_index(), job)
        sched = TPUBatchScheduler(h.logger, restored.snapshot(), h)
        sched.schedule_batch([reg_eval(job)])
        placed = [a for a in restored.allocs_by_job(None, job.id, True)
                  if not a.terminal_status()]
        assert len(placed) == 2
        assert columnar.GUARD_MISMATCHES == 0


@pytest.mark.columnar
@pytest.mark.slow
class TestRestoreTimeRegression:
    def test_100k_node_snapshot_restore_under_budget(self):
        """Scale regression: 100k nodes + 200k slab allocs must persist
        AND restore in single-digit seconds through the v2 path (the
        legacy msgpack path measured ~75s each way on this shape)."""
        st = StateStore()
        n = 100_000
        proto_node = make_node()
        for i in range(n):
            node = s._fast_copy(proto_node)
            node.id = f"node-{i:06d}"
            node.name = f"n{i}"
            node.resources = proto_node.resources
            st.upsert_node(i + 1, node)
        proto = mock.alloc()
        proto.resources = s.Resources(cpu=5, memory_mb=4, disk_mb=3)
        m = 200_000
        st.upsert_slabs(n + 2, [s.AllocSlab(
            proto=proto, ids=s.LazyUuids(m), names=s.LazyNames(m, "j.tg"),
            node_ids=[f"node-{i % n:06d}" for i in range(m)],
            prev_ids=[])])
        t0 = time.monotonic()
        blob = st.persist()
        persist_s = time.monotonic() - t0
        t0 = time.monotonic()
        restored = StateStore.restore(blob)
        restore_s = time.monotonic() - t0
        assert persist_s < 15.0, f"persist took {persist_s:.1f}s"
        assert restore_s < 15.0, f"restore took {restore_s:.1f}s"
        assert len(restored.nodes_table) == n
        cols = restored.columns()
        assert cols is not None and cols.n == n
        assert int(restored.column_usage(cols)[:, 0].sum()) == 5 * m


@pytest.mark.columnar
@pytest.mark.chaos
class TestWalFsyncChaos:
    def test_crash_mid_frame_recovers_with_torn_tail_truncated(
            self, tmp_path):
        from nomad_tpu.server.fsm import FSM, MessageType
        from nomad_tpu.server.raft import FileLog

        d = str(tmp_path / "raft")
        flog = FileLog(FSM(), d)
        native = flog._nwal is not None
        node = make_node()
        flog.apply(MessageType.NODE_REGISTER, {"node": node})
        applied = flog.applied_index()
        job = make_job(1)
        with fault.scenario({"seed": 3, "faults": [
                {"point": "wal.fsync", "action": "crash", "times": 1}]}):
            with pytest.raises(Exception):
                flog.apply(MessageType.JOB_REGISTER, {"job": job})
        flog.close()
        wal_file = os.path.join(d, "wal.crc" if native else "wal.log")
        torn = os.path.getsize(wal_file)

        flog2 = FileLog(FSM(), d)
        assert flog2.applied_index() == applied
        assert flog2.fsm.state.node_by_id(None, node.id) is not None
        assert flog2.fsm.state.job_by_id(None, job.id) is None
        assert os.path.getsize(wal_file) < torn, "torn tail not truncated"
        flog2.apply(MessageType.JOB_REGISTER, {"job": job})
        applied2 = flog2.applied_index()
        flog2.close()

        flog3 = FileLog(FSM(), d)
        assert flog3.applied_index() == applied2
        assert flog3.fsm.state.job_by_id(None, job.id) is not None
        flog3.close()

    def test_fsync_delay_point_slows_but_preserves_apply(self, tmp_path):
        from nomad_tpu.server.fsm import FSM, MessageType
        from nomad_tpu.server.raft import FileLog

        flog = FileLog(FSM(), str(tmp_path / "raft"))
        with fault.scenario({"seed": 1, "faults": [
                {"point": "wal.fsync", "action": "delay",
                 "delay": 0.05, "times": 1}]}):
            t0 = time.monotonic()
            flog.apply(MessageType.NODE_REGISTER, {"node": make_node()})
            assert time.monotonic() - t0 >= 0.05
        assert flog.applied_index() == 1
        flog.close()
