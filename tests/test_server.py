"""Server integration tests: the full control-plane pipeline in-process
(reference: nomad/worker_test.go, plan_apply_test.go, leader_test.go,
eval_broker_test.go — in-process servers, SURVEY.md §4 item 3)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import (
    EvalBroker,
    EvalBrokerError,
    MessageType,
    Server,
    ServerConfig,
)
from nomad_tpu.structs import structs as s


def wait_until(predicate, timeout=60.0, interval=0.02):
    """Generous default: the first tpu-batch placement in a process pays
    the XLA compile, which under load can take >10s — and in the quick
    tier (-m "not slow") no earlier kernel module has warmed the
    in-process cache, so this file's first placement pays it all."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


@pytest.fixture(params=[False, True], ids=["oracle-worker", "tpu-batch-worker"])
def server(request):
    """Every pipeline test runs twice: once through the per-eval oracle
    Worker and once through the TPU BatchWorker (worker.go:55 vs the
    batching-replaces-concurrency design, SURVEY.md §2.9) — the server
    semantics must be identical."""
    srv = Server(ServerConfig(num_schedulers=1,
                              use_tpu_batch_worker=request.param,
                              batch_size=8))
    srv.start()
    yield srv
    srv.shutdown()


def make_node():
    n = mock.node()
    n.resources.networks = []
    n.reserved.networks = []
    return n


def make_job(count=3):
    j = mock.job()
    j.task_groups[0].count = count
    for t in j.task_groups[0].tasks:
        t.resources.networks = []
    return j


class TestEndToEnd:
    def test_register_job_runs_through_pipeline(self, server):
        for _ in range(3):
            server.node_register(make_node())
        job = make_job(3)
        index, eval_id = server.job_register(job)
        assert eval_id

        assert wait_until(
            lambda: len(server.state.allocs_by_job(None, job.id, True)) == 3)
        # eval completion lands one raft apply AFTER the plan: wait, don't
        # sample (the worker acks between the two applies).
        assert wait_until(
            lambda: server.state.eval_by_id(None, eval_id).status
            == s.EVAL_STATUS_COMPLETE)
        # allocs have create_time stamped by plan apply
        for a in server.state.allocs_by_job(None, job.id, True):
            assert a.create_time > 0

    def test_capacity_exhaustion_blocks_then_unblocks(self, server):
        node = make_node()
        node.resources.cpu = 1100  # fits 2 x 500 after 100 reserved
        server.node_register(node)
        job = make_job(4)
        _, eval_id = server.job_register(job)

        assert wait_until(
            lambda: len(server.state.allocs_by_job(None, job.id, True)) == 2)
        # blocked eval tracked
        assert wait_until(
            lambda: server.blocked_evals.stats()["total_blocked"] == 1)

        # new capacity arrives → unblock → remaining 2 placed
        server.node_register(make_node())
        assert wait_until(
            lambda: len([
                a for a in server.state.allocs_by_job(None, job.id, True)
                if a.desired_status == s.ALLOC_DESIRED_STATUS_RUN]) == 4,
            timeout=15.0)

    def test_node_down_triggers_replacement(self, server):
        n1, n2 = make_node(), make_node()
        server.node_register(n1)
        server.node_register(n2)
        job = make_job(2)
        server.job_register(job)
        assert wait_until(
            lambda: len(server.state.allocs_by_job(None, job.id, True)) == 2)

        victims = [a for a in server.state.allocs_by_job(None, job.id, True)
                   if a.node_id == n1.id]
        server.node_update_status(n1.id, s.NODE_STATUS_DOWN)

        def replaced():
            allocs = server.state.allocs_by_job(None, job.id, True)
            live = [a for a in allocs
                    if a.desired_status == s.ALLOC_DESIRED_STATUS_RUN
                    and a.node_id == n2.id]
            lost = [a for a in allocs if a.client_status == s.ALLOC_CLIENT_STATUS_LOST]
            return len(live) == 2 and len(lost) == len(victims)

        assert wait_until(replaced)

    def test_heartbeat_expiry_marks_node_down(self):
        srv = Server(ServerConfig(num_schedulers=1, min_heartbeat_ttl=0.3,
                                  max_heartbeats_per_second=1000.0))
        srv.heartbeat.grace = 0.2
        srv.start()
        try:
            node = make_node()
            srv.node_register(node)
            srv.node_update_status(node.id, s.NODE_STATUS_READY)
            # stop heartbeating: TTL 0.3 + grace 0.2 → down within ~1s
            assert wait_until(
                lambda: srv.state.node_by_id(None, node.id).status == s.NODE_STATUS_DOWN,
                timeout=5.0)
        finally:
            srv.shutdown()

    def test_job_deregister_stops_allocs(self, server):
        server.node_register(make_node())
        job = make_job(2)
        server.job_register(job)
        assert wait_until(
            lambda: len(server.state.allocs_by_job(None, job.id, True)) == 2)
        server.job_deregister(job.id, purge=False)
        assert wait_until(
            lambda: all(a.desired_status == s.ALLOC_DESIRED_STATUS_STOP
                        for a in server.state.allocs_by_job(None, job.id, True)))

    def test_system_job_on_all_nodes(self, server):
        nodes = [make_node() for _ in range(3)]
        for n in nodes:
            server.node_register(n)
            server.node_update_status(n.id, s.NODE_STATUS_READY)
        job = mock.system_job()
        for t in job.task_groups[0].tasks:
            t.resources.networks = []
        server.job_register(job)
        assert wait_until(
            lambda: len(server.state.allocs_by_job(None, job.id, True)) == 3)
        placed_nodes = {a.node_id for a in server.state.allocs_by_job(None, job.id, True)}
        assert placed_nodes == {n.id for n in nodes}

    def test_periodic_job_dispatches_child(self, server):
        job = mock.job()
        for t in job.task_groups[0].tasks:
            t.resources.networks = []
        job.type = s.JOB_TYPE_BATCH
        # test spec: launch once, just in the future
        launch_at = time.time() + 0.5
        job.periodic = s.PeriodicConfig(
            enabled=True, spec_type=s.PERIODIC_SPEC_TEST, spec=str(launch_at))
        server.node_register(make_node())
        index, eval_id = server.job_register(job)
        assert eval_id == ""  # periodic jobs get no immediate eval

        def child_exists():
            return any(j.parent_id == job.id for j in server.state.jobs(None))

        assert wait_until(child_exists, timeout=30.0)
        # The launch record is a separate raft apply from the child job.
        assert wait_until(
            lambda: server.state.periodic_launch_by_id(None, job.id)
            is not None)

    def test_force_gc_removes_terminal_evals(self, server):
        server.node_register(make_node())
        job = make_job(1)
        _, eval_id = server.job_register(job)
        assert wait_until(
            lambda: server.state.eval_by_id(None, eval_id) is not None and
            server.state.eval_by_id(None, eval_id).status == s.EVAL_STATUS_COMPLETE)
        # mark the allocs client-terminal so the eval becomes GC-able
        allocs = server.state.allocs_by_job(None, job.id, True)
        for a in allocs:
            done = a.copy()
            done.client_status = s.ALLOC_CLIENT_STATUS_COMPLETE
            server.node_update_allocs([done])
        server.system_gc()
        assert wait_until(
            lambda: server.state.eval_by_id(None, eval_id) is None, timeout=10.0)


class TestEvalBroker:
    def make_eval(self, job_id=None, priority=50):
        ev = mock.eval()
        ev.priority = priority
        if job_id:
            ev.job_id = job_id
        return ev

    def test_enqueue_dequeue_ack(self):
        b = EvalBroker(nack_timeout=5.0)
        b.set_enabled(True)
        ev = self.make_eval()
        b.enqueue(ev)
        out, token = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        assert out.id == ev.id
        assert token
        assert b.outstanding(ev.id) == (token, True)
        b.ack(ev.id, token)
        assert b.outstanding(ev.id) == ("", False)
        assert b.stats()["total_ready"] == 0

    def test_priority_order(self):
        b = EvalBroker()
        b.set_enabled(True)
        low = self.make_eval(priority=20)
        high = self.make_eval(priority=90)
        b.enqueue(low)
        b.enqueue(high)
        out, t1 = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        assert out.id == high.id
        b.ack(high.id, t1)
        out2, _ = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        assert out2.id == low.id

    def test_per_job_serialization(self):
        b = EvalBroker()
        b.set_enabled(True)
        ev1 = self.make_eval(job_id="same-job")
        ev2 = self.make_eval(job_id="same-job")
        b.enqueue(ev1)
        b.enqueue(ev2)
        out1, t1 = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        # second eval for the job is blocked until ack
        out2, _ = b.dequeue([s.JOB_TYPE_SERVICE], 0)
        assert out2 is None
        b.ack(out1.id, t1)
        out3, _ = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        assert out3.id == ev2.id

    def test_nack_redelivers_then_fails(self):
        b = EvalBroker(nack_timeout=5.0, initial_nack_delay=0.0,
                       subsequent_nack_delay=0.0, delivery_limit=2)
        b.set_enabled(True)
        ev = self.make_eval()
        b.enqueue(ev)
        out, token = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        b.nack(ev.id, token)
        out, token2 = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        assert out.id == ev.id
        b.nack(ev.id, token2)
        # delivery limit hit → failed queue only
        out_none, _ = b.dequeue([s.JOB_TYPE_SERVICE], 0)
        assert out_none is None
        failed, _ = b.dequeue(["_failed"], 1.0)
        assert failed.id == ev.id

    def test_token_fencing(self):
        b = EvalBroker()
        b.set_enabled(True)
        ev = self.make_eval()
        b.enqueue(ev)
        out, token = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        with pytest.raises(EvalBrokerError):
            b.ack(ev.id, "wrong-token")
        b.ack(ev.id, token)

    def test_wait_delay(self):
        b = EvalBroker()
        b.set_enabled(True)
        ev = self.make_eval()
        ev.wait = 0.2
        b.enqueue(ev)
        out, _ = b.dequeue([s.JOB_TYPE_SERVICE], 0)
        assert out is None
        time.sleep(0.3)
        out, _ = b.dequeue([s.JOB_TYPE_SERVICE], 1.0)
        assert out.id == ev.id

    def test_dequeue_batch_drains(self):
        b = EvalBroker()
        b.set_enabled(True)
        evals = [self.make_eval() for _ in range(5)]
        for ev in evals:
            b.enqueue(ev)
        batch = b.dequeue_batch([s.JOB_TYPE_SERVICE], 10, 1.0)
        assert len(batch) == 5
        for ev, token in batch:
            b.ack(ev.id, token)


class TestRaftPersistence:
    def test_wal_replay_and_snapshot(self, tmp_path):
        cfg = ServerConfig(data_dir=str(tmp_path / "raft"))
        srv = Server(cfg)
        srv.start()
        try:
            srv.node_register(make_node())
            job = make_job(2)
            _, eval_id = srv.job_register(job)
            assert wait_until(
                lambda: len(srv.state.allocs_by_job(None, job.id, True)) == 2)
            # Quiesce before sampling: the worker's eval-complete
            # EVAL_UPDATE applies AFTER the placements become visible,
            # and sampling mid-stream made the restart comparison flaky
            # (replay legitimately recovered one more entry).
            assert wait_until(
                lambda: srv.state.eval_by_id(None, eval_id).status
                == s.EVAL_STATUS_COMPLETE)
            applied = srv.raft.applied_index()
        finally:
            srv.shutdown()

        # restart: WAL replay restores everything
        srv2 = Server(ServerConfig(data_dir=str(tmp_path / "raft")))
        try:
            assert srv2.raft.applied_index() == applied
            assert len(srv2.state.allocs_by_job(None, job.id, True)) == 2
            assert len(srv2.state.nodes(None)) == 1
            # snapshot + truncate, then restart again
            srv2.raft.snapshot()
        finally:
            srv2.raft.close()

        srv3 = Server(ServerConfig(data_dir=str(tmp_path / "raft")))
        try:
            assert srv3.raft.applied_index() == applied
            assert len(srv3.state.allocs_by_job(None, job.id, True)) == 2
        finally:
            srv3.raft.close()


class TestWALTornTail:
    def test_torn_tail_truncated_then_appended(self, tmp_path):
        """A torn tail record must be truncated on recovery so later
        appends stay reachable (raft.py FileLog._recover)."""
        data_dir = str(tmp_path / "raft")
        srv = Server(ServerConfig(data_dir=data_dir))
        srv.start()
        try:
            srv.node_register(make_node())
            applied = srv.raft.applied_index()
        finally:
            srv.shutdown()

        # simulate a crash mid-write: garbage half-record at the tail
        wal = os.path.join(data_dir, "wal.log")
        with open(wal, "ab") as f:
            f.write(b"\x40\x00\x00\x00partial")

        srv2 = Server(ServerConfig(data_dir=data_dir))
        try:
            assert srv2.raft.applied_index() == applied
            # new durable entries land after the truncated tail
            job = make_job(1)
            srv2.job_register(job)
            applied2 = srv2.raft.applied_index()
            assert applied2 > applied
        finally:
            srv2.raft.close()

        # both the old and the new entries replay
        srv3 = Server(ServerConfig(data_dir=data_dir))
        try:
            assert srv3.raft.applied_index() == applied2
            assert srv3.state.job_by_id(None, job.id) is not None
            assert len(srv3.state.nodes(None)) == 1
        finally:
            srv3.raft.close()

    def test_undecodable_native_record_truncated_then_appended(
            self, tmp_path):
        """A CRC-valid but undecodable record (garbage flush, or a
        pre-msgpack-format file) must end replay at the good prefix AND
        rewrite the native log to it, so post-recovery appends stay
        reachable on the next replay (raft.py FileLog._recover)."""
        import struct
        import zlib

        data_dir = str(tmp_path / "raft")
        srv = Server(ServerConfig(data_dir=data_dir))
        srv.start()
        try:
            srv.node_register(make_node())
            applied = srv.raft.applied_index()
            native = srv.raft._nwal is not None
        finally:
            srv.shutdown()

        # Append a CRC-valid record whose payload is not a msgpack entry
        # to whichever log is in use.
        garbage = b"\x93not-an-entry"
        crc_path = os.path.join(data_dir, "wal.crc")
        if native or os.path.exists(crc_path):
            with open(crc_path, "ab") as f:
                f.write(struct.pack("<II", len(garbage),
                                    zlib.crc32(garbage) & 0xFFFFFFFF))
                f.write(garbage)
        else:
            with open(os.path.join(data_dir, "wal.log"), "ab") as f:
                f.write(struct.pack("<Q", len(garbage)))
                f.write(garbage)

        srv2 = Server(ServerConfig(data_dir=data_dir))
        try:
            assert srv2.raft.applied_index() == applied
            job = make_job(1)
            srv2.job_register(job)
            applied2 = srv2.raft.applied_index()
            assert applied2 > applied
        finally:
            srv2.raft.close()

        srv3 = Server(ServerConfig(data_dir=data_dir))
        try:
            assert srv3.raft.applied_index() == applied2
            assert srv3.state.job_by_id(None, job.id) is not None
            assert len(srv3.state.nodes(None)) == 1
        finally:
            srv3.raft.close()


class TestPeriodicReAdd:
    def test_re_add_does_not_duplicate_chain(self):
        """Updating a tracked periodic job must not leave two live
        dispatch chains (periodic.py generation tombstones)."""
        from nomad_tpu.server.periodic import PeriodicDispatch

        launches = []
        pd = PeriodicDispatch(lambda parent, derived, t: launches.append(t))
        pd.set_enabled(True)
        job = make_job(1)
        now = time.time()
        spec = f"{now + 0.3},{now + 0.6}"
        job.periodic = s.PeriodicConfig(enabled=True, spec=spec,
                                        spec_type=s.PERIODIC_SPEC_TEST)
        pd.add(job)
        pd.add(job)  # re-register (spec update)
        pd.add(job)
        time.sleep(1.2)
        pd.set_enabled(False)
        # one chain fires each timestamp exactly once; duplicated chains
        # would fire them 3x
        assert len(launches) == 2, launches
        assert len(launches) == len(set(launches)), "duplicate launch times"


class TestBatchWorkerMixedStream:
    """A mixed eval stream (service + batch + system + blocked + a nacked
    batch) through the TPU BatchWorker — the worker_test.go role for the
    batch path (VERDICT r1 weak #3)."""

    def _mk_server(self):
        srv = Server(ServerConfig(num_schedulers=1,
                                  use_tpu_batch_worker=True, batch_size=8))
        srv.eval_broker.initial_nack_delay = 0.05
        srv.start()
        return srv

    def test_mixed_stream_places_everything(self):
        srv = self._mk_server()
        try:
            nodes = [make_node() for _ in range(4)]
            for n in nodes:
                srv.node_register(n)
                srv.node_update_status(n.id, s.NODE_STATUS_READY)

            service_jobs = [make_job(2) for _ in range(3)]
            batch_jobs = []
            for _ in range(2):
                j = make_job(1)
                j.type = s.JOB_TYPE_BATCH
                batch_jobs.append(j)
            sys_job = mock.system_job()
            for t in sys_job.task_groups[0].tasks:
                t.resources.networks = []

            for j in service_jobs + batch_jobs + [sys_job]:
                srv.job_register(j)

            for j in service_jobs:
                assert wait_until(lambda j=j: len(
                    srv.state.allocs_by_job(None, j.id, True)) == 2), \
                    f"service job {j.id} not fully placed"
            for j in batch_jobs:
                assert wait_until(lambda j=j: len(
                    srv.state.allocs_by_job(None, j.id, True)) == 1)
            # system job lands on every ready node despite the
            # service/batch stream (BatchWorker polls system/core too)
            assert wait_until(lambda: len(
                srv.state.allocs_by_job(None, sys_job.id, True)) == 4)
        finally:
            srv.shutdown()

    def test_batch_failure_nacks_and_redelivers(self, monkeypatch):
        """A scheduler crash nacks the whole batch; the broker redelivers
        and the second attempt places (eval_broker.go:540 Nack path)."""
        from nomad_tpu.ops import batch_sched as bs

        calls = {"n": 0}
        orig = bs.TPUBatchScheduler.schedule_batch

        def flaky(self, evals):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected batch failure")
            return orig(self, evals)

        monkeypatch.setattr(bs.TPUBatchScheduler, "schedule_batch", flaky)
        srv = self._mk_server()
        try:
            srv.node_register(make_node())
            job = make_job(2)
            _, eval_id = srv.job_register(job)
            # generous: redelivery + a cold XLA compile under full-suite
            # contention on a shared box
            assert wait_until(lambda: len(
                srv.state.allocs_by_job(None, job.id, True)) == 2, 60.0)
            assert calls["n"] >= 2
            assert wait_until(
                lambda: srv.state.eval_by_id(None, eval_id).status
                == s.EVAL_STATUS_COMPLETE)
        finally:
            srv.shutdown()

    def test_blocked_eval_unblocks_through_batch_worker(self):
        srv = self._mk_server()
        try:
            node = make_node()
            node.resources.cpu = 1100  # fits 2 x 500 after 100 reserved
            srv.node_register(node)
            job = make_job(4)
            srv.job_register(job)
            assert wait_until(lambda: len(
                srv.state.allocs_by_job(None, job.id, True)) == 2)
            assert wait_until(
                lambda: srv.blocked_evals.stats()["total_blocked"] == 1)
            srv.node_register(make_node())
            assert wait_until(lambda: len([
                a for a in srv.state.allocs_by_job(None, job.id, True)
                if a.desired_status == s.ALLOC_DESIRED_STATUS_RUN]) == 4,
                timeout=15.0)
        finally:
            srv.shutdown()
