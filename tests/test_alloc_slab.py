"""Columnar AllocSlab path: state-store equivalence with per-object
upserts, plan-applier partial/gang commits, log-codec round-trip, and
snapshot persistence (the bulk-placement machinery behind the TPU batch
scheduler's finalize phase)."""
from __future__ import annotations

import pytest

from nomad_tpu import mock
from nomad_tpu.state import StateStore
from nomad_tpu.structs import structs as s


def _proto(job, ev_id="ev-1"):
    """Prototype like batch_sched._finalize builds — the slab path only
    serves no-network specs (network asks take the per-alloc offer path),
    so the mock tasks' network asks are stripped."""
    tg = job.task_groups[0]
    for t in tg.tasks:
        t.resources.networks = []
    combined = s.Resources(disk_mb=tg.ephemeral_disk.size_mb)
    for t in tg.tasks:
        combined.add(t.resources)
    return s.Allocation(
        eval_id=ev_id,
        job_id=job.id,
        job=job,
        task_group=tg.name,
        resources=combined,
        task_resources={t.name: t.resources.copy() for t in tg.tasks},
        desired_status=s.ALLOC_DESIRED_STATUS_RUN,
        client_status=s.ALLOC_CLIENT_STATUS_PENDING,
        shared_resources=s.Resources(disk_mb=tg.ephemeral_disk.size_mb),
    )


def _slab(job, nodes, ev_id="ev-1"):
    k = len(nodes)
    return s.AllocSlab(
        proto=_proto(job, ev_id),
        ids=s.generate_uuids(k),
        names=[f"{job.name}.{job.task_groups[0].name}[{i}]" for i in range(k)],
        node_ids=list(nodes),
    )


def _store_with_job(n_nodes=3, job=None):
    store = StateStore()
    if job is None:
        job = mock.job()
        job.task_groups[0].count = n_nodes
    store.upsert_job(1, job)
    # Real flows thread the STATE-STORED job (with its create_index) into
    # plans/allocs; use it so the summary create_index guard matches.
    job = store.job_by_id(None, job.id)
    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.id = f"node-{i}"
        store.upsert_node(2, node)
        nodes.append(node)
    return store, job, nodes


def test_slab_upsert_equivalent_to_object_upsert():
    """A slab insert must leave the store observably identical to
    inserting the same allocs as objects."""
    store_a, job_a, nodes_a = _store_with_job()
    store_b, job_b, _ = _store_with_job(job=job_a)
    node_ids = [n.id for n in nodes_a]

    slab = _slab(job_a, node_ids)
    store_a.upsert_slabs(10, [slab])

    allocs = []
    for i, nid in enumerate(node_ids):
        a = _proto(job_b)
        a.id = slab.ids[i]
        a.name = slab.names[i]
        a.node_id = nid
        allocs.append(a)
    store_b.upsert_allocs(10, allocs, owned=True)

    got_a = sorted(store_a.allocs(None), key=lambda a: a.id)
    got_b = sorted(store_b.allocs(None), key=lambda a: a.id)
    assert [a.id for a in got_a] == [a.id for a in got_b]
    for x, y in zip(got_a, got_b):
        assert (x.name, x.node_id, x.job_id, x.create_index, x.modify_index,
                x.client_status) == (
            y.name, y.node_id, y.job_id, y.create_index, y.modify_index,
            y.client_status)

    # Secondary indexes behave identically.
    for nid in node_ids:
        assert ([a.id for a in store_a.allocs_by_node(None, nid)]
                == [a.id for a in store_b.allocs_by_node(None, nid)])
    assert (len(store_a.allocs_by_job(None, job_a.id, True))
            == len(store_b.allocs_by_job(None, job_b.id, True)))
    assert (len(store_a.allocs_by_eval(None, "ev-1"))
            == len(store_b.allocs_by_eval(None, "ev-1")))

    # Summary bulk update matches the per-alloc accounting.
    sum_a = store_a.job_summary_by_id(None, job_a.id)
    sum_b = store_b.job_summary_by_id(None, job_b.id)
    tg = job_a.task_groups[0].name
    assert sum_a.summary[tg].starting == sum_b.summary[tg].starting == 3
    # Job flipped to running both ways.
    assert store_a.job_by_id(None, job_a.id).status == s.JOB_STATUS_RUNNING


def test_slab_lazy_materialization_caches():
    store, job, nodes = _store_with_job()
    slab = _slab(job, [n.id for n in nodes])
    store.upsert_slabs(10, [slab])
    aid = slab.ids[1]
    a1 = store.alloc_by_id(None, aid)
    a2 = store.alloc_by_id(None, aid)
    assert a1 is a2, "materialized alloc should be cached back"
    assert a1.node_id == nodes[1].id
    assert a1.create_index == 10 and a1.modify_index == 10


def test_slab_client_update_and_remove():
    store, job, nodes = _store_with_job()
    slab = _slab(job, [n.id for n in nodes])
    store.upsert_slabs(10, [slab])

    upd = s.Allocation(id=slab.ids[0],
                       client_status=s.ALLOC_CLIENT_STATUS_RUNNING)
    store.update_allocs_from_client(11, [upd])
    got = store.alloc_by_id(None, slab.ids[0])
    assert got.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
    # Siblings untouched (still pending via the shared proto).
    assert (store.alloc_by_id(None, slab.ids[1]).client_status
            == s.ALLOC_CLIENT_STATUS_PENDING)


def test_plan_result_full_commit_counts_slabs():
    store, job, nodes = _store_with_job()
    plan = s.Plan(eval_id="ev-1", job=job)
    plan.append_slab(_slab(job, [n.id for n in nodes]))
    assert not plan.is_no_op()
    assert plan.total_allocs() == 3

    result = s.PlanResult(alloc_slabs=list(plan.alloc_slabs))
    ok, expected, actual = result.full_commit(plan)
    assert ok and expected == 3 and actual == 3

    partial = s.PlanResult(
        alloc_slabs=[plan.alloc_slabs[0].filter_nodes({nodes[0].id})])
    ok, expected, actual = partial.full_commit(plan)
    assert not ok and expected == 3 and actual == 1


def test_plan_apply_partial_commit_filters_slab():
    """A slab node that fails the fit re-check is dropped; survivors
    commit (plan_apply.go:202 evaluatePlan semantics)."""
    from nomad_tpu.server.fsm import FSM
    from nomad_tpu.server.plan_apply import PlanApplier
    from nomad_tpu.server.plan_queue import PlanQueue
    from nomad_tpu.server.raft import RaftLog

    store, job, nodes = _store_with_job()
    # Fill node-0 to the brim so the slab's placement there fails.
    hog = _proto(job, ev_id="ev-0")
    hog.id = s.generate_uuid()
    hog.name = "hog"
    hog.node_id = nodes[0].id
    hog.resources = s.Resources(cpu=nodes[0].resources.cpu,
                                memory_mb=nodes[0].resources.memory_mb)
    store.upsert_allocs(5, [hog], owned=True)

    fsm = FSM(state=store)
    raft = RaftLog(fsm)
    applier = PlanApplier(PlanQueue(), raft)

    plan = s.Plan(eval_id="ev-1", job=job)
    plan.append_slab(_slab(job, [n.id for n in nodes]))
    snap = store.snapshot()
    result = applier.evaluate_plan(snap, plan)
    committed = {nid for sl in result.alloc_slabs for nid in sl.node_ids}
    assert nodes[0].id not in committed
    assert committed == {nodes[1].id, nodes[2].id}
    assert result.refresh_index > 0

    # Gang semantics: all-or-nothing.
    gang = s.Plan(eval_id="ev-2", job=job, all_at_once=True)
    gang.append_slab(_slab(job, [n.id for n in nodes], ev_id="ev-2"))
    gang_result = applier.evaluate_plan(snap, gang)
    assert not gang_result.alloc_slabs
    assert not gang_result.node_allocation

    # Applying the partial result lands exactly the committed subset.
    applier.apply_plan(plan, result, snap)
    placed = store.allocs_by_eval(None, "ev-1")
    assert sorted(a.node_id for a in placed) == sorted(committed)
    for a in placed:
        assert a.job is not None and a.create_time > 0


def test_slab_log_codec_roundtrip():
    from nomad_tpu.server.log_codec import decode_payload, encode_payload

    _, job, nodes = _store_with_job()
    slab = _slab(job, [n.id for n in nodes])
    blob = encode_payload({"job": job, "slabs": [slab], "allocs": []})
    out = decode_payload(blob)
    got = out["slabs"][0]
    assert isinstance(got, s.AllocSlab)
    assert got.ids == slab.ids
    assert got.node_ids == slab.node_ids
    assert got.proto.job_id == job.id


def test_persist_restore_materializes_slabs():
    store, job, nodes = _store_with_job()
    slab = _slab(job, [n.id for n in nodes])
    store.upsert_slabs(10, [slab])
    blob = store.persist()
    restored = StateStore.restore(blob)
    got = sorted(restored.allocs(None), key=lambda a: a.id)
    assert [a.id for a in got] == sorted(slab.ids)
    assert all(a.node_id for a in got)
    # Indexes rebuilt.
    assert len(restored.allocs_by_job(None, job.id, True)) == 3


def test_allocs_by_job_drains_only_that_jobs_slabs():
    """ISSUE 14: allocs_by_job materializes ONLY the requested job's
    pending slabs — an unrelated warm million-row slab stays deferred,
    so the mesh steady state's phase-1 reconciliation never pays an
    O(cluster) drain per fresh snapshot."""
    store, job_a, nodes = _store_with_job()
    job_b = mock.job()
    job_b.task_groups[0].count = 2
    store.upsert_job(5, job_b)
    job_b = store.job_by_id(None, job_b.id)

    slab_a = _slab(job_a, [n.id for n in nodes], ev_id="ev-a")
    slab_b = _slab(job_b, [nodes[0].id, nodes[1].id], ev_id="ev-b")
    store.upsert_slabs(10, [slab_a, slab_b])
    assert len(store._pending_slabs) == 2

    got = store.allocs_by_job(None, job_b.id, True)
    assert sorted(a.id for a in got) == sorted(slab_b.ids)
    # job_a's slab is still deferred; job_b's was drained.
    assert [sl is slab_a for sl in store._pending_slabs] == [True]
    assert job_b.id not in store._pending_by_job
    assert job_a.id in store._pending_by_job

    # A job with NO pending slabs doesn't disturb the deferred set.
    job_c = mock.job()
    store.upsert_job(11, job_c)
    assert store.allocs_by_job(None, job_c.id, True) == []
    assert [sl is slab_a for sl in store._pending_slabs] == [True]

    # The per-job drain filled the by-node cells for job_b only; a full
    # reader still sees everything via the global drain.
    assert sorted(a.id for a in store.allocs_by_job(None, job_a.id, True)) \
        == sorted(slab_a.ids)
    assert not store._pending_slabs
    by_node = {a.id for a in store.allocs_by_node(None, nodes[0].id)}
    assert slab_a.ids[0] in by_node and slab_b.ids[0] in by_node


def test_allocs_by_job_partial_drain_snapshot_independent():
    """Each snapshot drains its own pending copy: a per-job drain on one
    snapshot must not leak into the base store or a sibling."""
    store, job, nodes = _store_with_job()
    slab = _slab(job, [n.id for n in nodes])
    store.upsert_slabs(10, [slab])

    snap = store.snapshot()
    got = snap.allocs_by_job(None, job.id, True)
    assert sorted(a.id for a in got) == sorted(slab.ids)
    # The base store's deferred set is untouched by the snapshot's drain.
    assert len(store._pending_slabs) == 1
    assert sorted(a.id for a in store.allocs_by_job(None, job.id, True)) \
        == sorted(slab.ids)
