"""Load-harness + eval-broker admission-control tests (ISSUE 7).

The smoke scenario is the tier-1 gate for the whole control-plane
saturation plane: it drives the REAL server stack (workers, broker,
plan pipeline, heartbeats, event stream) with a fixed, seeded burst and
must complete in seconds, deterministically.
"""
import time

import pytest

from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.eval_broker import BrokerLimitError, EvalBroker
from nomad_tpu.structs import structs as s


def wait_until(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# harness smoke (the tier-1 loadgen gate)
# ---------------------------------------------------------------------------


class TestLoadgenSmoke:
    def test_smoke_scenario_end_to_end(self):
        from nomad_tpu.loadgen import LoadHarness
        from nomad_tpu.loadgen.scenario import get_scenario

        report = LoadHarness(get_scenario("smoke")).run()
        off, sus = report["offered"], report["sustained"]
        # Deterministic offered load: the seeded burst submits exactly
        # max_submissions jobs and every one completes.
        assert off["submitted"] == 30
        assert off["dropped_after_retries"] == 0
        assert sus["completed_total"] == 30
        assert sus["stragglers_after_drain"] == 0
        assert sus["evals_per_s"] > 0
        # The report's latency sections are populated and the harness
        # agrees with the server's own telemetry plane.
        s2r = report["latency_ms"]["submit_to_running"]
        assert s2r["count"] > 0 and s2r["p99"] >= s2r["p50"] > 0
        assert report["latency_ms"]["plan_apply"].get("count", 0) > 0
        broker = report["control_plane"]["broker"]
        assert broker["Pending"] == 0
        # Simulated clients really heartbeat, with jitter-dispersed TTLs.
        hb = report["heartbeat"]
        assert hb["renewals"] >= 20
        assert hb["distinct_ttls"] > 1
        # Event fan-out probe ran against the filtered subscribers.
        assert report["event_fanout"]["subscribers"] >= 8
        assert report["event_fanout"]["us_per_event"] > 0

    def test_overload_sheds_and_stays_bounded(self):
        """Scaled-down 10× overload against a bounded broker: admission
        rejects fire, the pending queue never outgrows the cap, and the
        run still terminates with no stragglers (accepted work drains,
        rejected work is dropped by the client after its retries)."""
        from dataclasses import replace

        from nomad_tpu.loadgen import LoadHarness
        from nomad_tpu.loadgen.scenario import get_scenario

        sc = replace(get_scenario("overload_10x"),
                     num_nodes=20, num_clients=8, arrival_rate=1500.0,
                     max_submissions=400, subscribers=8,
                     broker_max_pending=32, drain_s=30.0)
        report = LoadHarness(sc).run()
        off = report["offered"]
        broker = report["control_plane"]["broker"]
        assert off["admission_rejects_seen"] > 0
        assert broker["AdmissionRejects"] > 0
        assert broker["MaxPending"] == 32
        assert broker["Pending"] <= 32
        assert report["sustained"]["stragglers_after_drain"] == 0
        # Accounting closes: accepted = submitted tracked, and accepted
        # + dropped = attempts that got an answer.
        assert off["submitted"] + off["dropped_after_retries"] <= 400
        assert report["sustained"]["completed_total"] == off["submitted"]


# ---------------------------------------------------------------------------
# broker admission control units
# ---------------------------------------------------------------------------


def make_eval(job_id, eval_id=None, priority=50, trigger_index=0):
    return s.Evaluation(id=eval_id or s.generate_uuid(), job_id=job_id,
                        type=s.JOB_TYPE_SERVICE, priority=priority,
                        status=s.EVAL_STATUS_PENDING,
                        job_modify_index=trigger_index)


class TestBrokerAdmission:
    def test_coalesce_keeps_newest_sheds_older(self):
        b = EvalBroker(coalesce=True)
        b.set_enabled(True)
        try:
            b.enqueue(make_eval("j1", "e0", trigger_index=1))
            b.enqueue(make_eval("j1", "e1", trigger_index=2))  # deferred
            b.enqueue(make_eval("j1", "e2", trigger_index=3))  # coalesces
            st = b.extended_stats()
            assert st["CoalescedTotal"] == 1
            assert st["ShedTotal"] == 1
            assert st["ByState"]["deferred"] == 1
            shed = b.get_shed(timeout=0.1)
            assert [ev.id for ev in shed] == ["e1"]
            # Queued eval unaffected; the kept deferred one is e2.
            ev, token = b.dequeue([s.JOB_TYPE_SERVICE], 0.1)
            assert ev.id == "e0"
            b.ack("e0", token)
            ev, token = b.dequeue([s.JOB_TYPE_SERVICE], 0.5)
            assert ev.id == "e2"
        finally:
            b.set_enabled(False)

    def test_coalesce_refuses_when_keeper_would_miss_trigger(self):
        """A higher-priority deferred eval with an OLDER trigger index
        must not absorb a newer trigger (a node death, an unblock) —
        both stay queued."""
        b = EvalBroker(coalesce=True)
        b.set_enabled(True)
        try:
            b.enqueue(make_eval("j1", "e0", trigger_index=1))
            b.enqueue(make_eval("j1", "e1", priority=90, trigger_index=2))
            b.enqueue(make_eval("j1", "e2", priority=50, trigger_index=9))
            st = b.extended_stats()
            assert st["CoalescedTotal"] == 0
            assert st["ByState"]["deferred"] == 2
        finally:
            b.set_enabled(False)

    def test_admission_rejects_past_cap_with_retry_after(self):
        b = EvalBroker(max_pending=2, bypass_priority=90)
        b.set_enabled(True)
        try:
            b.enqueue(make_eval("j1"))
            b.enqueue(make_eval("j2"))
            with pytest.raises(BrokerLimitError) as exc:
                b.check_admission(50)
            assert exc.value.retry_after > 0
            assert exc.value.pending == 2
            # Priority at/above the bypass floor is always admitted.
            b.check_admission(90)
            # And below the cap admission is open again.
            ev, token = b.dequeue([s.JOB_TYPE_SERVICE], 0.1)
            b.ack(ev.id, token)
            b.check_admission(50)
            assert b.extended_stats()["AdmissionRejects"] == 1
        finally:
            b.set_enabled(False)

    def test_limit_error_wire_roundtrip(self):
        err = BrokerLimitError(1.25, 300, 256)
        rebuilt = BrokerLimitError.from_message(
            f"BrokerLimitError: {err}".split(": ", 1)[1])
        assert rebuilt.retry_after == pytest.approx(1.25)
        assert (rebuilt.pending, rebuilt.limit) == (300, 256)

    def test_delivery_attempts_histogram_in_stats(self):
        b = EvalBroker(nack_timeout=60.0)
        b.set_enabled(True)
        try:
            b.enqueue(make_eval("j1", "e0"))
            ev, token = b.dequeue([s.JOB_TYPE_SERVICE], 0.1)
            b.nack(ev.id, token)
            ev, token = b.dequeue([s.JOB_TYPE_SERVICE], 2.0)
            st = b.extended_stats()
            assert st["DeliveryAttempts"] == {"2": 1}
            assert st["ByState"]["unacked"] == 1
        finally:
            b.set_enabled(False)

    def test_server_job_register_429s_when_saturated(self):
        srv = Server(ServerConfig(num_schedulers=1, broker_max_pending=1,
                                  min_heartbeat_ttl=60))
        srv.start()
        try:
            assert wait_until(srv.is_leader, timeout=10.0)
            for w in srv.workers:
                w.set_pause(True)

            def job(n):
                jid = f"adm-{n}"
                return s.Job(
                    region="global", id=jid, name=jid,
                    type=s.JOB_TYPE_SERVICE, priority=50,
                    datacenters=["dc1"],
                    task_groups=[s.TaskGroup(
                        name="tg", count=1,
                        ephemeral_disk=s.EphemeralDisk(size_mb=10),
                        tasks=[s.Task(
                            name="t", driver="exec",
                            config={"command": "/bin/date"},
                            resources=s.Resources(cpu=10, memory_mb=10),
                            log_config=s.LogConfig())])])

            srv.job_register(job(0))
            with pytest.raises(BrokerLimitError):
                srv.job_register(job(1))
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# eval.e2e umbrella span (tracing satellite)
# ---------------------------------------------------------------------------


class TestEvalE2ESpan:
    def test_submit_to_ack_umbrella_span_recorded(self):
        from nomad_tpu.utils import tracing

        tracing.enable()
        srv = Server(ServerConfig(num_schedulers=1, min_heartbeat_ttl=60))
        srv.start()
        try:
            assert wait_until(srv.is_leader, timeout=10.0)
            srv.node_register(s.Node(
                id="e2e-node", datacenter="dc1", name="e2e-node",
                attributes={"kernel.name": "linux", "driver.exec": "1"},
                resources=s.Resources(cpu=4000, memory_mb=8192,
                                      disk_mb=100 * 1024, iops=100),
                reserved=s.Resources(), status=s.NODE_STATUS_READY))
            jid = "e2e-job"
            job = s.Job(
                region="global", id=jid, name=jid,
                type=s.JOB_TYPE_SERVICE, priority=50,
                datacenters=["dc1"],
                task_groups=[s.TaskGroup(
                    name="tg", count=1,
                    ephemeral_disk=s.EphemeralDisk(size_mb=10),
                    tasks=[s.Task(name="t", driver="exec",
                                  config={"command": "/bin/date"},
                                  resources=s.Resources(cpu=10,
                                                        memory_mb=10),
                                  log_config=s.LogConfig())])])
            _, eval_id = srv.job_register(job)
            assert wait_until(
                lambda: (ev := srv.state.eval_by_id(None, eval_id))
                is not None and ev.terminal_status())
            assert wait_until(lambda: any(
                sp["Name"] == "eval.e2e"
                for sp in tracing.trace_for_eval(eval_id)), timeout=10.0)
            e2e = [sp for sp in tracing.trace_for_eval(eval_id)
                   if sp["Name"] == "eval.e2e"]
            assert len(e2e) == 1
            assert e2e[0]["Attrs"]["outcome"] == "acked"
            assert e2e[0]["Attrs"]["submit"] == "job_register"
            # The umbrella COVERS the whole lifecycle: its window spans
            # the broker enqueue and the worker's scheduling.
            spans = tracing.trace_for_eval(eval_id)
            enq = [sp for sp in spans if sp["Name"] == "broker.enqueue"]
            assert enq and e2e[0]["Start"] <= enq[0]["Start"] \
                and e2e[0]["End"] >= enq[0]["End"]
        finally:
            srv.shutdown()
            tracing.disable()
