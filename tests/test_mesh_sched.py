"""Node-mesh production path tests (ISSUE 8 tentpole).

The fused sharded program (parallel/sharded.sharded_fused_pass, driven
by TPUBatchScheduler._dispatch_mesh) must be BIT-IDENTICAL to the
single-chip fused program — same placements, same per-alloc AllocMetric
scores — under a pinned tie-break seed (NOMAD_TPU_RNG_SEED), on the
8-device virtual CPU mesh conftest forces.  Exactness is by
construction (k_cand ≥ max count ⇒ every round's global top-k lies in
the gathered local top-k candidates), so these are equality tests, not
budget tests.

Plus the PR 5/6 composition on the mesh: single-dispatch/single-fetch
(one ``batch.fetch`` span), device-resident usage deltas landing on the
owning shard, the per-shard differential guard feeding the breaker with
the offending shard id, the staleness fence, non-divisible mesh sizes
padding the node axis up instead of silently falling back, and the
double-buffered schedule_stream driving the mesh dispatch/fetch split.
"""
import random

import jax
import numpy as np
import pytest

from nomad_tpu import fault, mock
from nomad_tpu.ops import batch_sched, resident
from nomad_tpu.ops.batch_sched import TPUBatchScheduler
from nomad_tpu.ops.breaker import KernelCircuitBreaker
from nomad_tpu.parallel import make_node_mesh
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import structs as s
from nomad_tpu.utils import tracing


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    return make_node_mesh(jax.devices()[:8])


def make_node(rng=None):
    node = mock.node()
    node.resources.networks = []
    node.reserved.networks = []
    if rng is not None:
        node.resources.cpu = rng.choice([2000, 4000, 8000])
        node.resources.memory_mb = rng.choice([4096, 8192, 16384])
    node.compute_class()
    return node


def make_job(count, rng=None, constrained=False):
    job = mock.job()
    job.task_groups[0].count = count
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.networks = []
            if rng is not None:
                t.resources.cpu = rng.choice([100, 250, 500])
                t.resources.memory_mb = rng.choice([64, 256, 512])
    if constrained:
        tg = job.task_groups[0]
        tg.constraints = list(tg.constraints) + [
            s.Constraint("${attr.kernel.name}", "linux", "="),
            s.Constraint("", "", s.CONSTRAINT_DISTINCT_HOSTS),
        ]
    return job


def reg_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def build_twin_problem(seed, n_nodes=24, n_jobs=4, max_count=4,
                       constrained=False):
    rng = random.Random(seed)
    nodes = [make_node(rng) for _ in range(n_nodes)]
    jobs = [make_job(rng.randint(1, max_count), rng,
                     constrained=constrained and i % 2 == 0)
            for i in range(n_jobs)]
    harnesses = []
    for _ in range(2):
        h = Harness()
        for node in nodes:
            h.state.upsert_node(h.next_index(), node.copy())
        for job in jobs:
            h.state.upsert_job(h.next_index(), job)
        harnesses.append(h)
    return harnesses[0], harnesses[1], jobs


def placements_with_scores(h, jobs):
    """(job, tg) → sorted [(node_id, sorted score items)]: the
    bit-identity basis — same kernel ⇒ same slots AND same per-node
    AllocMetric score entries."""
    out = {}
    for job in jobs:
        for a in h.state.allocs_by_job(None, job.id, True):
            if a.terminal_status():
                continue
            scores = tuple(sorted((a.metrics.scores or {}).items()))
            out.setdefault((job.id, a.task_group), []).append(
                (a.node_id, scores))
    return {k: sorted(v) for k, v in out.items()}


def run_batch(h, jobs, monkeypatch, mesh=None, seed=1234, breaker=None):
    monkeypatch.setenv("NOMAD_TPU_RNG_SEED", str(seed))
    kw = {}
    if mesh is not None:
        kw["mesh"] = mesh
    if breaker is not None:
        kw["breaker"] = breaker
    sched = TPUBatchScheduler(h.logger, h.snapshot(), h, **kw)
    return sched.schedule_batch([reg_eval(j) for j in jobs])


class TestMeshBitIdentity:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_mesh_vs_single_chip_bit_identical(self, mesh, seed,
                                               monkeypatch):
        h_mesh, h_single, jobs = build_twin_problem(seed)
        st_m = run_batch(h_mesh, jobs, monkeypatch, mesh=mesh, seed=seed)
        st_s = run_batch(h_single, jobs, monkeypatch, seed=seed)
        assert st_m.mesh_shards == 8 and st_m.fused == 1
        assert st_s.mesh_shards == 0
        pm = placements_with_scores(h_mesh, jobs)
        ps = placements_with_scores(h_single, jobs)
        assert pm == ps
        assert sum(len(v) for v in pm.values()) > 0

    def test_mesh_constrained_distinct_hosts_identical(self, mesh,
                                                       monkeypatch):
        h_mesh, h_single, jobs = build_twin_problem(
            11, n_nodes=20, n_jobs=6, constrained=True)
        run_batch(h_mesh, jobs, monkeypatch, mesh=mesh, seed=11)
        run_batch(h_single, jobs, monkeypatch, seed=11)
        assert (placements_with_scores(h_mesh, jobs)
                == placements_with_scores(h_single, jobs))

    def test_mesh_single_fetch_span(self, mesh, monkeypatch):
        """Single-dispatch/single-fetch contract on the mesh path:
        exactly one ``batch.fetch`` span per healthy batch."""
        h_mesh, _h, jobs = build_twin_problem(3)
        evals = [reg_eval(j) for j in jobs]
        monkeypatch.setenv("NOMAD_TPU_RNG_SEED", "3")
        tracing.enable()
        try:
            sched = TPUBatchScheduler(h_mesh.logger, h_mesh.snapshot(),
                                      h_mesh, mesh=mesh)
            stats = sched.schedule_batch(evals)
            fetches = [sp for sp in tracing.trace_for_eval(evals[0].id)
                       if sp["Name"] == "batch.fetch"]
        finally:
            tracing.disable()
        assert stats.mesh_shards == 8
        assert len(fetches) == 1

    def test_nonuniform_mesh_pads_node_axis_up(self, monkeypatch):
        """A mesh whose size does not divide the 128-row pad (3 devices)
        pads the node axis up to lcm(128, D) — MISSING-filled shards are
        infeasible by construction — instead of abandoning the mesh; the
        result stays bit-identical to single-chip."""
        mesh3 = make_node_mesh(jax.devices()[:3])
        h_mesh, h_single, jobs = build_twin_problem(5, n_nodes=18)
        passes = batch_sched.MESH_PASSES
        st_m = run_batch(h_mesh, jobs, monkeypatch, mesh=mesh3, seed=5)
        assert batch_sched.MESH_PASSES == passes + 1
        assert st_m.mesh_shards == 3
        run_batch(h_single, jobs, monkeypatch, seed=5)
        assert (placements_with_scores(h_mesh, jobs)
                == placements_with_scores(h_single, jobs))

    def test_mesh_network_batch_identical(self, mesh, monkeypatch):
        """Network asks (bandwidth / port accounting) on the mesh path:
        the per-node port/bandwidth state shards like the usage rows and
        placements stay bit-identical to single-chip."""
        nodes = []
        for _ in range(12):
            n = mock.node()          # keeps its mock networks
            n.compute_class()
            nodes.append(n)
        jobs = []
        for _ in range(3):
            j = mock.job()           # tasks keep network asks
            j.task_groups[0].count = 2
            jobs.append(j)
        hs = []
        for _ in range(2):
            h = Harness()
            for n in nodes:
                h.state.upsert_node(h.next_index(), n.copy())
            for j in jobs:
                h.state.upsert_job(h.next_index(), j)
            hs.append(h)
        st_m = run_batch(hs[0], jobs, monkeypatch, mesh=mesh, seed=42)
        run_batch(hs[1], jobs, monkeypatch, seed=42)
        assert st_m.device_ran and st_m.mesh_shards == 8
        pm = placements_with_scores(hs[0], jobs)
        assert pm == placements_with_scores(hs[1], jobs)
        assert sum(len(v) for v in pm.values()) == 6

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(8))
    def test_mesh_fuzz_bit_identical(self, mesh, seed, monkeypatch):
        """Slow fuzz sweep: randomized fleets/jobs (heterogeneous
        resources, mixed counts, constraint/distinct mixes) stay
        bit-identical — placements AND scores — between the mesh and
        single-chip fused programs under the pinned seed."""
        rng = random.Random(1000 + seed)
        h_mesh, h_single, jobs = build_twin_problem(
            2000 + seed,
            n_nodes=rng.randint(9, 60),
            n_jobs=rng.randint(2, 10),
            max_count=rng.randint(2, 12),
            constrained=bool(seed % 2))
        run_batch(h_mesh, jobs, monkeypatch, mesh=mesh, seed=seed)
        run_batch(h_single, jobs, monkeypatch, seed=seed)
        pm = placements_with_scores(h_mesh, jobs)
        ps = placements_with_scores(h_single, jobs)
        assert pm == ps


class TestMeshResident:
    """Sharded-resident composition, mirroring tests/test_resident.py:
    delta apply on the owning shard, per-shard guard, fence, breaker."""

    def _harness(self, n_nodes=12):
        h = Harness()
        for _ in range(n_nodes):
            h.state.upsert_node(h.next_index(), make_node())
        return h

    def _run(self, h, mesh, brk=None, state=None, job=None):
        if job is None:
            job = make_job(2)
            h.state.upsert_job(h.next_index(), job)
        kw = {"breaker": brk} if brk is not None else {}
        sched = TPUBatchScheduler(
            h.logger, state if state is not None else h.snapshot(),
            h, mesh=mesh, **kw)
        stats = sched.schedule_batch([reg_eval(job)])
        placed = len([a for a in h.state.allocs_by_job(None, job.id, True)
                      if not a.terminal_status()])
        return stats, placed

    def test_mesh_delta_path_with_guard(self, mesh, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "1")
        resident.reset_counters()
        h = self._harness()
        s1, p1 = self._run(h, mesh)
        assert s1.full_reencodes == 1 and not s1.resident_hits
        assert p1 == 2
        s2, p2 = self._run(h, mesh)
        assert s2.resident_hits == 1 and p2 == 2
        assert s2.mesh_shards == 8
        assert resident.GUARD_RUNS >= 1
        assert resident.GUARD_MISMATCHES == 0
        resident.reset_counters()

    def test_mesh_staleness_fence(self, mesh, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "0")
        resident.reset_counters()
        h = self._harness()
        self._run(h, mesh)
        fence_job = make_job(2)
        h.state.upsert_job(h.next_index(), fence_job)
        stale = h.snapshot()
        self._run(h, mesh)
        self._run(h, mesh)
        cached = resident._STATE.alloc_index
        s3, p3 = self._run(h, mesh, state=stale, job=fence_job)
        assert s3.staleness_fences == 1 and s3.full_reencodes == 1
        assert p3 == 2
        assert resident._STATE.alloc_index == cached, \
            "fence must not regress the mirror"
        resident.reset_counters()

    def test_mesh_guard_trip_attributes_shard(self, mesh, monkeypatch,
                                              caplog):
        """Injected mirror corruption: the per-shard differential guard
        catches it, names the offending shard id, feeds the breaker,
        and the batch still places from the fresh full encode."""
        import logging

        monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "1")
        resident.reset_counters()
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=3600.0)
        h = self._harness()
        self._run(h, mesh, brk=brk)
        self._run(h, mesh, brk=brk)
        with caplog.at_level(logging.ERROR, "nomad_tpu.ops.resident"):
            with fault.scenario({"seed": 5, "faults": [
                    {"point": "ops.resident_state", "action": "corrupt",
                     "times": 1}]}):
                s3, p3 = self._run(h, mesh, brk=brk)
        assert resident.GUARD_MISMATCHES == 1
        assert brk.state == "open"
        assert p3 == 2, "corrupted-mirror batch must still place"
        assert "mesh shards [" in caplog.text, \
            "guard mismatch must attribute the owning shard"
        resident.reset_counters()

    def test_mesh_schedule_stream_pipelined(self, mesh, monkeypatch):
        """The prepare/dispatch/complete split drives the mesh dispatch
        asynchronously: a double-buffered stream of batches places
        everything with resident delta hits after the cold batch — over
        the DONATED sharded mirror (default on), whose guard-at-every-
        hit bit-compare proves usage is never optimistic (batch k's
        placements land in the mirror only after k finalizes)."""
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "1")
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "1")
        resident.reset_counters()
        h = self._harness(n_nodes=16)
        jobs, batches = [], []
        for _ in range(4):
            job = make_job(2)
            h.state.upsert_job(h.next_index(), job)
            jobs.append(job)
            batches.append([reg_eval(job)])
        sched = TPUBatchScheduler(h.logger, h.snapshot(), h, mesh=mesh)
        stats = sched.schedule_stream(
            batches, state_source=lambda: h.snapshot())
        assert len(stats) == 4
        assert all(st.mesh_shards == 8 for st in stats)
        assert sum(st.resident_hits for st in stats) >= 3
        assert resident.GUARD_MISMATCHES == 0
        assert resident.DEV_INSTALLS == 1, (
            "the sharded mirror must install once and ride the stream "
            "in place")
        assert resident.DEV_APPLIES >= 2
        assert resident.DEV_GUARD_MISMATCHES == 0
        st_res = resident._STATE
        assert st_res is not None and st_res.used_dev is not None
        np.testing.assert_array_equal(
            np.asarray(st_res.used_dev).astype(np.int64), st_res.used)
        for job in jobs:
            live = [a for a in h.state.allocs_by_job(None, job.id, True)
                    if not a.terminal_status()]
            assert len(live) == 2
        resident.reset_counters()


class TestMeshDonatedMirror:
    """ISSUE 14: the donated per-shard usage mirror on the node mesh.

    The [n_pad, 4] usage matrix lives node-sharded on the mesh (one
    donated [n_local, 4] buffer per shard), is caught up in place by
    shard-routed donated scatter-adds, and is loaned into
    ``sharded_fused_pass`` as a donated arg returned aliased — so the
    replicated per-batch u_rows/u_vals upload disappears.  These pin
    (a) bit-identity of placements AND of the mirror vs the sparse
    delta-upload path after N donated applies, (b) the loan protocol
    under a dispatch exception (slot empties, next batch reinstalls),
    and (c) the NOMAD_TPU_RESIDENT_DEVICE=0 kill-switch."""

    def _harness(self, n_nodes=12):
        h = Harness()
        for i in range(n_nodes):
            node = make_node()
            node.id = f"mesh-dev-{i:02d}"
            node.name = node.id
            h.state.upsert_node(h.next_index(), node)
        return h

    def _stream(self, h, mesh, batches=5, brk=None, count=2, rng=None):
        placements = []
        for _ in range(batches):
            job = make_job(count if rng is None
                           else rng.randint(1, count), rng)
            h.state.upsert_job(h.next_index(), job)
            kw = {"breaker": brk} if brk is not None else {}
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                                      mesh=mesh, **kw)
            sched.schedule_batch([reg_eval(job)])
            placements.append(sorted(
                (a.node_id, tuple(sorted((a.metrics.scores or {}).items())))
                for a in h.state.allocs_by_job(None, job.id, True)
                if not a.terminal_status()))
        return placements

    def test_donated_applies_bit_identical_to_delta_path(self, mesh,
                                                         monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_RNG_SEED", "991")
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "1")

        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "1")
        resident.reset_counters()
        h_dev = self._harness()
        pl_dev = self._stream(h_dev, mesh)
        assert resident.DEV_INSTALLS == 1, (
            "the sharded mirror must install exactly once and then "
            "round-trip in place through the fused mesh program")
        assert resident.DEV_APPLIES >= 4
        st = resident._STATE
        assert st is not None and st.used_dev is not None
        # Physically sharded: every mesh device holds its slice.
        assert len(st.used_dev.sharding.device_set) == 8
        np.testing.assert_array_equal(
            np.asarray(st.used_dev).astype(np.int64), st.used)
        host_mirror = st.used.copy()

        resident.reset_counters()
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "0")
        h_dl = self._harness()
        pl_dl = self._stream(h_dl, mesh)
        assert resident.DEV_INSTALLS == 0 and resident.DEV_APPLIES == 0
        assert pl_dev == pl_dl
        np.testing.assert_array_equal(resident._STATE.used, host_mirror)
        resident.reset_counters()

    def test_loan_exception_empties_slot_and_reinstalls(self, mesh,
                                                        monkeypatch):
        """A dispatch exception between take and give consumes the
        donated loan: the slot must be EMPTY afterwards (never a dead
        handle) and the next batch reinstalls from host and places."""
        import nomad_tpu.parallel.sharded as shmod

        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "1")
        # Lenient breaker: the injected dispatch failure must feed it
        # WITHOUT opening it, so the next batch exercises the reinstall
        # path rather than the oracle route.
        brk = KernelCircuitBreaker(threshold=0.1, window=32,
                                   min_checks=16, cooldown=3600.0)
        resident.reset_counters()
        h = self._harness()
        self._stream(h, mesh, batches=2, brk=brk)
        assert resident.DEV_INSTALLS == 1

        orig = shmod.sharded_fused_pass

        def boom(*a, **k):
            raise RuntimeError("injected mesh dispatch failure")

        monkeypatch.setattr(shmod, "sharded_fused_pass", boom)
        job = make_job(2)
        h.state.upsert_job(h.next_index(), job)
        sched = TPUBatchScheduler(h.logger, h.snapshot(), h, mesh=mesh,
                                  breaker=brk)
        with pytest.raises(RuntimeError):
            sched.schedule_batch([reg_eval(job)])
        st = resident._STATE
        assert st is not None and st.used_dev is None, (
            "the consumed loan must leave the slot empty")

        monkeypatch.setattr(shmod, "sharded_fused_pass", orig)
        pl = self._stream(h, mesh, batches=1, brk=brk)
        assert len(pl[0]) == 2
        assert resident.DEV_INSTALLS == 2, (
            "the batch after a consumed loan must reinstall from host")
        st = resident._STATE
        assert st is not None and st.used_dev is not None
        np.testing.assert_array_equal(
            np.asarray(st.used_dev).astype(np.int64), st.used)
        resident.reset_counters()

    def test_kill_switch_keeps_delta_upload_path(self, mesh, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "0")
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "1")
        resident.reset_counters()
        h = self._harness()
        pl = self._stream(h, mesh, batches=3)
        assert all(len(p) == 2 for p in pl)
        assert resident.DEV_INSTALLS == 0 and resident.DEV_APPLIES == 0
        assert resident.HITS >= 2, "delta path must still serve hits"
        assert resident.GUARD_MISMATCHES == 0
        resident.reset_counters()

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(6))
    def test_donated_mirror_fuzz_bit_identical(self, mesh, seed,
                                               monkeypatch):
        """Slow multi-seed fuzz: randomized fleets + job streams place
        bit-identically — placements AND AllocMetric scores — between
        the donated sharded mirror and the delta-upload path, with the
        guard at every hit proving the mirror never drifts."""
        monkeypatch.setenv("NOMAD_TPU_RNG_SEED", str(3000 + seed))
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "1")
        rng = random.Random(7000 + seed)
        n_nodes = rng.randint(10, 40)
        n_batches = rng.randint(3, 7)
        max_count = rng.randint(2, 6)

        out = []
        for flag in ("1", "0"):
            monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", flag)
            resident.reset_counters()
            h = self._harness(n_nodes=n_nodes)
            out.append(self._stream(
                h, mesh, batches=n_batches, count=max_count,
                rng=random.Random(5000 + seed)))
            assert resident.GUARD_MISMATCHES == 0
            assert resident.DEV_GUARD_MISMATCHES == 0
        assert out[0] == out[1]
        resident.reset_counters()
