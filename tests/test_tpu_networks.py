"""Differential tests: network/port accounting and distinct_property on the
device path vs the CPU oracle (VERDICT r1 'What's missing' #5; reference
scheduler/rank.go:190-238, nomad/structs/network.go:245,
scheduler/propertyset.go:11)."""
import random

import pytest

from nomad_tpu import mock
from nomad_tpu.ops import batch_sched  # noqa: F401 — registers 'tpu-batch'
from nomad_tpu.ops import encode
from nomad_tpu.scheduler import Harness, new_scheduler, new_service_scheduler
from nomad_tpu.structs import structs as s
from nomad_tpu.structs.network import (
    MAX_DYNAMIC_PORT,
    MIN_DYNAMIC_PORT,
    NetworkIndex,
)

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def reg_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def make_nodes(h, n, mbits=1000):
    nodes = []
    for i in range(n):
        node = mock.node()
        node.resources.networks = [s.NetworkResource(
            device="eth0", cidr=f"192.168.0.{100 + i}/32", mbits=mbits)]
        node.reserved.networks = []
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)
        nodes.append(node)
    return nodes


def port_job(count=1, reserved=(), dynamic=1, mbits=10):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = count
    for t in tg.tasks:
        t.resources.networks = [s.NetworkResource(
            mbits=mbits,
            reserved_ports=[s.Port(f"r{p}", p) for p in reserved],
            dynamic_ports=[s.Port(f"d{i}") for i in range(dynamic)],
        )]
    return job


def existing_alloc(h, job_src, node, reserved=(), mbits=10):
    """A live alloc occupying ports/bandwidth on ``node``."""
    alloc = mock.alloc()
    alloc.node_id = node.id
    alloc.job = job_src
    alloc.job_id = job_src.id
    net = s.NetworkResource(
        device="eth0", ip=node.resources.networks[0].cidr.split("/")[0],
        mbits=mbits,
        reserved_ports=[s.Port(f"r{p}", p) for p in reserved])
    alloc.task_resources = {"web": s.Resources(
        cpu=100, memory_mb=64, networks=[net])}
    alloc.resources = s.Resources(cpu=100, memory_mb=64, networks=[net])
    h.state.upsert_allocs(h.next_index(), [alloc])
    return alloc


def run_batch(h, jobs):
    evals = [reg_eval(j) for j in jobs]
    sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
    sched.schedule_batch(evals)
    return evals


class TestDevicePortAccounting:
    def test_reserved_port_conflict_avoided(self):
        """A node whose reserved port is taken is infeasible on the device
        path, exactly as the oracle's assign_network failure."""
        h = Harness()
        nodes = make_nodes(h, 4)
        blocker = mock.job()
        h.state.upsert_job(h.next_index(), blocker)
        existing_alloc(h, blocker, nodes[0], reserved=(8080,))

        job = port_job(count=3, reserved=(8080,))
        h.state.upsert_job(h.next_index(), job)
        run_batch(h, [job])

        allocs = h.state.allocs_by_job(None, job.id, True)
        placed_nodes = {a.node_id for a in allocs}
        assert len(allocs) == 3
        assert nodes[0].id not in placed_nodes, \
            "placed on a node with a conflicting reserved port"

    def test_within_batch_reserved_conflict(self):
        """Two jobs asking the same reserved port in ONE batch must land on
        different nodes — the device commits port bits between specs."""
        h = Harness()
        make_nodes(h, 2)
        jobs = []
        for _ in range(2):
            j = port_job(count=1, reserved=(9000,))
            h.state.upsert_job(h.next_index(), j)
            jobs.append(j)
        run_batch(h, jobs)

        n1 = {a.node_id for a in h.state.allocs_by_job(None, jobs[0].id, True)}
        n2 = {a.node_id for a in h.state.allocs_by_job(None, jobs[1].id, True)}
        assert len(n1) == 1 and len(n2) == 1
        assert n1 != n2, "same reserved port double-booked on one node"

    def test_dynamic_ports_assigned_and_valid(self):
        h = Harness()
        make_nodes(h, 4)
        job = port_job(count=4, dynamic=2)
        h.state.upsert_job(h.next_index(), job)
        run_batch(h, [job])

        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 4
        seen_by_node = {}
        for a in allocs:
            for tr in a.task_resources.values():
                assert tr.networks, "no network offer on placed alloc"
                offer = tr.networks[0]
                assert offer.ip, "offer missing IP"
                vals = [p.value for p in offer.dynamic_ports]
                assert len(vals) == 2
                for v in vals:
                    assert MIN_DYNAMIC_PORT <= v < MAX_DYNAMIC_PORT
                node_ports = seen_by_node.setdefault(a.node_id, set())
                assert not (node_ports & set(vals)), "dynamic port collision"
                node_ports.update(vals)

    def test_bandwidth_exhaustion(self):
        """Nodes without remaining bandwidth are skipped (network.go:60
        Overcommitted / rank.go bandwidth-exceeded)."""
        h = Harness()
        nodes = make_nodes(h, 3, mbits=100)
        blocker = mock.job()
        h.state.upsert_job(h.next_index(), blocker)
        existing_alloc(h, blocker, nodes[0], mbits=80)

        job = port_job(count=2, dynamic=0, mbits=50)
        h.state.upsert_job(h.next_index(), job)
        run_batch(h, [job])

        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 2
        assert nodes[0].id not in {a.node_id for a in allocs}

    def test_oracle_and_device_agree_on_port_feasibility(self):
        """Same cluster + same port-constrained job: oracle and tpu-batch
        place on the same feasible node set (tie-breaks aside)."""

        def run(kind):
            h = Harness()
            nodes = make_nodes(h, 6)
            blocker = mock.job()
            h.state.upsert_job(h.next_index(), blocker)
            # Ports 7000 taken on nodes 0-2 → only 3-5 feasible.
            for i in range(3):
                existing_alloc(h, blocker, nodes[i], reserved=(7000,))
            job = port_job(count=3, reserved=(7000,), dynamic=1)
            h.state.upsert_job(h.next_index(), job)
            ev = reg_eval(job)
            if kind == "tpu-batch":
                sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
                sched.process(ev)
            else:
                h.process(new_service_scheduler, ev)
            placed = {a.node_id for a in
                      h.state.allocs_by_job(None, job.id, True)}
            free = {n.id for n in nodes[3:]}
            return placed, free

        for kind in ("oracle", "tpu-batch"):
            placed, free = run(kind)
            assert placed == free, f"{kind}: placed {placed} != free {free}"

    def test_no_port_allocs_overcommit_check(self):
        """Plan-applied network offers replay cleanly into a NetworkIndex
        (no hidden double-bookings)."""
        h = Harness()
        make_nodes(h, 3)
        jobs = []
        for i in range(3):
            j = port_job(count=2, reserved=(6000 + i,), dynamic=1)
            h.state.upsert_job(h.next_index(), j)
            jobs.append(j)
        run_batch(h, jobs)

        by_node = {}
        for j in jobs:
            for a in h.state.allocs_by_job(None, j.id, True):
                by_node.setdefault(a.node_id, []).append(a)
        for node_id, allocs in by_node.items():
            node = h.state.node_by_id(None, node_id)
            idx = NetworkIndex()
            idx.set_node(node)
            collide = idx.add_allocs(allocs)
            assert not collide, f"port collision on node {node_id}"
            assert not idx.overcommitted()


class TestDeviceDistinctProperty:
    def rack_nodes(self, h, racks):
        nodes = []
        for i, rack in enumerate(racks):
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            node.meta["rack"] = rack
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)
            nodes.append(node)
        return nodes

    def dp_job(self, count):
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = count
        for t in tg.tasks:
            t.resources.networks = []
        tg.constraints = list(tg.constraints) + [s.Constraint(
            "${meta.rack}", "", s.CONSTRAINT_DISTINCT_PROPERTY)]
        return job

    def test_one_alloc_per_property_value(self):
        h = Harness()
        nodes = self.rack_nodes(h, ["r1", "r1", "r2", "r2", "r3", "r3"])
        job = self.dp_job(3)
        h.state.upsert_job(h.next_index(), job)
        run_batch(h, [job])

        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 3
        racks = [h.state.node_by_id(None, a.node_id).meta["rack"]
                 for a in allocs]
        assert len(set(racks)) == 3, f"rack reuse: {racks}"

    def test_count_exceeding_values_partially_places(self):
        h = Harness()
        self.rack_nodes(h, ["r1", "r2", "r3"])
        job = self.dp_job(5)
        h.state.upsert_job(h.next_index(), job)
        evals = run_batch(h, [job])

        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 3
        # The eval records the failure, like the oracle
        # (generic_sched.go:218 blocked-eval creation on failed placements).
        updated = [e for e in h.evals if e.id == evals[0].id]
        assert updated and updated[-1].failed_tg_allocs

    def test_existing_value_excluded(self):
        h = Harness()
        nodes = self.rack_nodes(h, ["r1", "r2", "r3"])
        job = self.dp_job(2)
        h.state.upsert_job(h.next_index(), job)
        existing = existing_alloc_no_net(h, job, nodes[0])
        run_batch(h, [job])

        allocs = [a for a in h.state.allocs_by_job(None, job.id, True)
                  if a.id != existing.id]
        racks = {h.state.node_by_id(None, a.node_id).meta["rack"]
                 for a in allocs}
        assert "r1" not in racks, "reused the rack of an existing alloc"

    def test_matches_oracle(self):
        def run(kind, seed):
            h = Harness()
            rng = random.Random(seed)
            racks = [f"r{rng.randrange(4)}" for _ in range(12)]
            self.rack_nodes(h, racks)
            job = self.dp_job(4)
            h.state.upsert_job(h.next_index(), job)
            ev = reg_eval(job)
            if kind == "tpu-batch":
                sched = new_scheduler("tpu-batch", h.logger, h.snapshot(), h)
                sched.process(ev)
            else:
                h.process(new_service_scheduler, ev)
            allocs = h.state.allocs_by_job(None, job.id, True)
            racks_used = sorted(h.state.node_by_id(None, a.node_id).meta["rack"]
                                for a in allocs)
            return len(allocs), racks_used

        for seed in (1, 2, 3):
            n_oracle, racks_oracle = run("oracle", seed)
            n_batch, racks_batch = run("tpu-batch", seed)
            assert n_oracle == n_batch
            assert len(set(racks_oracle)) == len(racks_oracle)
            assert len(set(racks_batch)) == len(racks_batch)


class TestOracleGating:
    def test_multiple_distinct_property_routes_to_oracle(self):
        h = Harness()
        for i in range(4):
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            node.meta["rack"] = f"r{i}"
            node.meta["zone"] = f"z{i % 2}"
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 2
        for t in tg.tasks:
            t.resources.networks = []
        tg.constraints = list(tg.constraints) + [
            s.Constraint("${meta.rack}", "", s.CONSTRAINT_DISTINCT_PROPERTY),
            s.Constraint("${meta.zone}", "", s.CONSTRAINT_DISTINCT_PROPERTY)]
        h.state.upsert_job(h.next_index(), job)
        run_batch(h, [job])

        # Placed correctly (by the oracle fallback): both racks AND zones
        # distinct.
        allocs = h.state.allocs_by_job(None, job.id, True)
        assert len(allocs) == 2
        racks = {h.state.node_by_id(None, a.node_id).meta["rack"]
                 for a in allocs}
        zones = {h.state.node_by_id(None, a.node_id).meta["zone"]
                 for a in allocs}
        assert len(racks) == 2 and len(zones) == 2

    def test_spec_gate_reasons(self):
        job = port_job(count=1, reserved=(5000, 5000))
        spec = encode.build_spec(job, job.task_groups[0], False)
        assert "reserved ports" in spec.needs_oracle

        job2 = mock.job()
        job2.constraints = [s.Constraint(
            "${meta.rack}", "", s.CONSTRAINT_DISTINCT_PROPERTY)]
        tg2 = job2.task_groups[0].copy()
        tg2.name = "second"
        job2.task_groups.append(tg2)
        spec2 = encode.build_spec(job2, job2.task_groups[0], False)
        assert "job-level" in spec2.needs_oracle


def existing_alloc_no_net(h, job_src, node):
    alloc = mock.alloc()
    alloc.node_id = node.id
    alloc.job = job_src
    alloc.job_id = job_src.id
    alloc.task_group = job_src.task_groups[0].name
    alloc.task_resources = {"web": s.Resources(cpu=100, memory_mb=64)}
    alloc.resources = s.Resources(cpu=100, memory_mb=64)
    h.state.upsert_allocs(h.next_index(), [alloc])
    return alloc
