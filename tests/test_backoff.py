"""utils/backoff.py + the retry loops that consume it
(RemoteServerRPC leader re-resolution, scheduler retry_max storm cap).
"""
import random

import pytest

from nomad_tpu.server.rpc import NoLeaderError, RemoteServerRPC, RPCError
from nomad_tpu.structs import structs as s
from nomad_tpu.utils.backoff import Backoff, retry, wait_until


class TestBackoff:
    def test_exponential_schedule_without_jitter(self):
        b = Backoff(base=0.1, factor=2.0, max_delay=1.0, jitter=0.0)
        assert [round(b.next_delay(), 6) for _ in range(6)] == [
            0.1, 0.2, 0.4, 0.8, 1.0, 1.0]
        b.reset()
        assert b.next_delay() == pytest.approx(0.1)

    def test_jitter_bounded_and_seeded(self):
        b1 = Backoff(base=0.1, max_delay=2.0, rng=random.Random(7))
        b2 = Backoff(base=0.1, max_delay=2.0, rng=random.Random(7))
        d1 = [b1.next_delay() for _ in range(8)]
        d2 = [b2.next_delay() for _ in range(8)]
        assert d1 == d2  # seeded ⇒ reproducible
        for i, d in enumerate(d1):
            assert 0.0 <= d <= min(2.0, 0.1 * 2 ** i) + 1e-9
        # full jitter actually jitters
        assert len({round(d, 9) for d in d1}) > 1

    def test_base_must_be_positive(self):
        with pytest.raises(ValueError):
            Backoff(base=0.0)


class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []
        sleeps = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise IOError("transient")
            return "ok"

        assert retry(flaky, retries=5, retry_on=(IOError,),
                     sleep=sleeps.append,
                     backoff=Backoff(base=0.01, jitter=0.0)) == "ok"
        assert len(calls) == 3
        assert sleeps == [0.01, 0.02]

    def test_budget_exhausted_reraises(self):
        observed = []

        def always_fails():
            raise IOError("down")

        with pytest.raises(IOError):
            retry(always_fails, retries=2, retry_on=(IOError,),
                  sleep=lambda d: None,
                  on_retry=lambda e, n: observed.append(n))
        assert observed == [0, 1]

    def test_unlisted_exception_escapes_immediately(self):
        def typo():
            raise KeyError("not retryable")

        with pytest.raises(KeyError):
            retry(typo, retries=5, retry_on=(IOError,),
                  sleep=lambda d: None)


class TestWaitUntil:
    def test_true_immediately_no_sleep(self):
        sleeps = []
        assert wait_until(lambda: True, 1.0, sleep=sleeps.append)
        assert sleeps == []

    def test_ramps_interval_until_true(self):
        state = {"n": 0}
        sleeps = []

        def pred():
            state["n"] += 1
            return state["n"] > 4

        clock = {"t": 0.0}

        def fake_sleep(d):
            sleeps.append(d)
            clock["t"] += d

        assert wait_until(pred, 10.0, initial=0.001, max_interval=0.01,
                          factor=2.0, sleep=fake_sleep,
                          clock=lambda: clock["t"])
        assert sleeps == [0.001, 0.002, 0.004, 0.008]

    def test_timeout_returns_false(self):
        clock = {"t": 0.0}

        def fake_sleep(d):
            clock["t"] += d

        assert not wait_until(lambda: False, 0.05, sleep=fake_sleep,
                              clock=lambda: clock["t"])


class _FakePool:
    """Scripted ConnPool: addr → list of outcomes (exception or value),
    consumed per call."""

    def __init__(self, script):
        self.script = {k: list(v) for k, v in script.items()}
        self.calls = []

    def call(self, addr, method, body, **kw):
        self.calls.append(addr)
        outcomes = self.script.get(addr)
        if not outcomes:
            raise OSError(f"connection refused: {addr}")
        out = outcomes.pop(0)
        if isinstance(out, Exception):
            raise out
        return out


class TestRemoteRPCRetries:
    def test_no_leader_reply_promotes_hinted_leader(self):
        """A follower's NoLeaderError names the leader; the next attempt
        must go straight there instead of re-walking the stale list."""
        pool = _FakePool({
            "10.0.0.1:4647": [NoLeaderError("10.0.0.3:4647")],
            "10.0.0.3:4647": [{"Index": 7, "HeartbeatTTL": 10.0}],
        })
        rpc = RemoteServerRPC(["10.0.0.1:4647", "10.0.0.2:4647"],
                              pool=pool, sleep=lambda d: None)
        index, ttl = rpc.node_update_status("n1", "ready")
        assert (index, ttl) == (7, 10.0)
        assert pool.calls == ["10.0.0.1:4647", "10.0.0.3:4647"]
        assert rpc.servers[0] == "10.0.0.3:4647"  # leader stays preferred

    def test_bounded_rounds_with_backoff_then_raise(self):
        pool = _FakePool({})  # everything refuses
        sleeps = []
        rpc = RemoteServerRPC(["a:1", "b:2"], pool=pool, max_rounds=3,
                              sleep=sleeps.append)
        with pytest.raises(RPCError, match="no servers reachable"):
            rpc._call("Node.Register", {})
        assert len(pool.calls) == 6          # 2 servers × 3 rounds
        assert len(sleeps) == 2              # backoff between rounds
        assert all(d > 0 for d in sleeps)

    def test_prose_no_leader_reply_never_pollutes_server_list(self):
        """During elections servers reply NoLeaderError('no cluster
        leader') / 'not the leader' / '' — prose, not an address.  It
        must be treated as a plain failure (demote + retry), never
        inserted into the server list as a dial target."""
        pool = _FakePool({
            "a:1": [NoLeaderError("no cluster leader"),
                    {"Index": 2, "HeartbeatTTL": 5.0}],
            "b:2": [NoLeaderError("")],
        })
        rpc = RemoteServerRPC(["a:1", "b:2"], pool=pool,
                              sleep=lambda d: None)
        index, _ = rpc.node_update_status("n1", "ready")
        assert index == 2
        assert sorted(rpc.servers) == ["a:1", "b:2"]  # nothing bogus

    def test_failed_server_demoted(self):
        pool = _FakePool({
            "a:1": [OSError("refused"), {"Index": 1, "HeartbeatTTL": 5.0}],
            "b:2": [{"Index": 1, "HeartbeatTTL": 5.0}],
        })
        rpc = RemoteServerRPC(["a:1", "b:2"], pool=pool,
                              sleep=lambda d: None)
        rpc.node_update_status("n1", "ready")
        assert pool.calls == ["a:1", "b:2"]
        assert rpc.servers == ["b:2", "a:1"]  # a demoted behind b


class TestRetryMaxStormCap:
    def test_progress_resets_but_total_is_capped(self):
        """A plan that makes token progress every attempt (staleness
        rejections under churn) must not resubmit forever."""
        from nomad_tpu.scheduler.util import SetStatusError, retry_max

        calls = []
        with pytest.raises(SetStatusError, match="maximum attempts"):
            # progress "made" every time ⇒ attempts always reset; only
            # the total cap (3 × 8 = 24) stops the storm
            retry_max(3, lambda: (calls.append(1), False)[1],
                      reset=lambda: True)
        assert len(calls) == 24

        calls.clear()
        with pytest.raises(SetStatusError):
            retry_max(3, lambda: (calls.append(1), False)[1],
                      reset=lambda: True, max_total=5)
        assert len(calls) == 5

    def test_done_short_circuits(self):
        from nomad_tpu.scheduler.util import retry_max

        calls = []
        retry_max(3, lambda: (calls.append(1), True)[1])
        assert len(calls) == 1
