"""Priority-tier preemption: oracle eviction-set selection, the batched
device twin, scheduler integration, and the plan-apply staleness fence
(scheduler/preempt.py, ops/preempt.py, server/plan_apply.py)."""
import logging

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.generic import GenericScheduler
from nomad_tpu.scheduler import preempt as oracle
from nomad_tpu.structs import structs as s


def make_node(cpu=4000, mem=8192):
    n = mock.node()
    n.resources = s.Resources(cpu=cpu, memory_mb=mem,
                              disk_mb=100 * 1024, iops=150)
    n.reserved = None
    n.resources.networks = []
    return n


def make_alloc(node, prio, cpu, mem, job=None):
    if job is None:
        job = mock.job()
        job.priority = prio
        job.task_groups[0].count = 0
    a = s.Allocation(
        id=s.generate_uuid(), job_id=job.id, job=job, node_id=node.id,
        task_group="web", name=f"{job.name}.web[0]",
        resources=s.Resources(cpu=cpu, memory_mb=mem))
    return a


def assert_inclusion_minimal(node, allocs, ask, victims):
    """No member of the eviction set can be spared: removing any single
    victim from the set breaks the fit."""
    from nomad_tpu.structs.funcs import remove_allocs

    survivors = remove_allocs(allocs, victims)
    probe = s.Allocation(id="_probe", resources=ask)
    from nomad_tpu.structs.funcs import allocs_fit

    fit, _, _ = allocs_fit(node, survivors + [probe])
    assert fit, "eviction set does not make the ask fit"
    for spared in victims:
        kept = [v for v in victims if v.id != spared.id]
        survivors2 = remove_allocs(allocs, kept)
        fit2, _, _ = allocs_fit(node, survivors2 + [probe])
        assert not fit2, f"victim {spared.id} was unnecessary"


# -- oracle ----------------------------------------------------------------


def test_oracle_minimality_trims_unneeded_victims():
    # Greedy prefix picks the big-memory prio-10 alloc first, but the ask
    # only needs cpu — the reverse trim must drop it.
    node = make_node(cpu=1000, mem=8192)
    mem_hog = make_alloc(node, 10, cpu=0, mem=6000)
    cpu_hog = make_alloc(node, 20, cpu=900, mem=100)
    allocs = [mem_hog, cpu_hog]
    ask = s.Resources(cpu=800, memory_mb=100)
    victims = oracle.find_eviction_set(node, allocs, ask, priority=50)
    assert victims is not None
    assert [v.id for v in victims] == [cpu_hog.id]
    assert_inclusion_minimal(node, allocs, ask, victims)


def test_oracle_orders_priority_then_largest_first():
    node = make_node(cpu=4000, mem=8192)
    small_low = make_alloc(node, 10, cpu=500, mem=500)
    big_low = make_alloc(node, 10, cpu=1500, mem=1500)
    mid = make_alloc(node, 30, cpu=2000, mem=2000)
    allocs = [small_low, mid, big_low]
    # Needs 1500 cpu freed: one eviction of the LARGEST prio-10 alloc
    # suffices; evicting prio-30 work or both prio-10 allocs would not
    # be minimal-cheapest.
    ask = s.Resources(cpu=1500, memory_mb=1500)
    victims = oracle.find_eviction_set(node, allocs, ask, priority=50)
    assert [v.id for v in victims] == [big_low.id]
    assert_inclusion_minimal(node, allocs, ask, victims)


def test_oracle_never_evicts_equal_or_higher_priority():
    node = make_node(cpu=1000, mem=1000)
    peer = make_alloc(node, 50, cpu=900, mem=900)
    ask = s.Resources(cpu=500, memory_mb=500)
    # Same tier: nothing to evict.
    assert oracle.find_eviction_set(node, [peer], ask, priority=50) is None
    higher = make_alloc(node, 80, cpu=900, mem=900)
    assert oracle.find_eviction_set(node, [higher], ask, priority=50) is None
    # Strictly lower: allowed.
    victims = oracle.find_eviction_set(node, [peer], ask, priority=51)
    assert [v.id for v in victims] == [peer.id]


def test_oracle_fit_without_eviction_returns_empty():
    node = make_node()
    low = make_alloc(node, 10, cpu=100, mem=100)
    ask = s.Resources(cpu=500, memory_mb=500)
    assert oracle.find_eviction_set(node, [low], ask, priority=50) == []


def test_oracle_infeasible_when_all_candidates_insufficient():
    node = make_node(cpu=1000, mem=1000)
    low = make_alloc(node, 10, cpu=300, mem=300)
    high = make_alloc(node, 90, cpu=600, mem=600)
    # Evicting the only candidate (prio 10) frees 300: 100 free + 300
    # < 500 cpu — and the prio-90 alloc is untouchable.
    ask = s.Resources(cpu=500, memory_mb=500)
    assert oracle.find_eviction_set(node, [low, high], ask,
                                    priority=50) is None


# -- scheduler integration (the evict/priority flags are consumed) ---------


def fill_cluster(h, n_nodes=3, per_node=3, filler_prio=20,
                 alloc_cpu=1200, alloc_mem=2500):
    filler = mock.job()
    filler.priority = filler_prio
    filler.task_groups[0].count = 0
    h.state.upsert_job(h.next_index(), filler)
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.resources.networks = []
        n.reserved.networks = []
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
        for k in range(per_node):
            a = s.Allocation(
                id=s.generate_uuid(), job_id=filler.id, job=filler,
                node_id=n.id, task_group="web", name=f"f.web[{k}]",
                resources=s.Resources(cpu=alloc_cpu, memory_mb=alloc_mem))
            h.state.upsert_allocs(h.next_index(), [a])
    return filler, nodes


def high_prio_job(count=2, prio=70, cpu=1000, mem=2000):
    job = mock.job()
    job.priority = prio
    job.task_groups[0].count = count
    for t in job.task_groups[0].tasks:
        t.resources = s.Resources(cpu=cpu, memory_mb=mem)
    return job


def register_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def test_oracle_scheduler_preempts_when_enabled():
    h = Harness()
    filler, _ = fill_cluster(h)
    job = high_prio_job()
    h.state.upsert_job(h.next_index(), job)
    sched = GenericScheduler(h.logger, h.snapshot(), h, batch=False,
                             preemption_enabled=True)
    sched.process(register_eval(job))

    plan = h.plans[0]
    placed = [a for l in plan.node_allocation.values() for a in l]
    evicted = [a for l in plan.node_preemptions.values() for a in l]
    assert len(placed) == 2
    assert evicted and all(a.desired_status == s.ALLOC_DESIRED_STATUS_EVICT
                           for a in evicted)
    assert all(a.desired_description == s.ALLOC_PREEMPTED for a in evicted)
    # The no-eviction-of-equal-or-higher-priority invariant, end to end.
    for a in evicted:
        victim_job = h.state.job_by_id(None, a.job_id)
        assert victim_job.priority < job.priority
    # Evicted jobs get a blocked follow-up eval so they reschedule.
    pe = [e for e in h.create_evals
          if e.triggered_by == s.EVAL_TRIGGER_PREEMPTION]
    assert len(pe) == 1
    assert pe[0].job_id == filler.id
    assert pe[0].status == s.EVAL_STATUS_BLOCKED


def test_oracle_scheduler_without_preemption_blocks():
    h = Harness()
    fill_cluster(h)
    job = high_prio_job()
    h.state.upsert_job(h.next_index(), job)
    sched = GenericScheduler(h.logger, h.snapshot(), h, batch=False,
                             preemption_enabled=False)
    sched.process(register_eval(job))
    assert h.plans == []
    blocked = [e for e in h.create_evals
               if e.status == s.EVAL_STATUS_BLOCKED]
    assert blocked, "disabled preemption must leave a blocked eval"


def test_batch_scheduler_preempt_pass():
    from nomad_tpu.ops.batch_sched import TPUBatchScheduler

    h = Harness()
    filler, _ = fill_cluster(h, n_nodes=4)
    job = high_prio_job(count=3)
    h.state.upsert_job(h.next_index(), job)
    sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                              preemption_enabled=True)
    stats = sched.schedule_batch([register_eval(job)])

    assert stats.preempt_placed == 3
    assert stats.preempt_checked == 3
    assert stats.preempt_agree == stats.preempt_checked
    plan = h.plans[0]
    evicted = [a for l in plan.node_preemptions.values() for a in l]
    assert stats.preempt_evicted == len(evicted) > 0
    assert len(h.state.allocs_by_job(None, job.id, True)) == 3
    pe = [e for e in h.create_evals
          if e.triggered_by == s.EVAL_TRIGGER_PREEMPTION]
    assert len(pe) == 1 and pe[0].job_id == filler.id


def test_batch_preempt_evicts_slab_backed_allocs():
    """Steady-state clusters hold SLAB-backed allocs (the TPU placement
    path); victims must be materialized rows with real ids, not shared
    slab protos, or the plan applier's staleness fence rejects every
    preemption commit."""
    from nomad_tpu.ops.batch_sched import TPUBatchScheduler

    h = Harness()
    for i in range(3):
        n = mock.node()
        n.resources.networks = []
        n.reserved.networks = []
        h.state.upsert_node(h.next_index(), n)
    # Fill via the batch scheduler itself so state holds AllocSlabs.
    filler = mock.job()
    filler.priority = 20
    filler.task_groups[0].count = 9
    for t in filler.task_groups[0].tasks:
        t.resources = s.Resources(cpu=1200, memory_mb=2500)
    h.state.upsert_job(h.next_index(), filler)
    TPUBatchScheduler(h.logger, h.snapshot(), h).schedule_batch(
        [register_eval(filler)])
    # NO state reads between fill and preempt: a by-id/by-job read would
    # materialize the slab rows and hide the shared-proto hazard this
    # test exists to pin.

    job = high_prio_job(count=2)
    h.state.upsert_job(h.next_index(), job)
    sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                              preemption_enabled=True)
    stats = sched.schedule_batch([register_eval(job)])

    assert stats.preempt_placed == 2
    assert stats.preempt_agree == stats.preempt_checked == 2
    plan = h.plans[-1]
    evicted = [a for l in plan.node_preemptions.values() for a in l]
    assert evicted and all(a.id for a in evicted)
    # The evictions landed on the REAL state rows.
    evicted_state = [a for a in h.state.allocs_by_job(None, filler.id, True)
                     if a.desired_status == s.ALLOC_DESIRED_STATUS_EVICT]
    assert {a.id for a in evicted_state} == {a.id for a in evicted}
    assert len(h.state.allocs_by_job(None, job.id, True)) == 2


def test_batch_scheduler_preempt_disabled_is_inert():
    from nomad_tpu.ops.batch_sched import TPUBatchScheduler

    h = Harness()
    fill_cluster(h, n_nodes=2)
    job = high_prio_job(count=1)
    h.state.upsert_job(h.next_index(), job)
    sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                              preemption_enabled=False)
    stats = sched.schedule_batch([register_eval(job)])
    assert stats.preempt_placed == 0
    assert not h.state.allocs_by_job(None, job.id, True)


# -- kernel/oracle agreement ------------------------------------------------


def test_selfcheck_small_cluster():
    from nomad_tpu.ops.preempt import selfcheck

    assert selfcheck(n_nodes=16, n_specs=8, seed=3, log=lambda *a: None)


def test_kernel_invariant_no_high_priority_eviction():
    from nomad_tpu.ops.preempt import (
        encode_alloc_tensors, eviction_sets, random_cluster)
    import jax.numpy as jnp

    nodes, allocs_by_node, asks, priorities = random_cluster(24, 12, seed=7)
    prio_np, sizes, sorted_allocs = encode_alloc_tensors(
        [n.id for n in nodes], allocs_by_node, oracle.alloc_priority)
    free = np.zeros((len(nodes), 4), dtype=np.int32)
    used = np.zeros((len(nodes), 4), dtype=np.int32)
    denom = np.ones((len(nodes), 2), dtype=np.float32)
    for i, n in enumerate(nodes):
        cap = np.array([n.resources.cpu, n.resources.memory_mb,
                        n.resources.disk_mb, n.resources.iops])
        u = np.array([n.reserved.cpu, n.reserved.memory_mb,
                      n.reserved.disk_mb, n.reserved.iops])
        for a in allocs_by_node[n.id]:
            u = u + np.array(oracle.alloc_size(a))
        free[i], used[i] = cap - u, u
        denom[i] = (cap[0] - n.reserved.cpu, cap[1] - n.reserved.memory_mb)
    ask_arr = np.array([[r.cpu, r.memory_mb, r.disk_mb, r.iops]
                        for r in asks], dtype=np.int32)
    jp = np.array(priorities, dtype=np.int32)
    mask, feasible, n_evict, _ = (np.asarray(x) for x in eviction_sets(
        jnp.asarray(free), jnp.asarray(used), jnp.asarray(denom),
        jnp.asarray(prio_np), jnp.asarray(sizes),
        jnp.asarray(ask_arr), jnp.asarray(jp)))
    # Masked allocs always have strictly lower priority than the spec.
    for u in range(len(asks)):
        sel = mask[u]                                   # [N, A]
        assert not np.any(sel & (prio_np >= jp[u])), u
        assert np.array_equal(sel.sum(axis=1), n_evict[u])
        assert not np.any(n_evict[u][~feasible[u]]), "mask outside feasible"


@pytest.mark.slow
def test_fuzz_kernel_matches_oracle():
    from nomad_tpu.ops.preempt import agreement_check, random_cluster

    for seed in (1, 2, 3, 4):
        nodes, allocs_by_node, asks, priorities = random_cluster(
            48, 24, seed=seed)
        checked, n_mismatch, mismatches = agreement_check(
            nodes, allocs_by_node, asks, priorities)
        assert checked == 48 * 24
        assert n_mismatch == 0, mismatches


# -- plan apply: optimistic concurrency over preempted allocs ---------------


def make_applier():
    from nomad_tpu.server import (
        BlockedEvals, EvalBroker, FSM, InmemLog, PlanApplier, PlanQueue)

    fsm = FSM(logger=logging.getLogger("test-preempt"))
    raft = InmemLog(fsm)
    broker = EvalBroker()
    broker.set_enabled(True)
    blocked = BlockedEvals(broker)
    blocked.set_enabled(True)
    pq = PlanQueue()
    pq.set_enabled(True)
    return PlanApplier(pq, raft, blocked_evals=blocked), raft, blocked


def seed_victim(raft):
    from nomad_tpu.server import MessageType

    node = mock.node()
    node.resources.networks = []
    node.reserved.networks = []
    raft.apply(MessageType.NODE_REGISTER, {"node": node})
    filler = mock.job()
    filler.priority = 20
    filler.task_groups[0].count = 0
    raft.apply(MessageType.JOB_REGISTER, {"job": filler})
    victim = s.Allocation(
        id=s.generate_uuid(), job_id=filler.id, node_id=node.id,
        task_group="web", name="f.web[0]",
        resources=s.Resources(cpu=3000, memory_mb=6000))
    raft.apply(MessageType.ALLOC_UPDATE, {"allocs": [victim],
                                          "job": filler})
    return node, filler, victim


def preempt_plan(snap, node, victim, hi_job):
    plan = s.Plan(eval_id=s.generate_uuid(), priority=hi_job.priority,
                  job=hi_job)
    plan.append_preempted_alloc(snap.alloc_by_id(None, victim.id))
    placed = s.Allocation(
        id=s.generate_uuid(), job_id=hi_job.id, node_id=node.id,
        task_group="web", name="hi.web[0]",
        resources=s.Resources(cpu=2000, memory_mb=4000))
    plan.append_alloc(placed)
    return plan, placed


def test_plan_apply_commits_evict_and_place_atomically():
    applier, raft, blocked = make_applier()
    node, filler, victim = seed_victim(raft)
    hi = mock.job()
    hi.priority = 80
    snap = raft.fsm.state.snapshot()
    plan, placed = preempt_plan(snap, node, victim, hi)

    result = applier.evaluate_plan(snap, plan)
    assert result.node_preemptions
    assert result.full_commit(plan)[0]
    applier.apply_plan(plan, result, snap)

    state = raft.fsm.state
    assert (state.alloc_by_id(None, victim.id).desired_status
            == s.ALLOC_DESIRED_STATUS_EVICT)
    assert state.alloc_by_id(None, placed.id) is not None
    evs = [e for e in state.evals(None)
           if e.triggered_by == s.EVAL_TRIGGER_PREEMPTION]
    assert len(evs) == 1
    assert evs[0].job_id == filler.id
    assert evs[0].status == s.EVAL_STATUS_BLOCKED
    assert blocked.stats()["total_blocked"] == 1


def test_plan_apply_rejects_stale_preempted_alloc():
    from nomad_tpu.server import MessageType

    applier, raft, _ = make_applier()
    node, filler, victim = seed_victim(raft)
    hi = mock.job()
    hi.priority = 80
    snap = raft.fsm.state.snapshot()
    plan, _ = preempt_plan(snap, node, victim, hi)

    # Concurrent state change to the victim AFTER the scheduler's
    # snapshot: the client reports it running, bumping modify_index.
    upd = s._fast_copy(victim)
    upd.client_status = s.ALLOC_CLIENT_STATUS_RUNNING
    raft.apply(MessageType.ALLOC_CLIENT_UPDATE, {"allocs": [upd]})

    fresh_snap = raft.fsm.state.snapshot()
    result = applier.evaluate_plan(fresh_snap, plan)
    assert not result.node_preemptions
    assert not result.node_allocation
    assert result.refresh_index > 0, "rejection must force a state refresh"
    # The victim is untouched.
    assert (raft.fsm.state.alloc_by_id(None, victim.id).desired_status
            == s.ALLOC_DESIRED_STATUS_RUN)


def test_plan_apply_rejects_vanished_preempted_alloc():
    applier, raft, _ = make_applier()
    node, filler, victim = seed_victim(raft)
    hi = mock.job()
    hi.priority = 80
    snap = raft.fsm.state.snapshot()
    plan, _ = preempt_plan(snap, node, victim, hi)
    plan.node_preemptions[node.id][0].id = "no-such-alloc"
    result = applier.evaluate_plan(snap, plan)
    assert not result.node_preemptions and not result.node_allocation


def test_touched_node_ids_lazy_view():
    """ISSUE 14: the preempt gate's node-id view is lazy — len and
    iteration map touched usage rows to node ids without materializing
    a per-batch dict (1M entries at a warm 1M-alloc cluster)."""
    from nomad_tpu.ops.batch_sched import _TouchedNodeIds

    node_ids = [f"n{i}" for i in range(8)]
    view = _TouchedNodeIds(node_ids, [1, 5, 2])
    assert len(view) == 3
    assert sorted(view) == ["n1", "n2", "n5"]
    assert bool(view)
    empty = _TouchedNodeIds(node_ids, set())
    assert len(empty) == 0 and not list(empty)
