"""Host-attribution plane tests (ISSUE 19): the continuous profiler's
subsystem classifier, the lockcheck contention ledger, the GIL-pressure
probe, the flight recorder, and the trace fan-out."""

import json
import threading
import time

import pytest

from nomad_tpu.utils import blackbox, contprof, lockcheck, tracing
from nomad_tpu.utils.blackbox import FlightRecorder
from nomad_tpu.utils.contprof import classify_frames

pytestmark = pytest.mark.profiling

NT = "/home/x/nomad_tpu"  # any prefix works; rules match on suffixes
PY = "/usr/lib/python3.11"


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# classifier units
# ---------------------------------------------------------------------------


class TestClassifier:
    @pytest.mark.parametrize("frames,expected", [
        # leaf-first stacks; leaf-most mapped frame wins
        ([(f"{NT}/scheduler/generic_scheduler.py", "process")],
         "plan.evaluate"),
        ([(f"{NT}/codec/gen.py", "pack_Job")], "codec.encode"),
        ([(f"{NT}/codec/gen.py", "unpack_Job")], "codec.decode"),
        ([(f"{NT}/codec/native.py", "sniff_frame")], "codec.decode"),
        ([(f"{NT}/server/raft.py", "apply")], "raft.apply"),
        ([(f"{NT}/server/fsm.py", "_apply_plan")], "raft.apply"),
        ([(f"{NT}/server/log_codec.py", "append")], "raft.apply"),
        ([(f"{NT}/server/plan_apply.py", "_evaluate_plan")],
         "plan.evaluate"),
        ([(f"{NT}/server/plan_apply.py", "_apply_plan")], "plan.apply"),
        ([(f"{NT}/server/plan_queue.py", "dequeue")], "plan.apply"),
        ([(f"{NT}/server/follower_sched.py", "_forward")], "plan.apply"),
        ([(f"{NT}/server/eval_broker.py", "dequeue")], "broker"),
        ([(f"{NT}/server/blocked_evals.py", "unblock")], "broker"),
        ([(f"{NT}/server/event_broker.py", "publish")], "broker"),
        # heartbeat expiry work is broker machinery...
        ([(f"{NT}/server/heartbeat.py", "_invalidate")], "broker"),
        ([(f"{NT}/tenancy/drf.py", "pick")], "broker"),
        ([(f"{NT}/server/worker.py", "_snapshot_state")],
         "worker.snapshot"),
        ([(f"{NT}/server/worker.py", "invoke_scheduler")],
         "plan.evaluate"),
        ([(f"{NT}/ops/batch_sched.py", "_fetch_results")], "ops.fetch"),
        ([(f"{NT}/ops/batch_sched.py", "_dispatch_batch")],
         "ops.dispatch"),
        ([(f"{NT}/ops/batch_sched.py", "phase1")], "plan.evaluate"),
        ([(f"{NT}/ops/kernels.py", "score_nodes")], "ops.dispatch"),
        ([(f"{NT}/ops/decode.py", "expand_results")], "codec.decode"),
        ([(f"{NT}/ops/encode.py", "encode_static")], "ops.dispatch"),
        ([(f"{NT}/server/rpc.py", "_serve_conn")], "http"),
        ([(f"{NT}/agent/http.py", "metrics_request")], "http"),
        ([(f"{NT}/api/client.py", "get")], "http"),
        ([(f"{NT}/server/federation.py", "poll")], "federation"),
        ([(f"{NT}/loadgen/federation.py", "_drive")], "federation"),
        ([(f"{NT}/loadgen/harness.py", "_submit_loop")], "loadgen"),
    ])
    def test_known_stacks(self, frames, expected):
        assert classify_frames(frames) == expected

    def test_idle_leaves(self):
        for leaf in [(f"{PY}/threading.py", "wait"),
                     (f"{PY}/threading.py", "_wait_for_tstate_lock"),
                     (f"{PY}/selectors.py", "select"),
                     (f"{PY}/socket.py", "accept"),
                     (f"{NT}/utils/lockcheck.py", "_checked_sleep"),
                     # ...but its poll loop's bare time.sleep leaves
                     # _sweep as the leaf: that's the pacing sleep.
                     (f"{NT}/server/heartbeat.py", "_sweep"),
                     (f"{NT}/utils/contprof.py", "_gil_loop")]:
            # Even with hot nomad frames below it, a blocked leaf is idle.
            stack = [leaf, (f"{NT}/server/raft.py", "apply")]
            assert classify_frames(stack) == "idle", leaf

    def test_transparent_layers_walk_to_owner(self):
        # utils/structs/state frames are plumbing: attribution walks
        # past them to the subsystem that called in.
        stack = [
            (f"{NT}/structs/structs.py", "to_wire"),
            (f"{NT}/utils/telemetry.py", "add_sample"),
            (f"{NT}/state/state_store.py", "upsert_allocs"),
            (f"{NT}/server/fsm.py", "_apply_plan"),
        ]
        assert classify_frames(stack) == "raft.apply"

    def test_foreign_stack_is_other(self):
        assert classify_frames(
            [("/site-packages/numpy/core.py", "dot")]) == "other"
        assert classify_frames([]) == "other"

    def test_leafmost_match_wins_over_caller(self):
        # codec work invoked from raft is codec time, not raft time.
        stack = [(f"{NT}/codec/gen.py", "pack_LogEntry"),
                 (f"{NT}/server/raft.py", "append")]
        assert classify_frames(stack) == "codec.encode"

    def test_synthetic_sample_set_coverage(self):
        """The >=80%-of-non-idle coverage contract on a synthetic but
        representative sample population: one stack per hot subsystem,
        a couple of idle waiters, and ONE unattributable stack."""
        population = (
            [[(f"{NT}/scheduler/rank.py", "score")]] * 30
            + [[(f"{NT}/server/raft.py", "apply")]] * 20
            + [[(f"{NT}/codec/gen.py", "unpack_Job")]] * 15
            + [[(f"{NT}/server/eval_broker.py", "dequeue")]] * 10
            + [[(f"{NT}/server/plan_apply.py", "_apply_plan")]] * 10
            + [[(f"{NT}/agent/http.py", "handle")]] * 5
            + [[(f"{PY}/threading.py", "wait")]] * 40  # idle
            + [[("/site-packages/weird.py", "f")]] * 5  # unattributable
        )
        counts = {}
        for stack in population:
            sub = classify_frames(stack)
            counts[sub] = counts.get(sub, 0) + 1
        cov = contprof.ContinuousProfiler._coverage(counts)
        assert cov >= 0.80, counts
        # And the helper agrees with a hand computation.
        non_idle = sum(counts.values()) - counts["idle"]
        assert cov == round(1.0 - counts["other"] / non_idle, 4)


# ---------------------------------------------------------------------------
# live sampler
# ---------------------------------------------------------------------------


class TestSampler:
    def test_disarmed_surface(self):
        assert not contprof.enabled()
        assert contprof.window(30) == {"Enabled": False}
        assert contprof.shares() == {}
        assert contprof.host_attribution() is None
        contprof.reset()  # no-op, must not raise

    def test_samples_busy_nomad_thread(self):
        from nomad_tpu.server.plan_queue import PlanQueue

        q = PlanQueue()
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                for _ in range(200):
                    q.depth()  # leaf frame in server/plan_queue.py

        t = threading.Thread(target=busy, daemon=True)
        t.start()
        p = contprof.enable(hz=100, gil_ms=2.0)
        try:
            assert contprof.enabled()
            assert wait_until(
                lambda: p.window(30)["Counts"].get("plan.apply", 0) > 0,
                timeout=8.0)
            w = p.window(30)
            assert w["Enabled"] and w["ThreadSamples"] > 0
            assert abs(sum(w["Shares"].values()) - 1.0) < 0.05
            # The pytest main thread is blocked in wait_until's sleep →
            # some samples must be landing somewhere, and shares/counts
            # agree on the total.
            assert sum(w["Counts"].values()) == w["ThreadSamples"]
            ha = contprof.host_attribution()
            assert ha["enabled"] and ha["thread_samples"] > 0
            assert 0.0 <= ha["non_idle_coverage"] <= 1.0
        finally:
            stop.set()
            t.join(timeout=2.0)
            contprof.disable()
        assert not contprof.enabled()

    def test_reset_zeroes_leg_accounting(self):
        p = contprof.enable(hz=100, gil_ms=0.0)
        try:
            assert wait_until(
                lambda: p.host_attribution()["thread_samples"] > 1000,
                timeout=15.0)
            before = p.host_attribution()["thread_samples"]
            contprof.reset()
            after = p.host_attribution()["thread_samples"]
            # A tick may land between reset and read; the cumulative
            # counter restarting (not an absolute zero) is the contract.
            assert after < before / 4, (before, after)
        finally:
            contprof.disable()

    def test_gil_probe_under_cpu_spin(self):
        stop = threading.Event()

        def spin():
            x = 0
            while not stop.is_set():
                x += 1
            return x

        t = threading.Thread(target=spin, daemon=True)
        t.start()
        p = contprof.enable(hz=10, gil_ms=2.0)
        try:
            assert wait_until(
                lambda: p.gil_pressure_ms()["count"] > 20, timeout=8.0)
            g = p.gil_pressure_ms()
            assert g["count"] > 20
            assert g["p99"] >= g["p50"] >= 0.0
            assert g["max"] >= g["p99"]
        finally:
            stop.set()
            t.join(timeout=2.0)
            contprof.disable()


# ---------------------------------------------------------------------------
# contention ledger
# ---------------------------------------------------------------------------


class TestContentionLedger:
    def test_wait_histogram_records_blocked_acquire(self):
        lockcheck.arm()
        try:
            lockcheck.reset_waits()
            lk = lockcheck.make_tracked("test.contended")
            release = threading.Event()
            held = threading.Event()

            def holder():
                with lk:
                    held.set()
                    release.wait(2.0)

            t = threading.Thread(target=holder, daemon=True)
            t.start()
            assert held.wait(2.0)
            t0 = time.perf_counter()

            def waiter():
                with lk:
                    pass

            w = threading.Thread(target=waiter, daemon=True)
            w.start()
            time.sleep(0.05)  # let the waiter block ~50ms
            release.set()
            w.join(2.0)
            t.join(2.0)
            elapsed_ms = (time.perf_counter() - t0) * 1000.0

            stats = lockcheck.wait_stats()
            names = {st["name"]: st for st in stats}
            assert "test.contended" in names, stats
            st = names["test.contended"]
            # holder + waiter both acquired; the waiter's blocked time
            # dominates the max.
            assert st["count"] >= 2
            assert st["wait_s_max"] * 1000.0 >= 30.0
            assert st["wait_s_max"] * 1000.0 <= elapsed_ms + 1.0
            assert st["p99_ms"] >= st["p50_ms"] >= 0.0

            lockcheck.reset_waits()
            assert all(s["name"] != "test.contended"
                       for s in lockcheck.wait_stats())
            # The live TrackedLock keeps feeding the SAME aggregate
            # after an in-place reset.
            with lk:
                pass
            assert any(s["name"] == "test.contended"
                       for s in lockcheck.wait_stats())
        finally:
            lockcheck.disarm()
            lockcheck.reset_waits()

    def test_disarmed_acquire_records_nothing(self):
        lockcheck.arm()
        lk = lockcheck.make_tracked("test.disarmed")
        lockcheck.disarm()
        lockcheck.reset_waits()
        with lk:  # delegates, but the ledger is disarmed
            pass
        assert all(s["name"] != "test.disarmed"
                   for s in lockcheck.wait_stats())

    def test_merge_metrics_injects_histograms_and_gauges(self):
        lockcheck.arm()
        p = contprof.enable(hz=50, gil_ms=2.0)
        try:
            lockcheck.reset_waits()
            lk = lockcheck.make_tracked("test.merge")
            for _ in range(5):
                with lk:
                    pass
            assert wait_until(
                lambda: p.window(30)["ThreadSamples"] > 0, timeout=8.0)
            latest = {}
            contprof.merge_metrics(latest)
            key = "nomad.lock.test.merge.wait_seconds"
            assert key in latest["Samples"]
            summ = latest["Samples"][key]
            for field in ("count", "sum", "min", "max", "mean",
                          "p50", "p95", "p99"):
                assert field in summ
            assert latest["SampleTotals"][key][0] == summ["count"] == 5
            gauges = latest["Gauges"]
            assert "nomad.runtime.gil_delay_p99_ms" in gauges
            assert any(k.startswith("nomad.cpu.") for k in gauges)
        finally:
            contprof.disable()
            lockcheck.disarm()
            lockcheck.reset_waits()

    def test_merge_metrics_disarmed_is_noop(self):
        latest = {}
        contprof.merge_metrics(latest)
        assert latest == {}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

BUNDLE_KEYS = {"Reason", "Detail", "Wall", "UnixTime", "Pid", "Knobs",
               "Spans", "Events", "Profile", "Locks", "Threads",
               "Servers"}


class TestFlightRecorder:
    def test_capture_writes_valid_bundle(self, tmp_path):
        fr = FlightRecorder(directory=str(tmp_path), min_interval_s=30.0)
        path = fr.capture("breaker.open", {"Agreement": 0.5})
        assert path is not None
        with open(path, encoding="utf-8") as fh:
            bundle = json.load(fh)
        assert BUNDLE_KEYS <= set(bundle)
        assert bundle["Reason"] == "breaker.open"
        assert bundle["Detail"] == {"Agreement": 0.5}
        assert bundle["Profile"] == {"Enabled": False}
        assert isinstance(bundle["Threads"], str) and bundle["Threads"]
        assert fr.captured == [path]

    def test_rate_limit_dedupes_same_reason(self, tmp_path):
        fr = FlightRecorder(directory=str(tmp_path), min_interval_s=30.0)
        assert fr.capture("breaker.open", {}) is not None
        # Same reason inside the min interval: suppressed.
        assert fr.capture("breaker.open", {}) is None
        # force bypasses the limiter (operator path).
        assert fr.capture("breaker.open", {}, force=True) is not None
        assert len(fr.captured) == 2

    def test_global_floor_and_bundle_cap(self, tmp_path):
        fr = FlightRecorder(directory=str(tmp_path), min_interval_s=0.0,
                            max_bundles=2)
        assert fr.capture("a", {}) is not None
        # Different reason, but inside the ~1s global floor.
        assert fr.capture("b", {}) is None
        fr._last_any -= 2.0  # age past the floor
        assert fr.capture("b", {}) is not None
        fr._last_any -= 2.0
        # Lifetime cap reached (2 auto bundles).
        assert fr.capture("c", {}) is None
        # ...but forced captures are exempt from the cap.
        assert fr.capture("c", {}, force=True) is not None

    def test_note_trigger_disarmed_is_free(self, tmp_path):
        assert not blackbox.enabled()
        blackbox.note_trigger("breaker.open", {})  # no-op, must not raise
        assert blackbox.bundles() == []

    def test_note_trigger_captures_async(self, tmp_path):
        blackbox.enable(directory=str(tmp_path), min_interval_s=30.0)
        try:
            blackbox.note_trigger("auditor.violation", {"kind": "t"})
            assert wait_until(lambda: len(blackbox.bundles()) == 1,
                              timeout=8.0)
            # Second trigger for the same reason: rate-limited away.
            blackbox.note_trigger("auditor.violation", {"kind": "t"})
            time.sleep(0.3)
            assert len(blackbox.bundles()) == 1
            with open(blackbox.bundles()[0], encoding="utf-8") as fh:
                bundle = json.load(fh)
            assert BUNDLE_KEYS <= set(bundle)
        finally:
            blackbox.disable()

    def test_bundle_includes_registered_server_state(self, tmp_path):
        class FakeSink:
            def latest(self):
                return {"Gauges": {"nomad.x": 1}}

        class FakeMetrics:
            sink = FakeSink()

        class FakeServer:
            metrics = FakeMetrics()

            class config:
                node_name = "unit-1"

            def stats(self):
                return {"leader": True}

            def broker_stats(self):
                return {"Pending": 0}

        srv = FakeServer()
        blackbox.register_server(srv)
        try:
            bundle = blackbox.assemble_bundle("unit", {})
            assert [sv["Name"] for sv in bundle["Servers"]] == ["unit-1"]
            assert bundle["Servers"][0]["Stats"] == {"leader": True}
            assert bundle["Servers"][0]["Metrics"] == {
                "Gauges": {"nomad.x": 1}}
        finally:
            blackbox.unregister_server(srv)
        assert blackbox.assemble_bundle("unit", {})["Servers"] == []


# ---------------------------------------------------------------------------
# trace fan-out (satellite: /v1/trace/eval/<id> leader → followers)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestTraceFanout:
    """Marked chaos for the conftest tracing fixture (arms + clears the
    process-wide tracer around each test)."""

    def test_local_trace_short_circuits(self):
        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0))
        try:
            tr = tracing.TRACER
            with tr.span("plan.evaluate", eval_id="ev-local"):
                pass
            spans, source = srv.trace_for_eval_fanout("ev-local")
            assert spans and source == srv.config.rpc_advertise
        finally:
            srv.shutdown()

    def test_fans_out_to_peer_and_skips_dark(self, monkeypatch):
        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0))
        try:
            me = srv.config.rpc_advertise
            peer_spans = [{"name": "plan.evaluate", "eval_id": "ev-f"}]
            calls = []

            class FakePool:
                def call(self, addr, method, body, timeout=None):
                    calls.append((addr, method))
                    if addr == "10.0.0.8:4647":  # dark follower
                        raise OSError("connection refused")
                    assert method == "Status.TraceEval"
                    assert body == {"EvalID": "ev-f"}
                    return {"Spans": peer_spans}

                def close(self):
                    pass

            monkeypatch.setattr(srv, "pool", FakePool())
            monkeypatch.setattr(
                srv, "peer_addresses",
                lambda: [me, "10.0.0.8:4647", "10.0.0.9:4647"])
            spans, source = srv.trace_for_eval_fanout("ev-f")
            assert spans == peer_spans
            assert source == "10.0.0.9:4647"
            # Own address skipped, dark follower tried then skipped.
            assert [a for a, _ in calls] == ["10.0.0.8:4647",
                                             "10.0.0.9:4647"]
            # Nobody has it → empty, not an exception.
            monkeypatch.setattr(
                srv, "peer_addresses", lambda: [me, "10.0.0.8:4647"])
            assert srv.trace_for_eval_fanout("ev-f") == ([], "")
        finally:
            srv.shutdown()
