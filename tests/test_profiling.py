"""Debug/profiling surface: the pprof-equivalent endpoints under
/debug/pprof (reference: command/agent/http.go:173-178 mounts
net/http/pprof behind enableDebug) plus the profiling helpers."""

import json
import urllib.request

import pytest

import conftest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.utils import profiling


def _get(addr, path):
    with urllib.request.urlopen(addr + path, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture(scope="module")
def debug_agent(tmp_path_factory):
    cfg = conftest.dev_test_config()
    cfg.enable_debug = True
    tmp = tmp_path_factory.mktemp("dbg")
    cfg.client.alloc_dir = str(tmp / "allocs")
    cfg.client.state_dir = str(tmp / "state")
    a = Agent(cfg)
    a.start()
    yield a
    a.shutdown()


class TestPprofEndpoints:
    def test_cpu_profile(self, debug_agent):
        status, body = _get(debug_agent.http.address,
                            "/debug/pprof/profile?seconds=0.1")
        assert status == 200
        assert "Profile" in body
        assert "function calls" in body["Profile"]

    def test_heap(self, debug_agent):
        # First call arms the tracer, second returns data.
        _get(debug_agent.http.address, "/debug/pprof/heap")
        status, body = _get(debug_agent.http.address,
                            "/debug/pprof/heap?top=5")
        assert status == 200
        assert body.get("top") is not None
        assert body["current_bytes"] > 0

    def test_threads(self, debug_agent):
        status, body = _get(debug_agent.http.address,
                            "/debug/pprof/threads")
        assert status == 200
        # The HTTP serving thread itself must appear.
        assert "thread" in body["Stacks"]
        assert "http" in body["Stacks"]

    def test_gated_when_disabled(self, tmp_path):
        cfg = conftest.dev_test_config()
        cfg.enable_debug = False
        cfg.client.alloc_dir = str(tmp_path / "allocs")
        cfg.client.state_dir = str(tmp_path / "state")
        a = Agent(cfg)
        a.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as excinfo:
                _get(a.http.address, "/debug/pprof/threads")
            assert excinfo.value.code == 404
        finally:
            a.shutdown()


class TestDeviceTracer:
    def test_capture_writes_trace_dir(self, tmp_path):
        import os

        tracer = profiling.DeviceTracer(base_dir=str(tmp_path))
        import jax
        import jax.numpy as jnp

        tracer_dir = tracer.start()
        jnp.sum(jnp.arange(1024)).block_until_ready()
        info = tracer.stop()
        assert info["dir"] == tracer_dir
        # jax writes plugins/profile/... under the trace dir.
        found = [p for p, _dirs, files in os.walk(tracer_dir) if files]
        assert found, "trace produced no files"

    def test_single_active_trace(self, tmp_path):
        tracer = profiling.DeviceTracer(base_dir=str(tmp_path))
        tracer.start()
        with pytest.raises(RuntimeError):
            tracer.start()
        tracer.stop()
        with pytest.raises(RuntimeError):
            tracer.stop()
