"""Docker Engine API driver against a fake daemon on a unix socket
(reference: client/driver/docker_test.go runs against a real daemon; the
fake keeps the API contract testable in this environment)."""

import http.server
import json
import os
import socketserver
import struct
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.driver.docker_api import (
    DockerAPI,
    DockerAPIDriver,
    _demux,
)
from nomad_tpu.client.driver.driver import DriverContext, ExecContext
from nomad_tpu.client.driver.env import TaskEnv
from nomad_tpu.structs import structs as s


class _FakeDockerd(socketserver.ThreadingUnixStreamServer):
    allow_reuse_address = True
    daemon_threads = True


def _frame(stream: int, payload: bytes) -> bytes:
    return bytes([stream, 0, 0, 0]) + struct.pack(">I", len(payload)) + payload


class FakeState:
    def __init__(self):
        self.containers = {}
        self.images = {"present:latest"}
        self.pulled = []
        self.killed = []
        self.removed = []
        self.created_payloads = {}
        self.exit_code = 0
        self.wait_delay = 0.05


def make_handler(state: FakeState):
    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _raw(self, code, body, ctype="application/octet-stream"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path
            if path == "/_ping":
                return self._raw(200, b"OK", "text/plain")
            if path.endswith("/version"):
                return self._json(200, {"Version": "99.fake"})
            if "/images/" in path and path.endswith("/json"):
                name = path.split("/images/")[1][:-len("/json")]
                if ":" not in name:
                    name += ":latest"
                if name in state.images:
                    return self._json(200, {"Id": "sha256:abc"})
                return self._json(404, {"message": "no such image"})
            if path.endswith("/json") and "/containers/" in path:
                cid = path.split("/containers/")[1][:-len("/json")]
                if cid in state.containers:
                    return self._json(200, {"Id": cid,
                                            "State": {"Running": True}})
                return self._json(404, {"message": "no such container"})
            if "/logs" in path:
                return self._raw(200, _frame(1, b"hello-out\n")
                                 + _frame(2, b"hello-err\n"))
            if "/stats" in path:
                return self._json(200, {
                    "memory_stats": {"usage": 1048576},
                    "cpu_stats": {"cpu_usage": {"total_usage": 5000000}}})
            return self._json(404, {"message": f"GET {path}?"})

        def do_POST(self):
            path = self.path
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            if "/images/create" in path:
                image = path.split("fromImage=")[1]
                state.pulled.append(image)
                state.images.add(image)
                return self._raw(200, json.dumps(
                    {"status": "Download complete"}).encode() + b"\n")
            if path.endswith("/containers/create") or \
                    "/containers/create?name=" in path:
                name = path.split("name=")[1] if "name=" in path else "c"
                cid = f"cid-{len(state.containers)}-{name[:20]}"
                state.containers[cid] = "created"
                state.created_payloads[cid] = json.loads(body)
                return self._json(201, {"Id": cid})
            if path.endswith("/start"):
                cid = path.split("/containers/")[1][:-len("/start")]
                state.containers[cid] = "running"
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            if path.endswith("/wait"):
                cid = path.split("/containers/")[1][:-len("/wait")]
                time.sleep(state.wait_delay)
                state.containers[cid] = "exited"
                return self._json(200, {"StatusCode": state.exit_code})
            if "/kill" in path:
                cid = path.split("/containers/")[1].split("/kill")[0]
                sig = path.split("signal=")[1] if "signal=" in path else ""
                state.killed.append((cid, sig))
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            return self._json(404, {"message": f"POST {path}?"})

        def do_DELETE(self):
            cid = self.path.split("/containers/")[1].split("?")[0]
            state.removed.append(cid)
            state.containers.pop(cid, None)
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()

    return Handler


@pytest.fixture
def fake_dockerd(tmp_path):
    state = FakeState()
    sock = str(tmp_path / "docker.sock")
    server = _FakeDockerd(sock, make_handler(state))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield sock, state
    server.shutdown()


def _mk_driver(sock):
    api = DockerAPI(socket_path=sock)
    ctx = DriverContext(driver_name="docker", alloc_id="a1", config=None)
    return DockerAPIDriver(ctx, api), api


class _TaskDir:
    def __init__(self, base):
        self.dir = str(base)
        self.log_dir = os.path.join(str(base), "logs")
        self.task_name = "web"


def _mk_task(image="present", command=""):
    task = mock.job().task_groups[0].tasks[0]
    task.name = "web"
    task.driver = "docker"
    task.config = {"image": image}
    if command:
        task.config["command"] = command
    task.resources = s.Resources(cpu=250, memory_mb=64)
    task.resources.networks = []
    return task


class TestDockerAPIDriver:
    def test_fingerprint(self, fake_dockerd):
        sock, state = fake_dockerd
        drv, _ = _mk_driver(sock)
        node = mock.node()
        assert drv.fingerprint(node)
        assert node.attributes["driver.docker"] == "1"
        assert node.attributes["driver.docker.version"] == "99.fake"

    def test_unavailable_socket(self, tmp_path):
        drv, api = _mk_driver(str(tmp_path / "nope.sock"))
        assert not api.available()
        assert not drv.fingerprint(mock.node())

    def test_full_lifecycle(self, fake_dockerd, tmp_path):
        sock, state = fake_dockerd
        drv, _ = _mk_driver(sock)
        task = _mk_task(image="busybox", command="sleep")
        env = TaskEnv(env_map={"NOMAD_TASK_NAME": "web"})
        ectx = ExecContext(task_dir=_TaskDir(tmp_path / "task"), task_env=env)

        drv.prestart(ectx, task)  # image absent → pull
        assert state.pulled == ["busybox:latest"]

        resp = drv.start(ectx, task)
        handle = resp.handle
        cid = handle.cid
        payload = state.created_payloads[cid]
        assert payload["Image"] == "busybox"
        assert payload["HostConfig"]["Memory"] == 64 * 1024 * 1024
        assert payload["HostConfig"]["CpuShares"] == 250
        assert any(e.startswith("NOMAD_TASK_NAME=") for e in payload["Env"])
        assert payload["Cmd"] == ["sleep"]

        assert handle.wait_ch().wait(10.0)
        assert handle.wait_result().exit_code == 0
        # logs were flushed into the executor-style log tree
        out = open(os.path.join(ectx.task_dir.log_dir, "web.stdout.0"),
                   "rb").read()
        err = open(os.path.join(ectx.task_dir.log_dir, "web.stderr.0"),
                   "rb").read()
        assert out == b"hello-out\n" and err == b"hello-err\n"
        assert cid in state.removed

    def test_failure_exit_code(self, fake_dockerd, tmp_path):
        sock, state = fake_dockerd
        state.exit_code = 137
        drv, _ = _mk_driver(sock)
        ectx = ExecContext(task_dir=_TaskDir(tmp_path / "t2"), task_env=TaskEnv())
        resp = drv.start(ectx, _mk_task())
        assert resp.handle.wait_ch().wait(10.0)
        assert resp.handle.wait_result().exit_code == 137

    def test_kill_and_signal(self, fake_dockerd, tmp_path):
        sock, state = fake_dockerd
        state.wait_delay = 1.0
        drv, _ = _mk_driver(sock)
        ectx = ExecContext(task_dir=_TaskDir(tmp_path / "t3"), task_env=TaskEnv())
        resp = drv.start(ectx, _mk_task())
        resp.handle.signal(15)
        resp.handle.kill()
        sigs = [sig for _c, sig in state.killed]
        assert "SIGTERM" in sigs and "SIGKILL" in sigs
        assert resp.handle.wait_ch().wait(10.0)

    def test_open_reattach(self, fake_dockerd, tmp_path):
        sock, state = fake_dockerd
        state.wait_delay = 0.5
        drv, _ = _mk_driver(sock)
        ectx = ExecContext(task_dir=_TaskDir(tmp_path / "t4"), task_env=TaskEnv())
        resp = drv.start(ectx, _mk_task())
        hid = resp.handle.id()
        assert hid.startswith("docker-api:")
        h2 = drv.open(ectx, hid)
        assert h2.wait_ch().wait(10.0)

    def test_stats(self, fake_dockerd, tmp_path):
        sock, state = fake_dockerd
        state.wait_delay = 1.0
        drv, _ = _mk_driver(sock)
        ectx = ExecContext(task_dir=_TaskDir(tmp_path / "t5"), task_env=TaskEnv())
        resp = drv.start(ectx, _mk_task())
        st = resp.handle.stats()
        # Executor-schema keys: one stats shape regardless of transport.
        assert st["rss_bytes"] == 1048576
        assert st["cpu_seconds"] == pytest.approx(0.005)
        resp.handle.kill()


def test_demux_tty_fallback():
    out, err = _demux(b"raw tty output with no framing")
    assert out == b"raw tty output with no framing" and err == b""
