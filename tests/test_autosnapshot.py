"""Automatic FSM snapshotting on a live single-voter FileLog server
(ISSUE 10): entry/byte thresholds trip a background snapshot taken OFF
the apply path — the expensive serialization runs on a copy-on-write
state snapshot outside the log lock while appends keep flowing into a
freshly rolled WAL segment — and restore parity with operator-invoked
snapshots holds.
"""
import os
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.server.raft import FileLog


def wait_until(predicate, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def snapshots_in(d):
    return sorted(int(f.split("-", 1)[1]) for f in os.listdir(d)
                  if f.startswith("snapshot-")
                  and not f.endswith(".tmp"))


def segments_in(d):
    return [f for f in os.listdir(d) if f.startswith("walseg-")]


@pytest.mark.parametrize("native", [True, False])
class TestAutoSnapshot:
    def _mk(self, d, monkeypatch, native, **kw):
        if not native:
            monkeypatch.setenv("NOMAD_TPU_NO_NATIVE", "1")
        log = FileLog(FSM(), d, **kw)
        if not native:
            assert log._nwal is None
        return log

    def test_threshold_trips_under_live_writes(self, tmp_path,
                                               monkeypatch, native):
        """Concurrent appliers push past the entry threshold; the
        background thread snapshots (possibly repeatedly), segments are
        cleaned up, and a restart replays to the identical state."""
        d = str(tmp_path / "raft")
        log = self._mk(d, monkeypatch, native, snapshot_entries=40,
                       snapshot_bytes=0, snapshot_interval=0.05)

        def writer():
            for _ in range(60):
                log.apply(MessageType.NODE_REGISTER, {"node": mock.node()})

        threads = [threading.Thread(target=writer) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert log.applied_index() == 180
        assert wait_until(lambda: bool(snapshots_in(d))), \
            "no automatic snapshot was taken"
        # Sealed segments are deleted once the snapshot blob covering
        # them is durable.
        assert wait_until(lambda: not segments_in(d))
        log.close()

        log2 = self._mk(d, monkeypatch, native)
        assert log2.applied_index() == 180
        assert len(log2.fsm.state.nodes(None)) == 180
        log2.close()

    def test_snapshot_runs_off_the_apply_path(self, tmp_path,
                                              monkeypatch, native):
        """The serialization/persist step runs on the dedicated
        snapshot thread — never an applier's — and appends LANDED WHILE
        IT RAN survive the compaction (they flow into the fresh segment,
        which is not covered by the snapshot and must not be deleted)."""
        d = str(tmp_path / "raft")
        persist_threads = []
        release = threading.Event()
        entered = threading.Event()

        class SlowSnapLog(FileLog):
            def _persist_snapshot_blob(self, snap_store, index):
                persist_threads.append(threading.current_thread().name)
                entered.set()
                # Hold the persist open while the main thread appends:
                # the log lock is NOT held here, so these applies must
                # complete (a bounded wait proves it).
                release.wait(10.0)
                super()._persist_snapshot_blob(snap_store, index)

        if not native:
            monkeypatch.setenv("NOMAD_TPU_NO_NATIVE", "1")
        log = SlowSnapLog(FSM(), d, snapshot_entries=10, snapshot_bytes=0,
                          snapshot_interval=0.02)
        if not native:
            assert log._nwal is None
        for _ in range(12):
            log.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        assert entered.wait(5.0), "auto snapshot did not start"
        snap_index = None
        # Appends DURING the in-flight persist: if the snapshot held the
        # log lock these would block until release; give them a bounded
        # window instead.
        done = threading.Event()

        def late_appends():
            for _ in range(5):
                log.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
            done.set()

        t = threading.Thread(target=late_appends)
        t.start()
        assert done.wait(5.0), \
            "appends blocked behind the snapshot persist"
        release.set()
        t.join()
        assert wait_until(lambda: bool(snapshots_in(d)))
        snap_index = snapshots_in(d)[-1]
        assert log.applied_index() == 17
        assert snap_index <= 12  # the late appends are NOT in the blob
        log.close()

        # Off-path contract: the persist ran on the snapshot thread.
        assert persist_threads
        assert all(name == "filelog-snapshot" for name in persist_threads)

        # The late appends survive the restart: they were in the fresh
        # segment/active WAL, not in the deleted covered segments.
        if not native:
            monkeypatch.setenv("NOMAD_TPU_NO_NATIVE", "1")
        log2 = FileLog(FSM(), d)
        assert log2.applied_index() == 17
        assert len(log2.fsm.state.nodes(None)) == 17
        log2.close()

    def test_restore_parity_with_operator_snapshot(self, tmp_path,
                                                   monkeypatch, native):
        """An automatic snapshot and an operator-invoked snapshot of the
        same entry stream restore to identical state."""
        nodes = [mock.node() for _ in range(30)]

        d_auto = str(tmp_path / "auto")
        log_a = self._mk(d_auto, monkeypatch, native, snapshot_entries=10,
                         snapshot_bytes=0, snapshot_interval=0.02)
        d_op = str(tmp_path / "op")
        log_o = self._mk(d_op, monkeypatch, native, snapshot_entries=0,
                         snapshot_bytes=0)
        assert log_o._snap_thread is None  # thresholds 0 ⇒ no watcher
        for node in nodes:
            log_a.apply(MessageType.NODE_REGISTER, {"node": node})
            log_o.apply(MessageType.NODE_REGISTER, {"node": node})
        assert wait_until(lambda: bool(snapshots_in(d_auto)))
        log_o.snapshot()  # operator-invoked
        assert snapshots_in(d_op) == [30]
        log_a.close()
        log_o.close()

        ra = self._mk(d_auto, monkeypatch, native)
        ro = self._mk(d_op, monkeypatch, native)
        assert ra.applied_index() == ro.applied_index() == 30
        ids_a = {n.id for n in ra.fsm.state.nodes(None)}
        ids_o = {n.id for n in ro.fsm.state.nodes(None)}
        assert ids_a == ids_o == {n.id for n in nodes}
        ra.close()
        ro.close()

    def test_crash_between_roll_and_blob_loses_nothing(self, tmp_path,
                                                       monkeypatch,
                                                       native):
        """A crash after the WAL roll but BEFORE the snapshot blob is
        durable leaves the sealed segments on disk; recovery replays
        them — an unfinished snapshot can never lose entries."""
        d = str(tmp_path / "raft")

        class CrashySnapLog(FileLog):
            def _persist_snapshot_blob(self, snap_store, index):
                raise RuntimeError("injected crash before blob persist")

        if not native:
            monkeypatch.setenv("NOMAD_TPU_NO_NATIVE", "1")
        log = CrashySnapLog(FSM(), d, snapshot_entries=0, snapshot_bytes=0)
        for _ in range(8):
            log.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        with pytest.raises(RuntimeError):
            log.snapshot()
        assert segments_in(d), "roll did not seal a segment"
        assert not snapshots_in(d)
        log.close()

        log2 = self._mk(d, monkeypatch, native)
        assert log2.applied_index() == 8
        assert len(log2.fsm.state.nodes(None)) == 8
        # And a later (successful) snapshot cleans the leftovers up.
        log2.snapshot()
        assert snapshots_in(d) == [8]
        log2.close()
