"""System scheduler tests (reference: scheduler/system_sched_test.go)."""
from nomad_tpu import mock
from nomad_tpu.scheduler import Harness, new_system_scheduler
from nomad_tpu.structs import structs as s


def make_harness(num_nodes=10):
    h = Harness()
    nodes = []
    for _ in range(num_nodes):
        n = mock.node()
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
    return h, nodes


def sys_eval(job, triggered_by=s.EVAL_TRIGGER_JOB_REGISTER):
    return s.Evaluation(
        id=s.generate_uuid(),
        priority=job.priority,
        triggered_by=triggered_by,
        job_id=job.id,
        status=s.EVAL_STATUS_PENDING,
        type=s.JOB_TYPE_SYSTEM,
    )


def test_system_places_on_every_node():
    h, nodes = make_harness(10)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_system_scheduler, sys_eval(job))
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 10
    assert set(plan.node_allocation) == {n.id for n in nodes}
    h.assert_eval_status(s.EVAL_STATUS_COMPLETE)


def test_system_skips_infeasible_nodes():
    h, nodes = make_harness(5)
    # two nodes lack the exec driver
    for n in nodes[:2]:
        stored = h.state.node_by_id(None, n.id).copy()
        del stored.attributes["driver.exec"]
        stored.compute_class()
        h.state.upsert_node(h.next_index(), stored)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_system_scheduler, sys_eval(job))
    placed = [a for allocs in h.plans[0].node_allocation.values() for a in allocs]
    assert len(placed) == 3
    # filtered nodes don't count as queued failures
    assert h.evals[0].queued_allocations == {"web": 0}


def test_system_new_node_gets_alloc():
    h, _ = make_harness(3)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_system_scheduler, sys_eval(job))

    new_node = mock.node()
    h.state.upsert_node(h.next_index(), new_node)
    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_system_scheduler, sys_eval(job, s.EVAL_TRIGGER_NODE_UPDATE))
    placed = [a for allocs in h2.plans[0].node_allocation.values() for a in allocs]
    assert len(placed) == 1
    assert placed[0].node_id == new_node.id


def test_system_down_node_stops_alloc():
    h, nodes = make_harness(3)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_system_scheduler, sys_eval(job))

    down = nodes[0]
    h.state.update_node_status(h.next_index(), down.id, s.NODE_STATUS_DOWN)
    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_system_scheduler, sys_eval(job, s.EVAL_TRIGGER_NODE_UPDATE))
    plan = h2.plans[0]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert len(stopped) == 1
    assert stopped[0].node_id == down.id
    assert stopped[0].client_status == s.ALLOC_CLIENT_STATUS_LOST
    # system jobs never migrate — no replacement placement on live nodes
    assert plan.node_allocation == {}


def test_system_deregister_stops_all():
    h, _ = make_harness(3)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_system_scheduler, sys_eval(job))

    stopped_job = h.state.job_by_id(None, job.id).copy()
    stopped_job.stop = True
    h.state.upsert_job(h.next_index(), stopped_job)
    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_system_scheduler, sys_eval(job, s.EVAL_TRIGGER_JOB_DEREGISTER))
    stopped = [a for allocs in h2.plans[0].node_update.values() for a in allocs]
    assert len(stopped) == 3
