"""HCL jobspec parser tests (reference: jobspec/parse_test.go)."""

import pytest

from nomad_tpu import jobspec
from nomad_tpu.jobspec import ParseError, parse, parse_duration
from nomad_tpu.structs import structs as s

FULL = """
job "binstore" {
  region      = "fooregion"
  type        = "batch"
  priority    = 52
  all_at_once = true
  datacenters = ["us2", "eu1"]
  vault_token = "foo"

  meta {
    foo = "bar"
  }

  constraint {
    attribute = "kernel.os"
    value     = "windows"
  }

  update {
    stagger      = "60s"
    max_parallel = 2
  }

  group "binsl" {
    count = 5

    restart {
      attempts = 5
      interval = "10m"
      delay    = "15s"
      mode     = "delay"
    }

    ephemeral_disk {
      sticky = true
      size   = 150
    }

    task "binstore" {
      driver = "docker"
      user   = "bob"
      leader = true

      config {
        image = "example/binstore"
        labels {
          FOO = "bar"
        }
      }

      logs {
        max_files     = 14
        max_file_size = 101
      }

      env {
        HELLO = "world"
      }

      service {
        tags = ["foo", "bar"]
        port = "http"

        check {
          name     = "check-name"
          type     = "tcp"
          interval = "10s"
          timeout  = "2s"
          port     = "admin"
        }
      }

      resources {
        cpu    = 500
        memory = 128

        network {
          mbits = "100"

          port "one" {
            static = 1
          }
          port "http" {
          }
        }
      }

      kill_timeout = "22s"

      artifact {
        source = "http://foo.example.com/artifact"
        options {
          checksum = "md5:b8a4f3f72ecab0510a6a31e997461c5f"
        }
      }

      vault {
        policies = ["foo", "bar"]
      }

      template {
        source        = "foo"
        destination   = "foo"
        change_mode   = "signal"
        change_signal = "sighup"
        splay         = "10s"
      }
    }
  }
}
"""


def test_parse_full_job():
    job = parse(FULL)
    assert job.id == "binstore"
    assert job.name == "binstore"
    assert job.region == "fooregion"
    assert job.type == "batch"
    assert job.priority == 52
    assert job.all_at_once is True
    assert job.datacenters == ["us2", "eu1"]
    assert job.vault_token == "foo"
    assert job.meta == {"foo": "bar"}
    assert len(job.constraints) == 1
    c = job.constraints[0]
    assert (c.ltarget, c.rtarget, c.operand) == ("kernel.os", "windows", "=")
    assert job.update.stagger == 60.0
    assert job.update.max_parallel == 2

    assert len(job.task_groups) == 1
    tg = job.task_groups[0]
    assert tg.name == "binsl"
    assert tg.count == 5
    assert tg.restart_policy.attempts == 5
    assert tg.restart_policy.interval == 600.0
    assert tg.restart_policy.delay == 15.0
    assert tg.ephemeral_disk.sticky is True
    assert tg.ephemeral_disk.size_mb == 150

    task = tg.tasks[0]
    assert task.name == "binstore"
    assert task.driver == "docker"
    assert task.user == "bob"
    assert task.leader is True
    assert task.config["image"] == "example/binstore"
    assert task.config["labels"] == {"FOO": "bar"}
    assert task.log_config.max_files == 14
    assert task.log_config.max_file_size_mb == 101
    assert task.env == {"HELLO": "world"}
    assert task.kill_timeout == 22.0

    svc = task.services[0]
    assert svc.tags == ["foo", "bar"]
    assert svc.port_label == "http"
    assert svc.name == "binstore-binsl-binstore"
    chk = svc.checks[0]
    assert chk.name == "check-name"
    assert chk.type == "tcp"
    assert chk.interval == 10.0
    assert chk.timeout == 2.0
    assert chk.port_label == "admin"

    res = task.resources
    assert res.cpu == 500
    assert res.memory_mb == 128
    net = res.networks[0]
    assert net.mbits == 100
    assert [(p.label, p.value) for p in net.reserved_ports] == [("one", 1)]
    assert [p.label for p in net.dynamic_ports] == ["http"]

    art = task.artifacts[0]
    assert art.getter_source == "http://foo.example.com/artifact"
    assert art.relative_dest == "local/"
    assert art.getter_options["checksum"].startswith("md5:")

    assert task.vault.policies == ["foo", "bar"]
    tmpl = task.templates[0]
    assert tmpl.change_mode == "signal"
    assert tmpl.change_signal == "SIGHUP"
    assert tmpl.splay == 10.0


def test_parse_duration():
    assert parse_duration("10s") == 10.0
    assert parse_duration("10m") == 600.0
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration("250ms") == 0.25
    assert parse_duration("0") == 0.0
    with pytest.raises(ParseError):
        parse_duration("banana")
    with pytest.raises(ParseError):
        parse_duration("10")  # bare numbers in strings are not durations


def test_unknown_key_rejected():
    with pytest.raises(ParseError, match="invalid key"):
        parse('job "x" { bad_key = 1 }')
    with pytest.raises(ParseError, match="invalid key"):
        parse('job "x" { group "g" { bad = true } }')
    with pytest.raises(ParseError, match="invalid key"):
        parse('job "x" { task "t" { drivver = "x" } }')


def test_default_job():
    job = parse('job "foo" { }')
    assert job.id == "foo"
    assert job.name == "foo"
    assert job.region == "global"
    assert job.type == s.JOB_TYPE_SERVICE
    assert job.priority == s.JOB_DEFAULT_PRIORITY


def test_specify_id_and_name():
    job = parse('job "label" { id = "my-id" name = "my-name" }')
    assert job.id == "my-id"
    assert job.name == "my-name"


def test_bare_task_wraps_group():
    job = parse('job "foo" { task "bar" { driver = "raw_exec" } }')
    assert len(job.task_groups) == 1
    assert job.task_groups[0].name == "bar"
    assert job.task_groups[0].count == 1
    assert job.task_groups[0].tasks[0].driver == "raw_exec"


def test_constraint_sugar():
    job = parse('''
job "foo" {
  constraint {
    attribute = "$attr.kernel.version"
    regexp    = "[0-9.]+"
  }
  constraint {
    attribute = "$attr.kernel.version"
    version   = "~> 3.2"
  }
  constraint {
    attribute    = "$meta.data"
    set_contains = "foo,bar"
  }
  constraint {
    distinct_hosts = true
  }
  constraint {
    distinct_property = "${meta.rack}"
  }
}''')
    ops = [c.operand for c in job.constraints]
    assert ops == ["regexp", "version", "set_contains", "distinct_hosts",
                   "distinct_property"]
    assert job.constraints[0].rtarget == "[0-9.]+"
    assert job.constraints[1].rtarget == "~> 3.2"
    assert job.constraints[4].ltarget == "${meta.rack}"


def test_periodic_cron():
    job = parse('''
job "foo" {
  periodic {
    cron             = "*/5 * * * *"
    prohibit_overlap = true
  }
}''')
    assert job.periodic.enabled is True
    assert job.periodic.spec == "*/5 * * * *"
    assert job.periodic.spec_type == s.PERIODIC_SPEC_CRON
    assert job.periodic.prohibit_overlap is True


def test_parameterized_job():
    job = parse('''
job "p" {
  parameterized {
    payload       = "required"
    meta_required = ["foo"]
    meta_optional = ["bar"]
  }
  group "foo" {
    task "bar" {
      driver = "docker"
      dispatch_payload {
        file = "foo/bar"
      }
    }
  }
}''')
    assert job.parameterized_job.payload == "required"
    assert job.parameterized_job.meta_required == ["foo"]
    assert job.task_groups[0].tasks[0].dispatch_payload.file == "foo/bar"


def test_vault_inheritance():
    job = parse('''
job "example" {
  vault {
    policies = ["job"]
  }
  group "cache" {
    vault {
      policies = ["group"]
    }
    task "redis" { }
    task "redis2" {
      vault {
        policies = ["task"]
        env      = false
      }
    }
  }
  group "cache2" {
    task "redis" { }
  }
}''')
    g1 = job.task_groups[0]
    assert g1.tasks[0].vault.policies == ["group"]
    assert g1.tasks[1].vault.policies == ["task"]
    assert g1.tasks[1].vault.env is False
    g2 = job.task_groups[1]
    assert g2.tasks[0].vault.policies == ["job"]


def test_port_label_validation():
    with pytest.raises(ParseError, match="naming requirements"):
        parse('''
job "foo" {
  task "t" {
    resources {
      network {
        port "bad-label!" { }
      }
    }
  }
}''')
    with pytest.raises(ParseError, match="collision"):
        parse('''
job "foo" {
  task "t" {
    resources {
      network {
        mbits = 10
        port "dup" { static = 1 }
        port "dup" { }
      }
    }
  }
}''')


def test_nested_config_map():
    job = parse('''
job "foo" {
  task "bar" {
    driver = "docker"
    config {
      image = "example/image"
      port_map {
        db = 1234
      }
    }
  }
}''')
    cfg = job.task_groups[0].tasks[0].config
    assert cfg["port_map"] == {"db": 1234}


def test_multiple_jobs_rejected():
    with pytest.raises(ParseError):
        parse('job "a" { }\njob "b" { }')
    with pytest.raises(ParseError):
        parse('not_a_job "a" { }')


def test_heredoc_and_comments():
    job = parse('''
# leading comment
job "foo" {
  // line comment
  /* block
     comment */
  task "t" {
    driver = "raw_exec"
    template {
      destination = "local/x"
      data        = <<EOF
hello
world
EOF
    }
  }
}''')
    tmpl = job.task_groups[0].tasks[0].templates[0]
    assert tmpl.embedded_tmpl == "hello\nworld\n"


def test_parse_file(tmp_path):
    p = tmp_path / "job.nomad"
    p.write_text('job "f" { task "t" { driver = "raw_exec" } }')
    job = jobspec.parse_file(str(p))
    assert job.id == "f"
