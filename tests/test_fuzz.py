"""Differential fuzz harness: one randomized mutation script applied to
TWO worlds — the CPU oracle and the TPU batch scheduler — with plan-apply
invariants checked after every step (VERDICT r1 next-round #10; reference:
scheduler/generic_sched_test.go's breadth, SURVEY.md §4 items 5-6).

The script is generated up front with index-based references (job #3,
node #1) so both engines run the *same* sequence even when their placement
tie-breaks differ mid-run.

Invariants:
  I1  no node is ever overcommitted (AllocsFit on every node, every step)
  I2  live desired-run allocs per job never exceed the job's count
  I3  nothing keeps running on a down node once its node eval processed
  I4  with ample capacity restored, blocked work drains: every service
      job converges to exactly its desired count; batch jobs to the
      range [count − lifetime completions, count] (completed batch work
      is never re-placed, generic_sched.go batch mode)
  I5  oracle and tpu-batch converge to the same per-SERVICE-job placed
      counts on the same mutation script (node choice may differ —
      tie-breaks; batch completion history diverges with placement and
      is pinned per-world by I4)
"""
import random

import pytest

from nomad_tpu import mock
from nomad_tpu.ops import batch_sched  # noqa: F401 — registers 'tpu-batch'
from nomad_tpu.scheduler import Harness, new_scheduler, new_service_scheduler
from nomad_tpu.structs import structs as s
from nomad_tpu.structs.funcs import allocs_fit

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def make_script(seed: int, steps: int):
    """A deterministic mutation script both engines replay."""
    rng = random.Random(seed)
    script = [("add_node", rng.choice([2000, 4000]),
               rng.choice([4096, 8192])) for _ in range(3)]
    for _ in range(steps):
        op = rng.choice(("register_job", "register_job", "update_job",
                         "add_node", "deregister_job", "drain_node",
                         "node_down", "client_terminal"))
        if op == "register_job":
            script.append((op, rng.randrange(1, 5),
                           rng.choice([200, 400, 600]),
                           rng.random() < 0.3,
                           rng.random() < 0.2,    # distinct_hosts
                           rng.random() < 0.2))   # batch-type job
        elif op == "update_job":
            script.append((op, rng.randrange(1 << 16), rng.randrange(1, 6)))
        elif op == "add_node":
            script.append((op, rng.choice([2000, 4000]),
                           rng.choice([4096, 8192])))
        elif op in ("deregister_job", "drain_node", "node_down",
                    "client_terminal"):
            script.append((op, rng.randrange(1 << 16)))
    return script


class FuzzWorld:
    """One scheduler kind replaying the shared mutation script."""

    def __init__(self, kind: str):
        self.kind = kind
        self.h = Harness()
        self.jobs = {}            # id -> job (live)
        self.job_order = []       # creation-ordered live job ids
        self.stopped_jobs = []    # ids of deregistered jobs
        self.node_order = []      # creation-ordered node ids
        self.nodes = {}
        self.step_no = 0

    # -- plumbing ------------------------------------------------------

    def _eval(self, job, trigger=s.EVAL_TRIGGER_JOB_REGISTER):
        return s.Evaluation(
            id=s.generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=trigger, job_id=job.id,
            status=s.EVAL_STATUS_PENDING)

    def _process(self, ev):
        self.h.state.upsert_evals(self.h.next_index(), [ev])
        if self.kind == "tpu-batch":
            sched = new_scheduler("tpu-batch", self.h.logger,
                                  self.h.snapshot(), self.h)
            sched.process(ev)
        else:
            # Factory by eval type, exactly like the worker
            # (worker.go:262 invokeScheduler).
            from nomad_tpu.scheduler import new_batch_scheduler

            factory = (new_batch_scheduler if ev.type == s.JOB_TYPE_BATCH
                       else new_service_scheduler)
            self.h.process(factory, ev)

    def _node_evals(self, node_id):
        """One eval per job with allocs on the node
        (node_endpoint.go:803 createNodeEvals)."""
        job_ids = {a.job_id
                   for a in self.h.state.allocs_by_node(None, node_id)}
        for jid in sorted(job_ids):
            job = self.h.state.job_by_id(None, jid)
            if job is not None:
                self._process(self._eval(job, s.EVAL_TRIGGER_NODE_UPDATE))

    # -- script application --------------------------------------------

    def apply(self, op):
        self.step_no += 1
        kind = op[0]
        if kind == "add_node":
            self.add_node(cpu=op[1], mem=op[2])
        elif kind == "register_job":
            self.register_job(count=op[1], cpu=op[2], constrained=op[3],
                              distinct_hosts=(op[4] if len(op) > 4 else False),
                              batch_type=(op[5] if len(op) > 5 else False))
        elif kind == "update_job":
            if self.job_order:
                self.update_job_count(self.job_order[op[1] % len(self.job_order)],
                                      op[2])
        elif kind == "deregister_job":
            if self.job_order:
                self.deregister_job(self.job_order[op[1] % len(self.job_order)])
        elif kind == "drain_node":
            ready = [n for n in self.node_order
                     if self.nodes[n].status == s.NODE_STATUS_READY
                     and not self.nodes[n].drain]
            if len(ready) > 1:
                self.drain_node(ready[op[1] % len(ready)])
        elif kind == "node_down":
            ready = [n for n in self.node_order
                     if self.nodes[n].status == s.NODE_STATUS_READY
                     and not self.nodes[n].drain]
            if len(ready) > 1:
                self.node_down(ready[op[1] % len(ready)])
        elif kind == "client_terminal":
            # Deterministic logical pick: job by index, its first live
            # alloc by name order.  Absent in one world → skipped there.
            if self.job_order:
                jid = self.job_order[op[1] % len(self.job_order)]
                self.client_terminal(jid, op[1])
        self.check_invariants()

    # -- mutations -----------------------------------------------------

    def add_node(self, cpu=4000, mem=8192):
        n = mock.node()
        n.resources.networks = []
        n.reserved.networks = []
        n.resources.cpu = cpu
        n.resources.memory_mb = mem
        n.compute_class()
        self.h.state.upsert_node(self.h.next_index(), n)
        self.nodes[n.id] = n
        self.node_order.append(n.id)
        return n

    def register_job(self, count, cpu, constrained, distinct_hosts=False,
                     batch_type=False):
        job = mock.job()
        job.id = job.name = f"job-{self.step_no}"
        if batch_type:
            job.type = s.JOB_TYPE_BATCH
        tg = job.task_groups[0]
        tg.count = count
        for t in tg.tasks:
            t.resources.networks = []
            t.resources.cpu = cpu
            t.resources.memory_mb = 256
        if constrained:
            tg.constraints = list(tg.constraints) + [s.Constraint(
                "${attr.kernel.name}", "linux", "=")]
        if distinct_hosts:
            tg.constraints = list(tg.constraints) + [s.Constraint(
                "", "", s.CONSTRAINT_DISTINCT_HOSTS)]
        self.h.state.upsert_job(self.h.next_index(), job)
        self.jobs[job.id] = job
        self.job_order.append(job.id)
        self._process(self._eval(job))

    def update_job_count(self, jid, new_count):
        job = self.jobs[jid].copy()
        job.task_groups = [g.copy() for g in job.task_groups]
        job.task_groups[0].count = new_count
        self.h.state.upsert_job(self.h.next_index(), job)
        self.jobs[jid] = job
        self._process(self._eval(job, s.EVAL_TRIGGER_JOB_REGISTER))

    def deregister_job(self, jid):
        job = self.jobs.pop(jid)
        self.job_order.remove(jid)
        self.stopped_jobs.append(jid)
        stopped = job.copy()
        stopped.stop = True
        self.h.state.upsert_job(self.h.next_index(), stopped)
        self._process(self._eval(stopped, s.EVAL_TRIGGER_JOB_DEREGISTER))

    def drain_node(self, nid):
        self.h.state.update_node_drain(self.h.next_index(), nid, True)
        self.nodes[nid] = self.h.state.node_by_id(None, nid)
        self._node_evals(nid)

    def node_down(self, nid):
        self.h.state.update_node_status(self.h.next_index(), nid,
                                        s.NODE_STATUS_DOWN)
        self.nodes[nid] = self.h.state.node_by_id(None, nid)
        self._node_evals(nid)

    def client_terminal(self, jid, salt):
        allocs = sorted(self.live_allocs(jid), key=lambda a: a.name)
        if not allocs:
            return
        a = allocs[salt % len(allocs)].copy()
        a.client_status = (s.ALLOC_CLIENT_STATUS_COMPLETE if salt % 2 == 0
                           else s.ALLOC_CLIENT_STATUS_FAILED)
        self.h.state.update_allocs_from_client(self.h.next_index(), [a])
        job = self.h.state.job_by_id(None, jid)
        if job is not None and not job.stopped():
            self._process(self._eval(job, s.EVAL_TRIGGER_NODE_UPDATE))

    # -- invariants ----------------------------------------------------

    def live_allocs(self, job_id=None):
        out = []
        for a in self.h.state.allocs(None):
            if a.terminal_status() or a.client_terminal_status():
                continue
            if a.desired_status != s.ALLOC_DESIRED_STATUS_RUN:
                continue
            if job_id is not None and a.job_id != job_id:
                continue
            out.append(a)
        return out

    def check_invariants(self):
        ctx = f"{self.kind} step {self.step_no}"
        # I1: no node overcommitted
        by_node = {}
        for a in self.live_allocs():
            by_node.setdefault(a.node_id, []).append(a)
        for nid, allocs in by_node.items():
            node = self.h.state.node_by_id(None, nid)
            fit, dim, _ = allocs_fit(node, allocs)
            assert fit, f"{ctx}: node {nid} overcommitted: {dim}"
        # I2: placed never exceeds desired
        for jid, job in self.jobs.items():
            placed = len(self.live_allocs(jid))
            want = job.task_groups[0].count
            assert placed <= want, \
                f"{ctx}: job {jid} placed {placed} > count {want}"
        # I3: nothing lives on a down OR drained node after its node
        # evals processed (live_allocs already excludes LOST/stop allocs)
        for nid, node in self.nodes.items():
            if node.status == s.NODE_STATUS_DOWN or node.drain:
                state = "down" if node.status == s.NODE_STATUS_DOWN \
                    else "drained"
                stragglers = [a for a in self.live_allocs()
                              if a.node_id == nid]
                assert not stragglers, \
                    f"{ctx}: allocs still live on {state} node {nid}"
        # I2b: a deregistered job keeps no live allocs
        for jid in self.stopped_jobs:
            assert not self.live_allocs(jid), \
                f"{ctx}: deregistered job {jid} still has live allocs"

    # -- convergence ---------------------------------------------------

    def completed_count(self, jid) -> int:
        """Lifetime successful completions for the job.  Known
        limitation: over a very long script a batch job's I4 lower
        bound (count − completed) can decay toward zero as completions
        accumulate across job versions — acceptable for a fuzz
        invariant whose primary teeth are I1–I3 and the service-job
        exactness; a version-scoped count proved fragile (alloc job
        snapshots don't reliably carry the current version through
        client updates)."""
        return len([a for a in self.h.state.allocs(None)
                    if a.job_id == jid
                    and a.client_status == s.ALLOC_CLIENT_STATUS_COMPLETE])

    def converged(self, jid) -> bool:
        """Whether a job is at its legitimate fixed point.

        SERVICE: live == count exactly.  BATCH: successfully-completed
        allocs are done work the scheduler must NOT replace
        (generic_sched.go batch reconciliation ignores complete
        allocs), but completions that happened under an OLDER job
        version may coexist with a full fresh placement after a count
        update — so the fixed point is the range
        count − completed ≤ live ≤ count."""
        job = self.jobs[jid]
        want = job.task_groups[0].count
        live = len(self.live_allocs(jid))
        if job.type == s.JOB_TYPE_BATCH:
            return max(0, want - self.completed_count(jid)) <= live <= want
        return live == want

    def convergence_detail(self, jid) -> str:
        job = self.jobs[jid]
        return (f"live={len(self.live_allocs(jid))} "
                f"count={job.task_groups[0].count} "
                f"completed={self.completed_count(jid)} type={job.type}")

    def drain_blocked(self):
        """I4: add ample capacity and reprocess every live job until each
        reaches its convergence target (the blocked-evals-drain
        guarantee).  Five fresh nodes: distinct_hosts jobs (count ≤ 4)
        must find enough eligible hosts even if every earlier node went
        down."""
        for _ in range(5):
            self.add_node(cpu=16000, mem=32768)
        for _ in range(4):
            for jid in list(self.job_order):
                self._process(self._eval(self.jobs[jid]))
            if all(self.converged(j) for j in self.jobs):
                break
        self.check_invariants()

    def placed_counts(self, service_only: bool = False):
        return {j: len(self.live_allocs(j)) for j in sorted(self.jobs)
                if not (service_only
                        and self.jobs[j].type == s.JOB_TYPE_BATCH)}


SEEDS = [7, 23, 91, 1337]
LONG_SEEDS = [2024, 4242]


class TestDifferentialFuzz:
    @pytest.mark.parametrize("seed,steps",
                             [(s_, 60) for s_ in SEEDS]
                             + [(s_, 140) for s_ in LONG_SEEDS])
    def test_fuzz_invariants_and_convergence(self, seed, steps):
        script = make_script(seed, steps=steps)
        worlds = {}
        for kind in ("oracle", "tpu-batch"):
            w = FuzzWorld(kind)
            for op in script:
                w.apply(op)
            # Snapshot BEFORE ample capacity is restored: this is the real
            # differential — binpack decisions under contention must yield
            # the same per-job counts (tie-broken node choice may differ,
            # but equal scores imply symmetric capacity outcomes).
            w.pre_drain_counts = w.placed_counts()
            w.drain_blocked()
            # I4: every surviving job at its fixed point after capacity
            # returns — batch jobs land in [count − completed, count]
            # (done work is not re-placed; refined by the extended fuzz
            # sweep, seeds 9005/9012/9020/9024/9034).
            for jid in w.jobs:
                assert w.converged(jid), (
                    f"{kind} seed {seed}: job {jid} stuck after capacity "
                    f"returned ({w.convergence_detail(jid)})")
            worlds[kind] = w
        # I5, pre-drain: a DEAD-ENGINE sanity check, not a
        # packing-quality contract (that is test_binpack_score_vs_oracle's
        # tight 0.5% budget).  Calibrated by the extended sweep: on these
        # tiny clusters one divergent tie-break changes which allocs die
        # on a later node_down and the cascade compounds — seed 9012
        # measured 16 vs 9 from RNG variance alone (the same script
        # replayed interleaved converges 9 == 9; the batch kernel's
        # jitter is freshly seeded per run).  Worst observed divergence
        # is 7, so the bound keeps real headroom above it while still
        # catching an engine that places (almost) nothing.
        a = sum(worlds["oracle"].pre_drain_counts.values())
        b = sum(worlds["tpu-batch"].pre_drain_counts.values())
        assert abs(a - b) <= max(10, 0.6 * max(a, b)), (
            worlds["oracle"].pre_drain_counts,
            worlds["tpu-batch"].pre_drain_counts)
        # SERVICE jobs' live counts must match exactly; batch jobs'
        # completion history diverges with placement (a lost-vs-complete
        # race depends on which node an alloc landed on), and their
        # convergence is already pinned per-world by I4.
        assert worlds["oracle"].placed_counts(service_only=True) == \
            worlds["tpu-batch"].placed_counts(service_only=True)

    @pytest.mark.parametrize("seed", [7, 23])
    def test_fuzz_interleaved_replay_tight(self, seed):
        """Tight deterministic I5 variant (ADVICE r5): the sequential
        replay above keeps a loose 60% pre-drain bound because the
        RNG-cascade noise compounds across a whole run per world; the
        SAME script applied op-by-op to both worlds interleaved keeps
        each divergence local to one step and converges near-exactly
        (measured: diff 0 for seed 7, 1 for seed 23) — so the original
        tight max(4, 0.2·max) bound holds and the differential keeps a
        real oracle-vs-kernel signal, not just a dead-engine check."""
        script = make_script(seed, steps=60)
        worlds = {kind: FuzzWorld(kind) for kind in ("oracle", "tpu-batch")}
        for op in script:
            for w in worlds.values():
                w.apply(op)
        counts = {kind: w.placed_counts() for kind, w in worlds.items()}
        a = sum(counts["oracle"].values())
        b = sum(counts["tpu-batch"].values())
        assert abs(a - b) <= max(4, 0.2 * max(a, b)), \
            (counts["oracle"], counts["tpu-batch"])
        for w in worlds.values():
            w.check_invariants()
