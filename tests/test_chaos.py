"""Seeded end-to-end chaos scenarios (nomad_tpu/fault.py).

Every scenario is reproducible from one RNG seed: the fault plane's
per-rule RNGs and hit counters make the fire trace a pure function of
(seed, call order), and each test pins the seed.  Fast fixed-seed
scenarios run in tier-1; the probabilistic RPC sweep is marked slow.
"""
import socket
import threading
import time

import pytest

from nomad_tpu import fault, mock
from nomad_tpu.server import EvalBroker, Server, ServerConfig
from nomad_tpu.server.rpc import (
    ConnPool,
    RPCServer,
    TransportError,
    _recv_frame,
    _send_frame,
)
from nomad_tpu.structs import structs as s

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _always_disarm():
    """No scenario may leak into another test (or into tier-1 at large)."""
    yield
    fault.disarm()


def wait_until(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def make_node():
    n = mock.node()
    n.resources.networks = []
    n.reserved.networks = []
    return n


def make_job(count=2):
    j = mock.job()
    j.task_groups[0].count = count
    for t in j.task_groups[0].tasks:
        t.resources.networks = []
    return j


# ---------------------------------------------------------------------------
# the fault plane itself
# ---------------------------------------------------------------------------


class TestFaultPlane:
    def test_disarmed_is_inert(self):
        assert not fault.armed()
        assert fault.faultpoint("rpc.send") is None
        assert fault.trace() == []

    def test_same_seed_same_trace(self):
        """Probabilistic rules replay identically for one seed and
        diverge for another — the reproducibility contract chaos debugging
        rests on."""
        cfg = {"faults": [{"point": "p.q", "action": "drop", "prob": 0.5}]}

        def run(seed):
            with fault.scenario(cfg, seed=seed) as plane:
                for _ in range(64):
                    fault.faultpoint("p.q")
                return plane.trace()

        t_a, t_b, t_c = run(11), run(11), run(12)
        assert t_a == t_b
        assert 0 < len(t_a) < 64  # prob actually probabilistic
        assert t_a != t_c

    def test_after_times_and_match_gates(self):
        fault.arm({"seed": 0, "faults": [
            {"point": "a.b", "action": "delay", "after": 2, "times": 2,
             "match": {"index": 7}}]})
        fired = []
        for i in range(8):
            # non-matching ctx never fires and never consumes the budget
            assert fault.faultpoint("a.b", index=3) is None
            act = fault.faultpoint("a.b", index=7)
            fired.append(act is not None)
        # calls 1-2 skipped by `after`, 3-4 fire, budget exhausted after
        assert fired == [False, False, True, True, False, False, False,
                         False]

    def test_glob_points_and_error_action(self):
        fault.arm([{"point": "rpc.*", "action": "error",
                    "error": "boom injected"}])
        act = fault.faultpoint("rpc.send")
        with pytest.raises(fault.InjectedFault, match="boom injected"):
            act.raise_injected()


# ---------------------------------------------------------------------------
# transport: truncation mid-read, poisoned-connection discard
# ---------------------------------------------------------------------------


class TestTransportFaults:
    def test_recv_mid_frame_eof_is_transport_error(self):
        """A torn frame must surface as TransportError, not a confusing
        struct/msgpack decode error."""
        a, b = socket.socketpair()
        try:
            # length prefix promising 100 bytes, then only 3, then EOF
            a.sendall((100).to_bytes(4, "little") + b"abc")
            a.close()
            with pytest.raises(TransportError, match="mid-frame"):
                _recv_frame(b)
        finally:
            b.close()

    def test_oversized_frame_is_transport_error(self):
        a, b = socket.socketpair()
        try:
            a.sendall((1 << 30).to_bytes(4, "little"))
            with pytest.raises(TransportError, match="frame too large"):
                _recv_frame(b)
        finally:
            a.close()
            b.close()

    def _echo_server(self):
        srv = RPCServer()
        srv.register("Echo", lambda body: body)
        srv.start()
        return srv

    def test_pool_discards_poisoned_conn_after_truncation(self):
        srv = self._echo_server()
        pool = ConnPool(timeout=5.0)
        try:
            assert pool.call(srv.address, "Echo", {"x": 1}) == {"x": 1}
            # One frame send gets truncated: the connection is severed
            # mid-frame.  Whichever side it hits (request or reply), the
            # caller must see TransportError and the pool must NOT
            # re-pool the socket.
            with fault.scenario({"seed": 5, "faults": [
                    {"point": "rpc.send", "action": "truncate",
                     "times": 1}]}):
                with pytest.raises(TransportError):
                    pool.call(srv.address, "Echo", {"x": 2})
            assert all(not bucket for bucket in pool._idle.values()), \
                "poisoned connection re-entered the pool"
            # fresh dial works immediately after the scenario
            assert pool.call(srv.address, "Echo", {"x": 3}) == {"x": 3}
        finally:
            pool.close()
            srv.shutdown()

    def test_pool_discards_conn_after_reply_truncation(self):
        """`after: 1` skips the client's request send so the SERVER's
        reply frame is the one truncated — the client reads EOF mid-frame
        (the `_recv_exact` satellite fix) and the socket is discarded."""
        srv = self._echo_server()
        pool = ConnPool(timeout=5.0)
        try:
            with fault.scenario({"seed": 5, "faults": [
                    {"point": "rpc.send", "action": "truncate",
                     "after": 1, "times": 1}]}):
                with pytest.raises(TransportError):
                    pool.call(srv.address, "Echo", {"x": 2})
            assert all(not bucket for bucket in pool._idle.values())
            assert pool.call(srv.address, "Echo", {"x": 3}) == {"x": 3}
        finally:
            pool.close()
            srv.shutdown()

    def test_delay_is_benign(self):
        srv = self._echo_server()
        pool = ConnPool(timeout=5.0)
        try:
            with fault.scenario({"seed": 9, "faults": [
                    {"point": "rpc.send", "action": "delay", "delay": 0.01,
                     "times": 4}]}):
                for i in range(6):
                    assert pool.call(srv.address, "Echo",
                                     {"i": i}) == {"i": i}
        finally:
            pool.close()
            srv.shutdown()

    def test_dup_is_detected_never_misdelivered(self):
        """A duplicated frame desynchronizes the sequential stream; the
        seq fence must DETECT it (TransportError + connection discard) —
        what must never happen is a stale reply delivered as if it were
        the answer to a later request."""
        srv = self._echo_server()
        pool = ConnPool(timeout=5.0)
        try:
            desyncs = 0
            with fault.scenario({"seed": 9, "faults": [
                    {"point": "rpc.send", "action": "dup", "times": 1}]}):
                for i in range(4):
                    try:
                        assert pool.call(srv.address, "Echo",
                                         {"i": i}) == {"i": i}
                    except TransportError:
                        desyncs += 1
            assert desyncs <= 1
            for i in range(5):
                assert pool.call(srv.address, "Echo", {"i": i}) == {"i": i}
        finally:
            pool.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# leader crash during plan apply
# ---------------------------------------------------------------------------


class TestPlanApplyCrash:
    def test_crash_then_redelivery_loses_no_placements(self):
        """An injected leader crash mid-plan-apply (before the raft
        commit) nacks the eval; the broker redelivers and the replan
        places everything exactly once."""
        srv = Server(ServerConfig(num_schedulers=1))
        # fast redelivery: first nack re-enqueues after initial_nack_delay
        srv.eval_broker.initial_nack_delay = 0.1
        srv.start()
        try:
            for _ in range(3):
                srv.node_register(make_node())
            fault.arm({"seed": 21, "faults": [
                {"point": "plan.apply", "action": "crash", "times": 1}]})
            job = make_job(3)
            _, eval_id = srv.job_register(job)

            # the crash fired exactly once, then the redelivered eval
            # completed with every placement intact
            assert wait_until(
                lambda: srv.state.eval_by_id(None, eval_id).status
                == s.EVAL_STATUS_COMPLETE, timeout=30.0)
            assert fault.trace() == [("plan.apply", 0, "crash")]
            allocs = [a for a in srv.state.allocs_by_job(None, job.id, True)
                      if not a.terminal_status()]
            assert len(allocs) == 3
            assert len({a.id for a in allocs}) == 3
            assert len({a.name for a in allocs}) == 3  # no double-place
        finally:
            srv.shutdown()

    def test_failure_reason_recorded_on_eval(self):
        """A burned delivery attempt leaves WHY on the eval
        (worker.record_eval_failure) — visible to `eval-status` instead
        of only a server-side traceback."""
        srv = Server(ServerConfig(num_schedulers=1))
        srv.eval_broker.initial_nack_delay = 0.1
        srv.start()
        try:
            srv.node_register(make_node())
            fault.arm({"seed": 3, "faults": [
                {"point": "plan.apply", "action": "error",
                 "error": "injected applier fault", "times": 1}]})
            job = make_job(1)
            _, eval_id = srv.job_register(job)
            assert wait_until(
                lambda: "injected applier fault" in (
                    srv.state.eval_by_id(None, eval_id).status_description
                    or ""), timeout=30.0)
            desc = srv.state.eval_by_id(None, eval_id).status_description
            assert "scheduler error on delivery attempt 1" in desc
            # the retry then completes and clears the forensics
            assert wait_until(
                lambda: srv.state.eval_by_id(None, eval_id).status
                == s.EVAL_STATUS_COMPLETE, timeout=30.0)
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# raft: crash at a chosen log index
# ---------------------------------------------------------------------------


class TestRaftApplyFaults:
    def test_crash_at_chosen_index(self):
        """A rule matched on the prospective log index crashes exactly
        that apply; the entry is never persisted and the index is reused
        by the next successful apply."""
        srv = Server(ServerConfig(num_schedulers=0))
        srv.start()
        try:
            fault.arm({"seed": 1, "faults": [
                {"point": "raft.apply", "action": "crash",
                 "match": {"index": 2}}]})
            srv.node_register(make_node())            # index 1: fine
            victim = make_node()
            with pytest.raises(fault.InjectedFault):
                srv.node_register(victim)             # index 2: crashes
            assert srv.state.node_by_id(None, victim.id) is None
            assert srv.raft.applied_index() == 1
            assert fault.trace() == [("raft.apply", 0, "crash")]
            fault.disarm()
            n3 = make_node()
            srv.node_register(n3)                     # index 2 again, ok
            assert srv.state.node_by_id(None, n3.id) is not None
            assert srv.raft.applied_index() == 2
        finally:
            srv.shutdown()

    def test_step_down_surfaces_as_not_leader(self):
        from nomad_tpu.server.raft import NotLeaderError

        srv = Server(ServerConfig(num_schedulers=0))
        srv.start()
        try:
            fault.arm({"seed": 2, "faults": [
                {"point": "raft.apply", "action": "step_down", "times": 1,
                 "match": {"msg_type": "NODE_REGISTER"}}]})
            with pytest.raises(NotLeaderError):
                srv.node_register(make_node())
            fault.disarm()
            n2 = make_node()
            srv.node_register(n2)  # transient: the next apply succeeds
            assert srv.state.node_by_id(None, n2.id) is not None
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# heartbeat blackout → node down → allocs lost → rescheduled
# ---------------------------------------------------------------------------


class TestHeartbeatBlackout:
    def test_blackout_marks_down_loses_allocs_reschedules(self):
        srv = Server(ServerConfig(num_schedulers=1, min_heartbeat_ttl=0.3,
                                  max_heartbeats_per_second=1000.0))
        srv.heartbeat.grace = 0.2
        srv.start()
        stop = threading.Event()
        try:
            nodes = [make_node() for _ in range(2)]
            for n in nodes:
                srv.node_register(n)
                srv.node_update_status(n.id, s.NODE_STATUS_READY)

            def heartbeater():
                """Plays the node agents' heartbeat loop, routed through
                the client-side rpc.send fault point: a dropped frame
                never reaches the server (the real blackout shape) rather
                than arriving and resetting state."""
                while not stop.is_set():
                    for n in nodes:
                        act = fault.faultpoint(
                            "rpc.send", method="Node.UpdateStatus",
                            node_id=n.id, side="client")
                        if act is not None and act.kind == "drop":
                            continue  # frame lost on the wire
                        try:
                            srv.node_update_status(n.id, s.NODE_STATUS_READY)
                        except Exception:
                            pass
                    stop.wait(0.1)

            t = threading.Thread(target=heartbeater, daemon=True)
            t.start()

            job = make_job(1)
            srv.job_register(job)
            assert wait_until(lambda: [
                a for a in srv.state.allocs_by_job(None, job.id, True)
                if not a.terminal_status()], timeout=30.0)
            victim_alloc = [
                a for a in srv.state.allocs_by_job(None, job.id, True)
                if not a.terminal_status()][0]
            victim = victim_alloc.node_id
            other = next(n.id for n in nodes if n.id != victim)

            # blackout: the victim's heartbeats keep being SENT but every
            # frame is dropped on the wire — the TTL runs out server-side
            fault.arm({"seed": 13, "faults": [
                {"point": "rpc.send", "action": "drop",
                 "match": {"node_id": victim}}]})

            assert wait_until(
                lambda: srv.state.node_by_id(None, victim).status
                == s.NODE_STATUS_DOWN, timeout=10.0)

            def recovered():
                allocs = srv.state.allocs_by_job(None, job.id, True)
                lost = [a for a in allocs
                        if a.client_status == s.ALLOC_CLIENT_STATUS_LOST]
                live = [a for a in allocs if not a.terminal_status()
                        and a.client_status != s.ALLOC_CLIENT_STATUS_LOST]
                return (len(lost) == 1 and len(live) == 1
                        and live[0].node_id == other)

            assert wait_until(recovered, timeout=30.0)
        finally:
            stop.set()
            srv.shutdown()


# ---------------------------------------------------------------------------
# nack redelivery after a worker dies mid-eval
# ---------------------------------------------------------------------------


class TestNackRedelivery:
    def test_dead_worker_eval_redelivers_after_nack_timeout(self):
        broker = EvalBroker(nack_timeout=0.25, initial_nack_delay=0.0,
                            delivery_limit=3)
        broker.set_enabled(True)
        ev = mock.eval()
        broker.enqueue(ev)
        got, token = broker.dequeue([ev.type], 1.0)
        assert got.id == ev.id
        assert broker.delivery_attempts(ev.id) == 1
        # the worker holding `token` dies here: no ack, no nack —
        # the nack timer must fire and redeliver
        got2, token2 = broker.dequeue([ev.type], 5.0)
        assert got2 is not None and got2.id == ev.id
        assert token2 != token
        assert broker.delivery_attempts(ev.id) == 2
        broker.ack(ev.id, token2)
        assert broker.stats()["total_ready"] == 0
        assert broker.stats()["total_unacked"] == 0


# ---------------------------------------------------------------------------
# kernel corruption → breaker trips → oracle carries → probe recovers
# ---------------------------------------------------------------------------


class TestKernelOutputValidation:
    """Unit coverage for the structural validator that feeds the
    breaker (ops/batch_sched.validate_device_outputs)."""

    class _SP:
        def __init__(self, count):
            self.count = count

    class _CT:
        n_real = 4

    def _run(self, counts, up, rows, cols, cnt):
        import numpy as np

        from nomad_tpu.ops.batch_sched import validate_device_outputs
        return validate_device_outputs(
            [self._SP(c) for c in counts], self._CT,
            np.asarray(up), np.asarray(rows), np.asarray(cols),
            np.asarray(cnt))

    def test_healthy_output_passes(self):
        assert self._run([2, 1], [0, 0], [0, 0, 1], [1, 2, 3],
                         [1, 1, 1]) is None

    def test_negative_unplaced_rejected(self):
        assert "negative unplaced" in self._run([2], [-3], [], [], [])

    def test_unplaced_exceeding_asks_rejected(self):
        assert "exceeds ask count" in self._run([2], [7], [], [], [])

    def test_negative_node_index_rejected(self):
        assert "negative node index" in self._run(
            [2], [0], [0, 0], [1, -2], [1, 1])

    def test_placed_unplaced_mismatch_rejected(self):
        assert "!=" in self._run([2], [0], [0], [1], [5])


class TestKernelCorruptionBreaker:
    def _run_scenario(self, seed):
        from nomad_tpu.ops.batch_sched import TPUBatchScheduler
        from nomad_tpu.ops.breaker import KernelCircuitBreaker
        from nomad_tpu.scheduler import Harness

        clock = [0.0]
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=5.0, clock=lambda: clock[0])
        h = Harness()
        for _ in range(6):
            node = make_node()
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)

        def batch(n_jobs=2):
            jobs = []
            for _ in range(n_jobs):
                job = make_job(2)
                h.state.upsert_job(h.next_index(), job)
                jobs.append(job)
            evals = [s.Evaluation(
                id=s.generate_uuid(), priority=j.priority, type=j.type,
                triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=j.id,
                status=s.EVAL_STATUS_PENDING) for j in jobs]
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h, breaker=brk)
            stats = sched.schedule_batch(evals)
            placed = all(len([
                a for a in h.state.allocs_by_job(None, j.id, True)
                if not a.terminal_status()]) == 2 for j in jobs)
            return stats, placed

        out = {}
        with fault.scenario({"seed": seed, "faults": [
                {"point": "ops.kernel_result", "action": "corrupt",
                 "times": 1}]}) as plane:
            out["s1"], out["p1"] = batch()
            out["state1"] = brk.state
            out["s2"], out["p2"] = batch()      # breaker open → oracle
            out["state2"] = brk.state
            clock[0] += 10.0                    # past cooldown
            out["s3"], out["p3"] = batch()      # half-open probe, clean
            out["state3"] = brk.state
            out["trace"] = plane.trace()
        return out

    def test_trip_oracle_fallback_and_recovery(self):
        r = self._run_scenario(seed=42)
        # corrupted batch: rejected, fell back to oracle, still placed
        assert r["s1"].kernel_rejects == 1
        assert r["s1"].oracle_routed == 2
        assert r["p1"]
        assert r["state1"] == "open"
        # while open: every eval routed through the oracle, all complete
        assert r["s2"].oracle_routed == 2
        assert r["p2"]
        assert r["state2"] == "open"
        # after cooldown: clean probe closes the breaker, kernel path back
        assert r["s3"].oracle_routed == 0
        assert r["p3"]
        assert r["state3"] == "closed"

    def test_same_seed_same_chaos_trace(self):
        a = self._run_scenario(seed=7)
        b = self._run_scenario(seed=7)
        assert a["trace"] == b["trace"] == [
            ("ops.kernel_result", 0, "corrupt")]
        assert (a["state1"], a["state2"], a["state3"]) == \
               (b["state1"], b["state2"], b["state3"])

    def test_unresolved_probe_expires_and_regrants(self):
        """A probe batch that dies without resolving must not wedge the
        breaker half-open forever: after another cooldown a new probe is
        granted."""
        from nomad_tpu.ops.breaker import KernelCircuitBreaker

        clock = [0.0]
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=5.0, clock=lambda: clock[0])
        brk.record(False)
        assert brk.state == "open"
        clock[0] = 6.0
        assert brk.allow_kernel()       # half-open probe granted
        assert brk.state == "half-open"
        assert not brk.allow_kernel()   # concurrent batch stays on oracle
        clock[0] = 12.0                 # probe never resolved → expired
        assert brk.allow_kernel()       # fresh probe granted
        brk.on_probe(True)
        assert brk.state == "closed"

    def test_probe_device_exception_resolves_probe(self, monkeypatch):
        """A raw device error (not an integrity rejection) during the
        probe batch must re-open the breaker, not strand it half-open."""
        from nomad_tpu.ops.batch_sched import TPUBatchScheduler
        from nomad_tpu.ops.breaker import KernelCircuitBreaker
        from nomad_tpu.scheduler import Harness

        clock = [0.0]
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=5.0, clock=lambda: clock[0])
        brk.record(False)               # tripped open
        clock[0] = 6.0                  # next batch is the probe
        h = Harness()
        for _ in range(3):
            node = make_node()
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)
        job = make_job(1)
        h.state.upsert_job(h.next_index(), job)
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            status=s.EVAL_STATUS_PENDING)
        sched = TPUBatchScheduler(h.logger, h.snapshot(), h, breaker=brk)

        def blow_up(spec_list):
            raise RuntimeError("xla device died")

        # The split batch pipeline dispatches and fetches separately; a
        # raw device error can surface at either stage and both must
        # resolve the probe.  Dispatch-stage here; fetch-stage below.
        monkeypatch.setattr(sched, "_dispatch_device", blow_up)
        with pytest.raises(RuntimeError, match="xla device died"):
            sched.schedule_batch([ev])
        assert brk.state == "open"      # probe resolved dirty, not wedged

        clock[0] += 6.0                 # past cooldown: probe again
        monkeypatch.setattr(sched, "_dispatch_device",
                            lambda spec_list: {"fetch": "boom"})
        monkeypatch.setattr(
            sched, "_fetch_device",
            lambda handle: (_ for _ in ()).throw(
                RuntimeError("xla device died on fetch")))
        with pytest.raises(RuntimeError, match="xla device died on fetch"):
            sched.schedule_batch([ev])
        assert brk.state == "open"

    def test_breaker_trips_through_real_batch_worker(self, monkeypatch,
                                                     tmp_path):
        """End-to-end through Server + BatchWorker: a corrupted kernel
        batch trips the process-wide breaker; later jobs complete via the
        oracle while open; the breaker probes closed after cooldown. With
        the flight recorder armed, the trip auto-captures exactly one
        rate-limited bundle."""
        import json

        from nomad_tpu.ops import breaker as breaker_mod
        from nomad_tpu.utils import blackbox

        monkeypatch.setenv("NOMAD_TPU_BREAKER_MIN_CHECKS", "1")
        monkeypatch.setenv("NOMAD_TPU_BREAKER_COOLDOWN", "0.5")
        breaker_mod.reset_for_tests()
        blackbox.enable(directory=str(tmp_path), min_interval_s=300.0)
        srv = Server(ServerConfig(num_schedulers=1,
                                  use_tpu_batch_worker=True, batch_size=8))
        srv.start()
        try:
            for _ in range(4):
                srv.node_register(make_node())
            fault.arm({"seed": 33, "faults": [
                {"point": "ops.kernel_result", "action": "corrupt",
                 "times": 1}]})
            job1 = make_job(2)
            srv.job_register(job1)
            assert wait_until(lambda: len([
                a for a in srv.state.allocs_by_job(None, job1.id, True)
                if not a.terminal_status()]) == 2, timeout=60.0)
            assert breaker_mod.BREAKER.trips >= 1
            # while open/after: scheduling keeps working
            job2 = make_job(2)
            srv.job_register(job2)
            assert wait_until(lambda: len([
                a for a in srv.state.allocs_by_job(None, job2.id, True)
                if not a.terminal_status()]) == 2, timeout=60.0)
            # cooldown passes; a probe batch restores the kernel path
            time.sleep(0.6)
            job3 = make_job(2)
            srv.job_register(job3)
            assert wait_until(lambda: len([
                a for a in srv.state.allocs_by_job(None, job3.id, True)
                if not a.terminal_status()]) == 2, timeout=60.0)
            assert wait_until(
                lambda: breaker_mod.BREAKER.state == "closed", timeout=30.0)
            # The trip auto-captured a flight-recorder bundle (capture is
            # async on a daemon thread; wait for it to land on disk).
            assert wait_until(lambda: len(blackbox.bundles()) >= 1,
                              timeout=10.0)
            assert len(blackbox.bundles()) == 1, blackbox.bundles()
            with open(blackbox.bundles()[0], encoding="utf-8") as fh:
                bundle = json.load(fh)
            assert bundle["Reason"] == "breaker.open"
            assert bundle["Detail"]["Trips"] >= 1
            for key in ("Spans", "Events", "Profile", "Locks", "Threads",
                        "Servers", "Breaker", "Knobs"):
                assert key in bundle, key
            assert any(sv["Name"] == srv.config.node_name
                       for sv in bundle["Servers"])
            # A second trigger for the same reason inside the min
            # interval is suppressed by the limiter.
            blackbox.note_trigger("breaker.open", {"Trips": 99})
            time.sleep(0.3)
            assert len(blackbox.bundles()) == 1
        finally:
            blackbox.disable()
            srv.shutdown()
            monkeypatch.delenv("NOMAD_TPU_BREAKER_MIN_CHECKS")
            monkeypatch.delenv("NOMAD_TPU_BREAKER_COOLDOWN")
            breaker_mod.reset_for_tests()


# ---------------------------------------------------------------------------
# deep probabilistic sweep (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestDeepRPCSweep:
    @pytest.mark.parametrize("seed", [101, 202, 303])
    def test_lossy_wire_never_wedges_the_server(self, seed):
        """Probabilistic drop/dup/delay/truncate over a real RPC server:
        every call either succeeds or fails with a classified RPC error,
        and the server keeps answering cleanly after the storm."""
        from nomad_tpu.server.rpc import RPCError

        srv = RPCServer()
        srv.register("Echo", lambda body: body)
        srv.start()
        pool = ConnPool(timeout=0.5)
        try:
            ok = failed = 0
            with fault.scenario({"seed": seed, "faults": [
                    {"point": "rpc.send", "action": "truncate",
                     "prob": 0.10},
                    {"point": "rpc.send", "action": "dup", "prob": 0.10},
                    {"point": "rpc.send", "action": "delay",
                     "delay": 0.005, "prob": 0.10}]}):
                for i in range(120):
                    try:
                        assert pool.call(srv.address, "Echo",
                                         {"i": i}) == {"i": i}
                        ok += 1
                    except (RPCError, OSError):
                        failed += 1
            assert ok > 0 and failed > 0  # the storm was real, not fatal
            # A dup that fired on the storm's LAST successful call can
            # leave its stale extra reply buffered in a released conn;
            # the first post-storm use would detect the desync and
            # discard it.  Drop all idle conns so the post-storm check
            # exercises fresh connections only.
            pool.close()
            for i in range(10):
                assert pool.call(srv.address, "Echo", {"i": i}) == {"i": i}
        finally:
            pool.close()
            srv.shutdown()
