"""mTLS RPC tests (reference: helper/tlsutil region-wrapped mutual TLS):
servers demand CA-signed client certs; dialers verify the server against
the cluster CA; plaintext and wrong-CA peers are rejected."""
import subprocess
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.codec import to_wire
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool, RPCError
from nomad_tpu.utils.tlsutil import TLSConfig, client_context


def wait_until(pred, timeout=30.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def make_ca(dir_path, name="nomad-ca"):
    ca_key = dir_path / f"{name}.key"
    ca_crt = dir_path / f"{name}.crt"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "2",
         "-subj", f"/CN={name}"], check=True, capture_output=True)
    return ca_key, ca_crt


def issue_cert(dir_path, ca_key, ca_crt, cn):
    key = dir_path / f"{cn}.key"
    csr = dir_path / f"{cn}.csr"
    crt = dir_path / f"{cn}.crt"
    subprocess.run(
        ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={cn}"],
        check=True, capture_output=True)
    subprocess.run(
        ["openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
         "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
         "-days", "2"], check=True, capture_output=True)
    return key, crt


@pytest.fixture()
def pki(tmp_path):
    ca_key, ca_crt = make_ca(tmp_path)
    s_key, s_crt = issue_cert(tmp_path, ca_key, ca_crt, "server.global.nomad")
    c_key, c_crt = issue_cert(tmp_path, ca_key, ca_crt, "client.global.nomad")
    return {"ca": ca_crt, "server": (s_crt, s_key), "client": (c_crt, c_key),
            "dir": tmp_path}


def tls_server_config(pki, **kw):
    crt, key = pki["server"]
    return ServerConfig(
        enable_rpc=True,
        tls=TLSConfig(enabled=True, ca_file=str(pki["ca"]),
                      cert_file=str(crt), key_file=str(key)),
        **kw)


class TestMutualTLS:
    def test_rpc_over_mtls(self, pki):
        srv = Server(tls_server_config(pki, num_schedulers=0))
        srv.start()
        try:
            crt, key = pki["client"]
            pool = ConnPool(tls_context=client_context(TLSConfig(
                enabled=True, ca_file=str(pki["ca"]),
                cert_file=str(crt), key_file=str(key))))
            job = mock.job()
            for t in job.task_groups[0].tasks:
                t.resources.networks = []
            reply = pool.call(srv.config.rpc_advertise, "Job.Register",
                              {"Job": to_wire(job)})
            assert reply["Index"] > 0
            assert srv.state.job_by_id(None, job.id) is not None
            pool.close()
        finally:
            srv.shutdown()

    def test_plaintext_client_rejected(self, pki):
        srv = Server(tls_server_config(pki, num_schedulers=0))
        srv.start()
        try:
            pool = ConnPool()  # no TLS
            with pytest.raises(RPCError):
                pool.call(srv.config.rpc_advertise, "Status.Ping", {},
                          timeout=3.0)
        finally:
            srv.shutdown()

    def test_wrong_ca_client_rejected(self, pki, tmp_path):
        srv = Server(tls_server_config(pki, num_schedulers=0))
        srv.start()
        try:
            rogue_dir = tmp_path / "rogue"
            rogue_dir.mkdir()
            r_ca_key, r_ca_crt = make_ca(rogue_dir, "rogue-ca")
            r_key, r_crt = issue_cert(rogue_dir, r_ca_key, r_ca_crt,
                                      "intruder")
            pool = ConnPool(tls_context=client_context(TLSConfig(
                enabled=True, ca_file=str(r_ca_crt),
                cert_file=str(r_crt), key_file=str(r_key))))
            with pytest.raises(RPCError):
                pool.call(srv.config.rpc_advertise, "Status.Ping", {},
                          timeout=3.0)
        finally:
            srv.shutdown()

    def test_mtls_cluster_replicates(self, pki, tmp_path):
        """A 3-server raft cluster where every server↔server connection
        (gossip + raft channel) runs over mutual TLS."""
        crt, key = pki["server"]
        tls = TLSConfig(enabled=True, ca_file=str(pki["ca"]),
                        cert_file=str(crt), key_file=str(key))
        servers = []
        first = None
        for i in range(3):
            cfg = ServerConfig(
                node_name=f"tls-{i}", enable_rpc=True, tls=tls,
                data_dir=str(tmp_path / f"s{i}"), bootstrap_expect=3,
                start_join=[first] if first else [], num_schedulers=0)
            srv = Server(cfg)
            if first is None:
                first = srv.config.rpc_advertise
            servers.append(srv)
        for srv in servers:
            srv.start()
        try:
            assert wait_until(lambda: any(
                srv.is_leader() for srv in servers), 30.0), \
                "no leader over mTLS"
            leader = next(srv for srv in servers if srv.is_leader())
            job = mock.job()
            for t in job.task_groups[0].tasks:
                t.resources.networks = []
            leader.job_register(job)
            assert wait_until(lambda: all(
                srv.state.job_by_id(None, job.id) is not None
                for srv in servers), 10.0), "replication over mTLS failed"
        finally:
            for srv in servers:
                srv.shutdown()
