"""Native group-commit WAL (nomad_tpu/native/wal.cc) and its FileLog
integration: CRC framing, torn/corrupt-tail recovery, concurrent append
durability, and mixed native/legacy replay ordering."""

import os
import threading

import pytest

from nomad_tpu.native import NativeWAL, native_wal_available

pytestmark = pytest.mark.skipif(
    not native_wal_available(), reason="native toolchain unavailable")


class TestNativeWAL:
    def test_append_replay(self, tmp_path):
        p = str(tmp_path / "wal.crc")
        w = NativeWAL(p)
        for i in range(50):
            w.append(f"r{i}".encode())
        assert len(w) == 50
        w.close()

        w2 = NativeWAL(p)
        recs = list(w2.records())
        assert len(recs) == 50
        assert recs[0] == b"r0" and recs[-1] == b"r49"
        w2.close()

    def test_torn_tail_truncated(self, tmp_path):
        p = str(tmp_path / "wal.crc")
        w = NativeWAL(p)
        w.append(b"good-1")
        w.append(b"good-2")
        w.close()
        # Crash mid-write: a length prefix claiming more than exists.
        with open(p, "ab") as fh:
            fh.write(b"\xff\xff\x00\x00garbage")
        w2 = NativeWAL(p)
        assert list(w2.records()) == [b"good-1", b"good-2"]
        # Appends after recovery land cleanly after the truncation point.
        w2.append(b"good-3")
        w2.close()
        w3 = NativeWAL(p)
        assert list(w3.records()) == [b"good-1", b"good-2", b"good-3"]
        w3.close()

    def test_corrupt_crc_truncated(self, tmp_path):
        p = str(tmp_path / "wal.crc")
        w = NativeWAL(p)
        w.append(b"alpha")
        w.append(b"beta")
        w.close()
        # Flip a payload byte of the LAST record: CRC must reject it.
        size = os.path.getsize(p)
        with open(p, "r+b") as fh:
            fh.seek(size - 1)
            last = fh.read(1)
            fh.seek(size - 1)
            fh.write(bytes([last[0] ^ 0xFF]))
        w2 = NativeWAL(p)
        assert list(w2.records()) == [b"alpha"]
        w2.close()

    def test_concurrent_appends_all_durable(self, tmp_path):
        p = str(tmp_path / "wal.crc")
        w = NativeWAL(p)
        n_threads, per = 8, 100

        def worker(k):
            for i in range(per):
                w.append(f"t{k}-{i}".encode())

        ts = [threading.Thread(target=worker, args=(k,))
              for k in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(w) == n_threads * per
        w.close()
        w2 = NativeWAL(p)
        recs = list(w2.records())
        assert len(recs) == n_threads * per
        # Every thread's records appear, in that thread's order.
        for k in range(n_threads):
            mine = [r for r in recs if r.startswith(f"t{k}-".encode())]
            assert mine == [f"t{k}-{i}".encode() for i in range(per)]
        w2.close()

    def test_write_then_sync_split_api(self, tmp_path):
        """The raft log's two-phase path: write() buffers in order,
        sync_to() group-commits; records are durable and replayable."""
        p = str(tmp_path / "wal.crc")
        w = NativeWAL(p)
        seqs = [w.write(f"s{i}".encode()) for i in range(20)]
        assert seqs == list(range(1, 21))
        w.sync_to(seqs[-1])  # one fsync covers the whole batch
        w.close()
        w2 = NativeWAL(p)
        assert list(w2.records()) == [f"s{i}".encode() for i in range(20)]
        w2.close()

    def test_reset(self, tmp_path):
        p = str(tmp_path / "wal.crc")
        w = NativeWAL(p)
        w.append(b"x")
        w.reset()
        assert len(w) == 0
        w.append(b"y")
        w.close()
        w2 = NativeWAL(p)
        assert list(w2.records()) == [b"y"]
        w2.close()


class TestFileLogNative:
    def _mk(self, data_dir):
        from nomad_tpu.server.fsm import FSM, MessageType
        from nomad_tpu.server.raft import FileLog

        fsm = FSM()
        return FileLog(fsm, data_dir), MessageType

    def test_native_wal_used_and_replayed(self, tmp_path):
        from nomad_tpu import mock

        data_dir = str(tmp_path / "raft")
        log, MT = self._mk(data_dir)
        assert log._nwal is not None, "native WAL should be active"
        node = mock.node()
        log.apply(MT.NODE_REGISTER, {"node": node})
        log.close()
        assert os.path.getsize(os.path.join(data_dir, "wal.crc")) > 0

        log2, _ = self._mk(data_dir)
        assert log2.fsm.state.node_by_id(None, node.id) is not None
        log2.close()

    def test_native_torn_tail(self, tmp_path):
        from nomad_tpu import mock

        data_dir = str(tmp_path / "raft")
        log, MT = self._mk(data_dir)
        node = mock.node()
        log.apply(MT.NODE_REGISTER, {"node": node})
        applied = log.applied_index()
        log.close()

        with open(os.path.join(data_dir, "wal.crc"), "ab") as fh:
            fh.write(b"\x99\x00\x00\x00partial-record")

        log2, MT = self._mk(data_dir)
        assert log2.applied_index() == applied
        job = mock.job()
        log2.apply(MT.JOB_REGISTER, {"job": job})
        applied2 = log2.applied_index()
        log2.close()

        log3, _ = self._mk(data_dir)
        assert log3.applied_index() == applied2
        assert log3.fsm.state.job_by_id(None, job.id) is not None
        log3.close()

    def test_mixed_legacy_then_native_replays_in_order(self, tmp_path,
                                                       monkeypatch):
        """Entries written by the pure-Python fallback replay together
        with (and before) later native entries."""
        from nomad_tpu import mock

        data_dir = str(tmp_path / "raft")
        monkeypatch.setenv("NOMAD_TPU_NO_NATIVE", "1")
        log, MT = self._mk(data_dir)
        assert log._nwal is None
        node = mock.node()
        log.apply(MT.NODE_REGISTER, {"node": node})
        log.close()

        monkeypatch.delenv("NOMAD_TPU_NO_NATIVE")
        log2, MT = self._mk(data_dir)
        assert log2._nwal is not None
        assert log2.fsm.state.node_by_id(None, node.id) is not None
        job = mock.job()
        log2.apply(MT.JOB_REGISTER, {"job": job})
        applied = log2.applied_index()
        log2.close()

        log3, _ = self._mk(data_dir)
        assert log3.applied_index() == applied
        assert log3.fsm.state.node_by_id(None, node.id) is not None
        assert log3.fsm.state.job_by_id(None, job.id) is not None
        log3.close()

    def test_native_entries_survive_native_unavailable_boot(self, tmp_path,
                                                            monkeypatch):
        """A wal.crc written natively must replay through the pure-Python
        CRC reader when the toolchain disappears — silently ignoring it
        would roll back committed entries."""
        from nomad_tpu import mock

        data_dir = str(tmp_path / "raft")
        log, MT = self._mk(data_dir)
        assert log._nwal is not None
        node = mock.node()
        log.apply(MT.NODE_REGISTER, {"node": node})
        applied = log.applied_index()
        log.close()

        monkeypatch.setenv("NOMAD_TPU_NO_NATIVE", "1")
        log2, MT = self._mk(data_dir)
        assert log2._nwal is None
        assert log2.applied_index() == applied
        assert log2.fsm.state.node_by_id(None, node.id) is not None
        # New entries append to the legacy log with fresh indexes.
        job = mock.job()
        log2.apply(MT.JOB_REGISTER, {"job": job})
        applied2 = log2.applied_index()
        assert applied2 > applied
        log2.close()

        # Back on native: both files replay, in index order, no dups.
        monkeypatch.delenv("NOMAD_TPU_NO_NATIVE")
        log3, _ = self._mk(data_dir)
        assert log3.applied_index() == applied2
        assert log3.fsm.state.node_by_id(None, node.id) is not None
        assert log3.fsm.state.job_by_id(None, job.id) is not None
        log3.close()

    def test_failed_fsm_apply_does_not_wedge_the_sequencer(self, tmp_path):
        """An FSM apply that raises (deregister of an unknown node)
        propagates to its caller but must not wedge the apply sequencer
        for every later entry."""
        from nomad_tpu import mock

        log, MT = self._mk(str(tmp_path / "raft"))
        with pytest.raises(KeyError):
            log.apply(MT.NODE_DEREGISTER, {"node_id": "no-such-node"})
        node = mock.node()
        log.apply(MT.NODE_REGISTER, {"node": node})  # must not block
        assert log.fsm.state.node_by_id(None, node.id) is not None
        log.snapshot()  # drain loop must not spin either
        log.close()

    def test_concurrent_applies_group_commit_durable(self, tmp_path):
        """Concurrent raft appliers overlap their durability waits (the
        fsync happens OUTSIDE the apply lock); every acked entry must
        survive a reopen, in index order with no gaps."""
        import threading

        from nomad_tpu import mock

        data_dir = str(tmp_path / "raft")
        log, MT = self._mk(data_dir)
        n_threads, per = 6, 20

        def worker(k):
            for _ in range(per):
                log.apply(MT.NODE_REGISTER, {"node": mock.node()})

        ts = [threading.Thread(target=worker, args=(k,))
              for k in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        applied = log.applied_index()
        assert applied == n_threads * per
        log.close()

        log2, _ = self._mk(data_dir)
        assert log2.applied_index() == applied
        assert len(log2.fsm.state.nodes(None)) == n_threads * per
        log2.close()

    def test_concurrent_applies_durable_python_fallback(self, tmp_path,
                                                        monkeypatch):
        """Same guarantee through the pure-Python group-commit twin."""
        import threading

        from nomad_tpu import mock

        monkeypatch.setenv("NOMAD_TPU_NO_NATIVE", "1")
        data_dir = str(tmp_path / "raft")
        log, MT = self._mk(data_dir)
        assert log._nwal is None

        def worker(k):
            for _ in range(15):
                log.apply(MT.NODE_REGISTER, {"node": mock.node()})

        ts = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        applied = log.applied_index()
        assert applied == 60
        log.close()

        log2, _ = self._mk(data_dir)
        assert log2.applied_index() == applied
        log2.close()

    def test_snapshot_truncates_both_logs(self, tmp_path):
        from nomad_tpu import mock

        data_dir = str(tmp_path / "raft")
        log, MT = self._mk(data_dir)
        log.apply(MT.NODE_REGISTER, {"node": mock.node()})
        log.snapshot()
        assert os.path.getsize(os.path.join(data_dir, "wal.crc")) == 0
        applied = log.applied_index()
        log.close()

        log2, _ = self._mk(data_dir)
        assert log2.applied_index() == applied
        assert len(log2.fsm.state.nodes(None)) == 1
        log2.close()
