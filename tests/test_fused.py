"""Fused on-device score-and-commit tests (PR 6 tentpole).

The fused single-dispatch program (kernels.fused_pass) must be
BIT-IDENTICAL to the two-phase schedule/compact split it replaces —
asserted end-to-end under a pinned tie-break seed (NOMAD_TPU_RNG_SEED)
across randomized clusters/jobs — and the CPU GenericScheduler oracle
must agree on per-job placement counts with no node overcommitted
(scores stay within the quantization bound, which is 0: quantization is
exact-or-absent).  Plus: the single-transfer contract (exactly one
``batch.fetch`` span per fused batch), the narrow-dtype xfer codec, the
quantizer's exactness guarantees, and the chaos path — a corrupted
fused result buffer trips the breaker, the oracle carries the batch,
and a clean half-open probe restores the fused path.
"""
import random

import numpy as np
import pytest

from nomad_tpu import fault, mock
from nomad_tpu.ops import encode, resident, xfer
from nomad_tpu.ops.batch_sched import TPUBatchScheduler
from nomad_tpu.ops.breaker import KernelCircuitBreaker
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.generic import GenericScheduler
from nomad_tpu.structs import structs as s
from nomad_tpu.utils import tracing


def make_node(rng=None):
    node = mock.node()
    node.resources.networks = []
    node.reserved.networks = []
    if rng is not None:
        node.resources.cpu = rng.choice([2000, 4000, 8000])
        node.resources.memory_mb = rng.choice([4096, 8192, 16384])
    node.compute_class()
    return node


def make_job(count, rng=None):
    job = mock.job()
    job.task_groups[0].count = count
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.networks = []
            if rng is not None:
                t.resources.cpu = rng.choice([100, 250, 500])
                t.resources.memory_mb = rng.choice([64, 256, 512])
    return job


def reg_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def build_twin_problem(seed, n_nodes=24, n_jobs=4):
    """Two harnesses over identical fleets + identical jobs (shared job
    objects are immutable snapshots by store convention)."""
    rng = random.Random(seed)
    nodes = [make_node(rng) for _ in range(n_nodes)]
    jobs = [make_job(rng.randint(1, 4), rng) for _ in range(n_jobs)]
    harnesses = []
    for _ in range(2):
        h = Harness()
        for node in nodes:
            h.state.upsert_node(h.next_index(), node.copy())
        for job in jobs:
            h.state.upsert_job(h.next_index(), job)
        harnesses.append(h)
    return harnesses[0], harnesses[1], jobs


def placements_by_spec(h, jobs):
    """(job, tg) → sorted node ids of live allocs (the bit-identity
    comparison basis: same kernel ⇒ same multiset of slots)."""
    out = {}
    for job in jobs:
        for a in h.state.allocs_by_job(None, job.id, True):
            if a.terminal_status():
                continue
            out.setdefault((job.id, a.task_group), []).append(a.node_id)
    return {k: sorted(v) for k, v in out.items()}


def node_usage(h):
    used = {}
    for node in h.state.nodes(None):
        cpu = mem = 0
        for a in h.state.allocs_by_node(None, node.id):
            if a.terminal_status():
                continue
            if a.resources is not None:
                cpu += a.resources.cpu
                mem += a.resources.memory_mb
            else:
                cpu += sum(t.cpu for t in a.task_resources.values())
                mem += sum(t.memory_mb for t in a.task_resources.values())
        used[node.id] = (cpu, mem, node.resources.cpu,
                         node.resources.memory_mb)
    return used


def run_batch(h, jobs, fused, monkeypatch, seed=1234, breaker=None):
    monkeypatch.setenv("NOMAD_TPU_FUSED", "1" if fused else "0")
    monkeypatch.setenv("NOMAD_TPU_RNG_SEED", str(seed))
    for j in jobs:
        if h.state.job_by_id(None, j.id) is None:
            h.state.upsert_job(h.next_index(), j)
    kw = {"breaker": breaker} if breaker is not None else {}
    sched = TPUBatchScheduler(h.logger, h.snapshot(), h, **kw)
    return sched.schedule_batch([reg_eval(j) for j in jobs])


# -- xfer narrow dtypes -------------------------------------------------------

class TestXferNarrowDtypes:
    def test_host_roundtrip(self):
        arrays = {
            "a16": np.arange(-6, 6, dtype=np.int16).reshape(3, 4),
            "u16": np.array([0, 1, 65535], dtype=np.uint16),
            "a8": np.arange(-8, 8, dtype=np.int8),
            "mix32": np.arange(5, dtype=np.int32),
            "f": np.linspace(0, 1, 7, dtype=np.float32),
        }
        buf, meta = xfer.pack_host(arrays)
        out = xfer.unpack_host(buf, meta)
        for name, arr in arrays.items():
            np.testing.assert_array_equal(out[name], arr)

    def test_device_unpack_matches_host(self):
        import jax
        import jax.numpy as jnp

        arrays = {
            "q": np.array([[1, -2], [32767, -32768]], dtype=np.int16),
            "b": np.array([7, 250], dtype=np.uint16),
            "s": np.array([-128, 127, 3], dtype=np.int8),
        }
        buf, meta = xfer.pack_host(arrays)
        dev = jax.jit(
            lambda b: tuple(xfer.unpack_device(b, meta).values()))(
                jnp.asarray(buf))
        names = [m[0] for m in meta]
        for name, arr in zip(names, dev):
            np.testing.assert_array_equal(np.asarray(arr), arrays[name])
            assert np.asarray(arr).dtype == arrays[name].dtype

    def test_device_pack_roundtrip(self):
        import jax
        import jax.numpy as jnp

        arrays = {
            "slots": np.arange(12, dtype=np.uint16).reshape(2, 6),
            "sum": np.array([3, 9], dtype=np.int32),
        }

        @jax.jit
        def pack():
            buf, _ = xfer.pack_device(
                {k: jnp.asarray(v) for k, v in arrays.items()})
            return buf

        meta = xfer.layout({k: (xfer._tag(v.dtype), v.shape)
                            for k, v in arrays.items()})
        out = xfer.unpack_host(np.asarray(pack()), meta)
        for name, arr in arrays.items():
            np.testing.assert_array_equal(out[name], arr)


# -- quantizer ----------------------------------------------------------------

class TestQuantizeResourceRows:
    def test_int8_via_per_dim_scales(self):
        # ISSUE 13: every dimension here divides down into the int8
        # range (4000/32, 8192/128, 102400/1024, 150/2), so BOTH
        # matrices ship int8 under per-matrix, per-dimension scales —
        # this exact shape used to ride int16 under the shared codebook.
        cap = np.tile(np.array([4000, 8192, 102400, 150]), (16, 1))
        used = np.tile(np.array([120, 512, 0, 0]), (16, 1))
        q = encode.quantize_resource_rows(cap, used)
        assert q is not None and q.cap_tag == "i8" and q.used_tag == "i8"
        assert q.tag == "i8"
        assert q.scale.shape == (2, 4)
        assert q.scale[0].tolist() == [32, 128, 1024, 2]
        np.testing.assert_array_equal(
            encode.dequantize_rows(q.cap_q, q.scale[0]), cap)
        np.testing.assert_array_equal(
            encode.dequantize_rows(q.used_q, q.scale[1]), used)

    def test_int16_when_int8_divisibility_fails(self):
        # disk (102404) divides by 4 (int16 range) but not by the 1024
        # the int8 range needs → that dimension stays int16-scaled and
        # the capacity matrix ships int16; the all-zero used matrix
        # still rides int8 independently (per-matrix dtypes).
        cap = np.tile(np.array([4000, 8192, 102404, 150]), (16, 1))
        used = np.zeros((16, 4), dtype=np.int64)
        q = encode.quantize_resource_rows(cap, used)
        assert q is not None and q.cap_tag == "i16" and q.used_tag == "i8"
        assert q.tag == "i16"
        assert q.scale[0].tolist() == [32, 128, 4, 2]
        np.testing.assert_array_equal(
            encode.dequantize_rows(q.cap_q, q.scale[0]), cap)
        np.testing.assert_array_equal(
            encode.dequantize_rows(q.used_q, q.scale[1]), used)

    def test_int8_when_ranges_allow(self):
        cap = np.tile(np.array([100, 120, 64, 50]), (4, 1))
        used = np.zeros((4, 4), dtype=np.int64)
        q = encode.quantize_resource_rows(cap, used)
        assert q is not None and q.tag == "i8"
        np.testing.assert_array_equal(
            encode.dequantize_rows(q.cap_q, q.scale[0]), cap)

    def test_non_divisible_refuses(self):
        # 100001 needs scale 4 but is odd — exactness impossible, so the
        # quantizer must refuse rather than round.
        cap = np.tile(np.array([4000, 8192, 100001, 150]), (4, 1))
        used = np.zeros((4, 4), dtype=np.int64)
        assert encode.quantize_resource_rows(cap, used) is None

    def test_roundtrip_guard_catches_corruption(self):
        resident.reset_counters()
        cap = np.tile(np.array([4000, 8192, 102400, 150]), (8, 1))
        q = encode.quantize_resource_rows(cap, np.zeros_like(cap))
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=3600.0)
        assert resident.check_quant_roundtrip(cap, q.cap_q, q.scale[0],
                                              breaker=brk)
        bad = np.array(q.cap_q)
        bad[2, 1] += 3
        assert not resident.check_quant_roundtrip(cap, bad, q.scale[0],
                                                  breaker=brk)
        assert resident.QUANT_MISMATCHES == 1
        assert brk.agreement() < 1.0
        resident.reset_counters()


# -- fused vs two-phase vs oracle --------------------------------------------

class TestFusedParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 7])
    def test_fused_vs_two_phase_bit_identical(self, seed, monkeypatch):
        """Identical problem + pinned tie-break seed ⇒ the fused and
        two-phase programs place the identical (job, tg) → node
        multiset and report identical unplaced counts."""
        h_f, h_t, jobs = build_twin_problem(seed)
        st_f = run_batch(h_f, jobs, fused=True, monkeypatch=monkeypatch)
        st_t = run_batch(h_t, jobs, fused=False, monkeypatch=monkeypatch)
        assert st_f.fused == 1 and st_t.fused == 0
        assert placements_by_spec(h_f, jobs) == placements_by_spec(
            h_t, jobs)

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_fused_vs_cpu_oracle_fuzz(self, seed, monkeypatch):
        """Oracle parity: per-job placed counts equal, nothing
        overcommitted on either side (scores are within the
        quantization bound by construction — the bound is 0)."""
        h_f, h_o, jobs = build_twin_problem(seed, n_nodes=16, n_jobs=3)
        run_batch(h_f, jobs, fused=True, monkeypatch=monkeypatch)
        for job in jobs:
            GenericScheduler(h_o.logger, h_o.snapshot(), h_o,
                             batch=False).process(reg_eval(job))
        for job in jobs:
            live_f = [a for a in h_f.state.allocs_by_job(None, job.id,
                                                         True)
                      if not a.terminal_status()]
            live_o = [a for a in h_o.state.allocs_by_job(None, job.id,
                                                         True)
                      if not a.terminal_status()]
            assert len(live_f) == len(live_o), job.id
        for h in (h_f, h_o):
            for nid, (cpu, mem, cap_cpu, cap_mem) in node_usage(h).items():
                assert cpu <= cap_cpu and mem <= cap_mem, nid

    def test_multi_round_same_node_scores_stay_bounded(self, monkeypatch):
        """A spec committing to the SAME node across several capacity-
        feedback rounds (1-node cluster, count 3) must keep ONE binpack
        metric entry per node with the last commit's score — per-alloc
        slot entries must not SUM into a >18 pseudo-score."""
        h = Harness()
        node = make_node()
        h.state.upsert_node(h.next_index(), node)
        job = make_job(3)
        stats = run_batch(h, [job], fused=True, monkeypatch=monkeypatch)
        live = [a for a in h.state.allocs_by_job(None, job.id, True)
                if not a.terminal_status()]
        assert len(live) == 3 and stats.rounds == 3
        scores = live[0].metrics.scores
        binpack = scores.get(f"{node.id}.binpack")
        assert binpack is not None and 0.0 <= binpack <= 18.0, scores

    def test_quant_kill_switch_beats_memo(self, monkeypatch):
        """NOMAD_TPU_QUANT=0 must take effect immediately even when the
        cached static encode memoized quantized rows while it was on."""
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())
        monkeypatch.setenv("NOMAD_TPU_QUANT", "1")
        st1 = run_batch(h, [make_job(1)], fused=True,
                        monkeypatch=monkeypatch)
        assert st1.quantized == 1
        monkeypatch.setenv("NOMAD_TPU_QUANT", "0")
        st2 = run_batch(h, [make_job(1)], fused=True,
                        monkeypatch=monkeypatch)
        assert st2.quantized == 0

    def test_quantized_rows_active_and_exact(self, monkeypatch):
        """The mock fleet's resource rows quantize (disk needs a scale),
        the batch reports it, and placements still match the unquantized
        run bit-for-bit."""
        h_q, h_x, jobs = build_twin_problem(21)
        monkeypatch.setenv("NOMAD_TPU_QUANT", "1")
        st_q = run_batch(h_q, jobs, fused=True, monkeypatch=monkeypatch)
        monkeypatch.setenv("NOMAD_TPU_QUANT", "0")
        st_x = run_batch(h_x, jobs, fused=True, monkeypatch=monkeypatch)
        assert st_q.quantized == 1 and st_x.quantized == 0
        assert placements_by_spec(h_q, jobs) == placements_by_spec(
            h_x, jobs)


# -- the single-transfer contract --------------------------------------------

class TestSingleFetch:
    def test_exactly_one_fetch_span_per_fused_batch(self, monkeypatch):
        h_f, _h, jobs = build_twin_problem(31)
        tracing.enable()
        try:
            monkeypatch.setenv("NOMAD_TPU_FUSED", "1")
            sched = TPUBatchScheduler(h_f.logger, h_f.snapshot(), h_f)
            evals = [reg_eval(j) for j in jobs]
            stats = sched.schedule_batch(evals)
            spans = tracing.trace_for_eval(evals[0].id)
        finally:
            tracing.disable()
        assert stats.fused == 1
        fetches = [sp for sp in spans if sp["Name"] == "batch.fetch"]
        assert len(fetches) == 1, [sp["Name"] for sp in spans]
        assert fetches[0]["Attrs"].get("fused") == 1
        # A fully-placed batch needs no forensics fetch either.
        assert not [sp for sp in spans
                    if sp["Name"] == "batch.fetch_forensics"]
        assert stats.fetch_bytes > 0

    def test_window_overflow_falls_back_to_slot_record(self, monkeypatch):
        """A payload window smaller than nnz triggers the overflow path
        (slot-record fetch + host decode) — placements must still be
        bit-identical to the two-phase run."""
        from nomad_tpu.ops import kernels

        h_f, h_t, jobs = build_twin_problem(51)
        monkeypatch.setattr(kernels, "FUSED_WINDOW_BYTES", 64)
        st_f = run_batch(h_f, jobs, fused=True, monkeypatch=monkeypatch)
        monkeypatch.setattr(kernels, "FUSED_WINDOW_BYTES",
                            8 << 20)
        st_t = run_batch(h_t, jobs, fused=False, monkeypatch=monkeypatch)
        assert st_f.fused == 1
        assert placements_by_spec(h_f, jobs) == placements_by_spec(
            h_t, jobs)

    def test_failed_specs_add_at_most_one_forensics_fetch(self,
                                                          monkeypatch):
        """Overcommitted asks (capacity exhaustion at full feasibility)
        still fetch only the fused result buffer; a spec with a
        constraint filter adds exactly ONE batched forensics fetch."""
        h = Harness()
        for _ in range(4):
            h.state.upsert_node(h.next_index(), make_node())
        job = make_job(2)
        tg = job.task_groups[0]
        tg.constraints = list(tg.constraints) + [
            s.Constraint("${attr.kernel.name}", "plan9", "=")]
        h.state.upsert_job(h.next_index(), job)
        tracing.enable()
        try:
            monkeypatch.setenv("NOMAD_TPU_FUSED", "1")
            ev = reg_eval(job)
            TPUBatchScheduler(h.logger, h.snapshot(), h).schedule_batch(
                [ev])
            spans = tracing.trace_for_eval(ev.id)
        finally:
            tracing.disable()
        assert len([sp for sp in spans
                    if sp["Name"] == "batch.fetch"]) == 1
        assert len([sp for sp in spans
                    if sp["Name"] == "batch.fetch_forensics"]) == 1


# -- chaos: corrupted fused buffer -------------------------------------------

@pytest.mark.chaos
class TestFusedCorruption:
    def test_corrupt_fused_buffer_breaker_and_probe_recovery(
            self, monkeypatch):
        """ops.kernel_result corrupts the FUSED result buffer: the batch
        is rejected, the breaker trips, the oracle places everything;
        after the cooldown a clean half-open probe (still fused)
        restores the device path."""
        monkeypatch.setenv("NOMAD_TPU_FUSED", "1")
        clock = [0.0]
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=5.0, clock=lambda: clock[0])
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())

        def batch():
            jobs = [make_job(2) for _ in range(2)]
            for j in jobs:
                h.state.upsert_job(h.next_index(), j)
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                                      breaker=brk)
            stats = sched.schedule_batch([reg_eval(j) for j in jobs])
            placed = all(len([
                a for a in h.state.allocs_by_job(None, j.id, True)
                if not a.terminal_status()]) == 2 for j in jobs)
            return stats, placed

        with fault.scenario({"seed": 5, "faults": [
                {"point": "ops.kernel_result", "action": "corrupt",
                 "times": 1}]}):
            st1, placed1 = batch()
            fired = fault.trace()
        assert fired == [("ops.kernel_result", 0, "corrupt")]
        assert st1.kernel_rejects == 1 and placed1
        assert brk.state == "open"

        st2, placed2 = batch()              # open: oracle carries
        assert st2.oracle_routed == 2 and placed2

        clock[0] += 6.0                     # past cooldown: probe
        st3, placed3 = batch()
        assert st3.oracle_routed == 0 and st3.fused == 1 and placed3
        assert brk.state == "closed"


# -- packed-result decode twins (ISSUE 13) -----------------------------------

class TestNativeDecode:
    """native/decode.cc vs the numpy/python twins on seeded COO shapes
    (the conftest pins NOMAD_TPU_DECODE_GUARD_EVERY=1, so every guarded
    call in the batch path is ALSO twin-verified; these pin the module
    directly, including twin-only edge shapes)."""

    def _corpus(self, seed, n_specs=13, n_real=97):
        import random
        rng = random.Random(seed)
        rows, cols, cnts, scs, cos = [], [], [], [], []
        for u in range(n_specs):
            for _ in range(rng.randrange(0, 7)):
                rows.append(u)
                cols.append(rng.randrange(n_real))
                cnts.append(rng.randrange(1, 5))
                scs.append(rng.random() * 18.0)
                cos.append(rng.randrange(0, 3))
        return (np.array(rows, np.int32), np.array(cols, np.int32),
                np.array(cnts, np.int32), np.array(scs, np.float32),
                np.array(cos, np.int32), n_specs, n_real)

    @pytest.mark.parametrize("seed", [0, 1, 2, 9])
    def test_expand_matches_twin(self, seed):
        from nomad_tpu.ops import decode
        decode.reset_counters()
        rows, cols, cnts, _, _, n_specs, n_real = self._corpus(seed)
        off, exp = decode.expand_coo(rows, cols, cnts, n_specs, n_real,
                                     int(cnts.sum()))
        ref_off, ref_exp = decode._expand_twin(rows, cols, cnts,
                                               n_specs, n_real)
        np.testing.assert_array_equal(off, ref_off)
        np.testing.assert_array_equal(exp, ref_exp)
        assert decode.GUARD_MISMATCHES == 0
        decode.reset_counters()

    @pytest.mark.parametrize("seed", [0, 3, 7])
    def test_last_scores_matches_twin(self, seed):
        from nomad_tpu.ops import decode
        decode.reset_counters()
        rows, cols, cnts, scs, cos, n_specs, n_real = self._corpus(seed)
        out = decode.last_scores(rows, cols, scs, cos, n_specs, n_real)
        ref = decode._last_scores_twin(rows, cols, scs, cos, n_specs,
                                       n_real)
        for a, b in zip(out, ref):
            np.testing.assert_array_equal(a, b)
        assert decode.GUARD_MISMATCHES == 0
        decode.reset_counters()

    def test_empty_and_all_invalid(self):
        from nomad_tpu.ops import decode
        rows = np.array([-1, -1], np.int32)
        cols = np.array([5, 6], np.int32)
        cnts = np.array([1, 1], np.int32)
        off, exp = decode.expand_coo(rows, cols, cnts, 4, 10, 2)
        assert off.tolist() == [0, 0, 0, 0, 0] and len(exp) == 0
        off2, exp2 = decode.expand_coo(
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.int32), 3, 10, 0)
        assert off2.tolist() == [0, 0, 0, 0] and len(exp2) == 0


# -- compile-cache audit (ISSUE 13) ------------------------------------------

class TestCompileAudit:
    def test_same_shape_stream_compiles_once(self):
        """A stream of same-shape batches must add NO new placement-
        program signatures after the first — the recompile ceiling the
        bench --check guards at 200 batches rides this counter."""
        from nomad_tpu.ops import kernels

        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())

        def one_batch():
            job = make_job(2)
            h.state.upsert_job(h.next_index(), job)
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h)
            sched.schedule_batch([reg_eval(job)])

        one_batch()
        one_batch()   # resident-hit shape (no u_rows in the dyn pack)
        base = kernels.compile_signatures()
        for _ in range(4):
            one_batch()
        assert kernels.compile_signatures() == base, (
            "steady same-shape batches must not mint new program "
            "signatures")
