"""Multi-server clustering tests: in-process servers on loopback ports —
election, replication, leader forwarding, failover, restart catch-up, and
snapshot install (reference: nomad/leader_test.go, serf_test.go,
raft_rpc.go; SURVEY.md §4 item 3: multi-node = multiple Server structs in
one test process)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.codec import to_wire
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.server.log_codec import decode_payload, encode_payload
from nomad_tpu.server.raft import MultiRaft
from nomad_tpu.server.rpc import ConnPool
from nomad_tpu.structs import structs as s


def wait_until(predicate, timeout=30.0, interval=0.02):
    """Generous default budget: elections under full-suite CPU contention
    can need several rounds."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def make_job(count=1):
    j = mock.job()
    j.task_groups[0].count = count
    for t in j.task_groups[0].tasks:
        t.resources.networks = []
    return j


# ---------------------------------------------------------------------------
# log codec
# ---------------------------------------------------------------------------


class TestLogCodec:
    def test_roundtrip_job_register(self):
        job = mock.job()
        blob = encode_payload({"job": job})
        assert isinstance(blob, bytes)
        out = decode_payload(blob)
        assert isinstance(out["job"], s.Job)
        assert out["job"].id == job.id
        assert out["job"].task_groups[0].tasks[0].resources.cpu == \
            job.task_groups[0].tasks[0].resources.cpu

    def test_roundtrip_eval_and_alloc_lists(self):
        ev = mock.eval()
        alloc = mock.alloc()
        blob = encode_payload({"evals": [ev], "allocs": [alloc],
                               "node_id": "n1", "drain": True})
        out = decode_payload(blob)
        assert out["evals"][0].id == ev.id
        assert out["allocs"][0].id == alloc.id
        assert out["node_id"] == "n1" and out["drain"] is True

    def test_unknown_type_rejected(self):
        import msgpack
        evil = msgpack.packb({"__t": "os.system", "__d": {}},
                             use_bin_type=True)
        with pytest.raises(ValueError):
            decode_payload(evil)


# ---------------------------------------------------------------------------
# cluster harness
# ---------------------------------------------------------------------------


def make_cluster(tmp_path, n=3, bootstrap_expect=None):
    """n in-process servers; server 1 is the join point."""
    expect = bootstrap_expect or n
    servers = []
    first_addr = None
    for i in range(n):
        cfg = ServerConfig(
            node_name=f"server-{i + 1}",
            data_dir=str(tmp_path / f"s{i + 1}"),
            enable_rpc=True,
            bootstrap_expect=expect,
            start_join=[first_addr] if first_addr else [],
            num_schedulers=0,  # scheduling not under test here
        )
        srv = Server(cfg)
        if first_addr is None:
            first_addr = srv.config.rpc_advertise
        servers.append(srv)
    for srv in servers:
        srv.start()
    return servers


def find_leader(servers):
    for srv in servers:
        if srv.is_leader() and srv.raft.is_raft_leader():
            return srv
    return None


def wait_for_leader(servers, timeout=30.0):
    if not wait_until(lambda: find_leader(servers) is not None, timeout):
        detail = "; ".join(
            f"{srv.config.node_name}: raft={srv.raft.state} "
            f"term={srv.raft.term} leader_flag={srv.is_leader()} "
            f"peers={len(srv.raft.peers)} members={len(srv.members())}"
            for srv in servers)
        raise AssertionError(f"no leader elected: {detail}")
    return find_leader(servers)


class TestCluster:
    def test_election_replication_forwarding_failover(self, tmp_path):
        servers = make_cluster(tmp_path, 3)
        try:
            leader = wait_for_leader(servers)
            followers = [srv for srv in servers if srv is not leader]
            assert len(followers) == 2

            # Every server converges on the same member list and leader.
            assert wait_until(lambda: all(
                len(srv.members()) == 3 for srv in servers))
            assert wait_until(lambda: all(
                srv.leader_address() == leader.config.rpc_advertise
                for srv in servers))

            # Job register via RPC to a *follower* forwards to the leader
            # (rpc.go:178 forward) and replicates to all three.
            job = make_job()
            pool = ConnPool()
            reply = pool.call(followers[0].config.rpc_advertise,
                              "Job.Register", {"Job": to_wire(job)})
            assert reply["Index"] > 0 and reply["EvalID"]
            assert wait_until(lambda: all(
                srv.state.job_by_id(None, job.id) is not None
                for srv in servers), 5.0), "job did not replicate everywhere"

            # Kill the leader: the two survivors re-elect and no state is
            # lost (leader_test.go failover pattern).  Full default
            # budget: a 2-voter re-election can split-vote for many
            # rounds under full-suite CPU contention (the 10s bound
            # this used flaked roughly once per suite run).
            leader.shutdown()
            new_leader = wait_for_leader(followers)
            assert new_leader.state.job_by_id(None, job.id) is not None

            # Writes keep working through the new leader.
            job2 = make_job()
            reply2 = pool.call(new_leader.config.rpc_advertise,
                               "Job.Register", {"Job": to_wire(job2)})
            assert reply2["Index"] > reply["Index"]
            survivors = followers
            assert wait_until(lambda: all(
                srv.state.job_by_id(None, job2.id) is not None
                for srv in survivors), 5.0)
            pool.close()
        finally:
            for srv in servers:
                srv.shutdown()

    def test_follower_restart_catches_up(self, tmp_path):
        servers = make_cluster(tmp_path, 3)
        try:
            leader = wait_for_leader(servers)
            follower = next(srv for srv in servers if srv is not leader)

            job1 = make_job()
            leader.job_register(job1)
            assert wait_until(
                lambda: follower.state.job_by_id(None, job1.id) is not None)

            # Stop the follower, write while it is down, restart it with
            # the same data_dir: WAL + term recover, leader replays the
            # missing suffix.
            idx = servers.index(follower)
            cfg = follower.config
            follower.shutdown()
            time.sleep(0.2)

            job2 = make_job()
            leader.job_register(job2)

            restarted = Server(ServerConfig(
                node_name=cfg.node_name, data_dir=cfg.data_dir,
                enable_rpc=True, rpc_port=int(cfg.rpc_advertise.rsplit(":", 1)[1]),
                bootstrap_expect=3,
                start_join=[leader.config.rpc_advertise],
                num_schedulers=0))
            servers[idx] = restarted
            restarted.start()
            # Recovered job1 from its own WAL/snapshot, caught job2 up from
            # the leader.
            assert wait_until(
                lambda: restarted.state.job_by_id(None, job2.id) is not None,
                10.0), "restarted follower did not catch up"
            assert restarted.state.job_by_id(None, job1.id) is not None
        finally:
            for srv in servers:
                srv.shutdown()

    def test_snapshot_install_for_fresh_peer(self, tmp_path):
        servers = make_cluster(tmp_path, 3)
        try:
            leader = wait_for_leader(servers)
            follower = next(srv for srv in servers if srv is not leader)

            job1 = make_job()
            leader.job_register(job1)

            # Wipe a follower completely and compact the leader's log so
            # the entries the fresh peer needs are gone — forcing the
            # InstallSnapshot path.
            idx = servers.index(follower)
            follower.shutdown()
            time.sleep(0.2)
            job2 = make_job()
            leader.job_register(job2)
            leader.raft.snapshot()  # compaction: log starts past job2
            assert isinstance(leader.raft, MultiRaft)
            assert leader.raft.base_index > 0

            fresh = Server(ServerConfig(
                node_name="server-fresh",
                data_dir=str(tmp_path / "fresh"),
                enable_rpc=True,
                rpc_port=int(follower.config.rpc_advertise.rsplit(":", 1)[1]),
                bootstrap_expect=3,
                start_join=[leader.config.rpc_advertise],
                num_schedulers=0))
            servers[idx] = fresh
            fresh.start()
            assert wait_until(
                lambda: fresh.state.job_by_id(None, job2.id) is not None,
                10.0), "fresh peer did not receive a snapshot"
            assert fresh.state.job_by_id(None, job1.id) is not None
            # The InstallSnapshot moved the fresh peer's log base to the
            # leader's compaction horizon (poll: the base assignment runs
            # moments after the restored state becomes visible).
            assert wait_until(
                lambda: fresh.raft.base_index >= leader.raft.base_index, 5.0)
        finally:
            for srv in servers:
                srv.shutdown()


class TestStreamingInstallSnapshot:
    def test_fresh_peer_catches_up_via_chunked_install(self, tmp_path,
                                                       monkeypatch):
        """A follower far behind the compaction horizon receives the
        FSM snapshot as CHUNKED install_snapshot frames (ISSUE 10): with
        a tiny chunk ceiling the transfer must arrive in several pieces,
        reassemble, and restore — state parity and a raised log base on
        the receiver, chunk counters on the sender."""
        monkeypatch.setenv("NOMAD_TPU_SNAPSHOT_CHUNK", "512")
        servers = make_cluster(tmp_path, 3)
        try:
            leader = wait_for_leader(servers)
            follower = next(srv for srv in servers if srv is not leader)
            jobs = [make_job() for _ in range(5)]
            for job in jobs:
                leader.job_register(job)

            idx = servers.index(follower)
            follower.shutdown()
            time.sleep(0.2)
            leader.raft.snapshot()  # compaction: log starts past the jobs
            assert leader.raft.base_index > 0

            fresh = Server(ServerConfig(
                node_name="server-fresh",
                data_dir=str(tmp_path / "fresh"),
                enable_rpc=True,
                rpc_port=int(
                    follower.config.rpc_advertise.rsplit(":", 1)[1]),
                bootstrap_expect=3,
                start_join=[leader.config.rpc_advertise],
                num_schedulers=0))
            servers[idx] = fresh
            fresh.start()
            assert wait_until(
                lambda: all(fresh.state.job_by_id(None, j.id) is not None
                            for j in jobs), 15.0), \
                "fresh peer did not receive the chunked snapshot"
            assert wait_until(
                lambda: fresh.raft.base_index >= leader.raft.base_index,
                5.0)
            totals = leader.metrics.sink.latest()["CounterTotals"]
            assert totals.get("nomad.raft.snapshot.chunks_sent", 0) >= 2, \
                "snapshot went out as one frame despite the chunk ceiling"
        finally:
            for srv in servers:
                srv.shutdown()

    def test_out_of_sequence_chunk_rejected_then_recovers(self):
        """A chunk that does not continue the buffered sequence replies
        success=False (the sender restarts from offset 0) and never
        corrupts the receiver."""
        src = FSM()
        job = mock.job()
        src.apply(1, MessageType.JOB_REGISTER, {"job": job})
        blob = src.snapshot()
        cut = len(blob) // 2

        r = MultiRaft(FSM(), "127.0.0.1:1", pool=None, data_dir=None)
        base = {"kind": "install_snapshot", "term": 1,
                "leader": "127.0.0.1:2", "last_index": 7, "last_term": 1,
                "peers": ["127.0.0.1:1", "127.0.0.1:2"],
                "total": len(blob)}
        ok = r.handle_message(dict(base, offset=0, data=blob[:cut],
                                   done=False))
        assert ok["success"] is True
        # Skip ahead: sequence break → rejected, buffer dropped, FSM
        # untouched.
        bad = r.handle_message(dict(base, offset=cut + 8,
                                    data=blob[cut + 8:], done=True))
        assert bad["success"] is False
        assert r.fsm.state.job_by_id(None, job.id) is None
        # Restart from 0 succeeds end-to-end and restores the state.
        assert r.handle_message(dict(base, offset=0, data=blob[:cut],
                                     done=False))["success"] is True
        fin = r.handle_message(dict(base, offset=cut, data=blob[cut:],
                                    done=True))
        assert fin["success"] is True
        assert r.fsm.state.job_by_id(None, job.id) is not None
        assert r.base_index == 7
        r.close()


class TestDurableVotes:
    def test_term_and_vote_survive_restart(self, tmp_path):
        """A restarted server must not vote twice in the same term
        (Raft §5.2; the round-1 advisor finding)."""
        fsm = FSM()
        r = MultiRaft(fsm, "127.0.0.1:1", pool=None,
                      data_dir=str(tmp_path / "raft"))
        r.term = 7
        r.voted_for = "127.0.0.1:2"
        r._persist_meta()
        r.log.append([1, 7, int(MessageType.JOB_REGISTER),
                      encode_payload({"job": mock.job()})])
        r.store.append([r.log[-1]])
        r.close()

        r2 = MultiRaft(FSM(), "127.0.0.1:1", pool=None,
                       data_dir=str(tmp_path / "raft"))
        assert r2.term == 7
        assert r2.voted_for == "127.0.0.1:2"
        assert r2._last_log_index() == 1
        # The recovered entry is NOT applied (it was never known committed).
        assert r2.applied_index() == 0
        # A vote request for the same term from a different candidate is
        # refused because the vote was persisted.
        reply = r2._on_request_vote({
            "term": 7, "candidate": "127.0.0.1:3",
            "last_log_index": 5, "last_log_term": 7})
        assert reply["granted"] is False
        r2.close()


@pytest.mark.slow
class TestClientOverTCP:
    """A client connected to a server purely over the RPC wire — the
    reference's normal client↔server path (client/client.go:465 RPC via
    msgpack-rpc; round-1 advisor item: client-only agent against a server
    agent over TCP)."""

    def test_client_schedules_and_syncs_over_rpc(self, tmp_path):
        from nomad_tpu.client import Client, ClientConfig
        from nomad_tpu.server.rpc import RemoteServerRPC

        srv = Server(ServerConfig(enable_rpc=True, num_schedulers=1))
        srv.start()
        client = None
        try:
            rpc = RemoteServerRPC([srv.config.rpc_advertise])
            cfg = ClientConfig(alloc_dir=str(tmp_path / "allocs"),
                               state_dir=str(tmp_path / "state"))
            client = Client(cfg, rpc=rpc)
            client.start()

            assert wait_until(
                lambda: srv.node_get(client.node.id) is not None and
                srv.node_get(client.node.id).status == s.NODE_STATUS_READY)

            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            for t in tg.tasks:
                t.driver = "mock_driver"
                t.config = {"run_for": "30s"}
                t.resources.networks = []
                t.services = []
            srv.job_register(job)

            # Placement flows to the client over Node.GetClientAllocs and
            # the running status returns over Node.UpdateAlloc.
            assert wait_until(lambda: any(
                a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
                for a in srv.job_allocations(job.id)), 15.0)
        finally:
            if client is not None:
                client.shutdown()
            srv.shutdown()


class TestForceLeaveRejoin:
    def test_force_left_server_can_rejoin(self):
        """serf refutation: a force-left server that is actually alive
        out-bids the 'left' record with a higher incarnation on rejoin."""
        a = Server(ServerConfig(node_name="srv-a", enable_rpc=True,
                                num_schedulers=0))
        b = Server(ServerConfig(node_name="srv-b", enable_rpc=True,
                                num_schedulers=0))
        a.start()
        b.start()
        try:
            assert a.join([b.config.rpc_advertise]) == 1
            assert wait_until(lambda: len(a.members()) == 2
                              and len(b.members()) == 2)
            assert a.force_leave("srv-b")
            assert wait_until(lambda: any(
                m["Name"] == "srv-b" and m["Status"] == "left"
                for m in a.members()))
            # b rejoins: its refutation must flip the record back to alive
            # on BOTH sides.
            assert b.join([a.config.rpc_advertise]) == 1

            def alive_everywhere():
                return all(any(m["Name"] == "srv-b"
                               and m["Status"] == "alive"
                               for m in srv.members())
                           for srv in (a, b))

            assert wait_until(alive_everywhere, 10.0), (
                a.members(), b.members())
        finally:
            b.shutdown()
            a.shutdown()


class TestWireEndpointSurface:
    """The reference's RPC endpoint families (server.go:163-174) exist on
    the wire: Eval dequeue/ack flow, Plan.Submit, Region/Operator reads."""

    def test_eval_and_plan_wire_flow(self):
        srv = Server(ServerConfig(enable_rpc=True, num_schedulers=0))
        srv.start()
        pool = ConnPool()
        try:
            addr = srv.config.rpc_advertise
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            srv.node_register(node)
            job = make_job(1)
            reply = pool.call(addr, "Job.Register", {"Job": to_wire(job)})
            assert reply["EvalID"]

            # A remote worker dequeues the eval over the wire…  Since
            # ISSUE 11 a struct-codec connection delivers TYPED
            # Evaluations; a legacy msgpack connection still gets the
            # CamelCase tree — ensure() is the receiver contract.
            from nomad_tpu.api.codec import ensure
            from nomad_tpu.structs import structs as s

            dq = pool.call(addr, "Eval.Dequeue",
                           {"Schedulers": [job.type], "Timeout": 5.0})
            assert dq["Eval"] is not None
            assert ensure(s.Evaluation, dq["Eval"]).id == reply["EvalID"]
            token = dq["Token"]
            # …acks it…
            pool.call(addr, "Eval.Ack",
                      {"EvalID": reply["EvalID"], "Token": token})
            got = pool.call(addr, "Eval.GetEval",
                            {"EvalID": reply["EvalID"]})
            assert got["Eval"] is not None
            listed = pool.call(addr, "Eval.List", {})
            assert any(ensure(s.Evaluation, e).id == reply["EvalID"]
                       for e in listed["Evals"])

            regions = pool.call(addr, "Region.List", {})
            assert regions["Regions"] == ["global"]
            raft_cfg = pool.call(addr, "Operator.RaftGetConfiguration", {})
            assert raft_cfg["Servers"]
        finally:
            pool.close()
            srv.shutdown()


class TestOperatorRemovePeer:
    """operator raft remove-peer end-to-end (api/operator.go:69
    RaftRemovePeerByAddress → Operator endpoint → raft config change)."""

    def test_remove_dead_peer_via_follower_forward(self, tmp_path):
        servers = make_cluster(tmp_path, 3)
        pool = ConnPool()
        try:
            leader = wait_for_leader(servers)
            followers = [srv for srv in servers if srv is not leader]
            dead, alive = followers
            dead_addr = dead.config.rpc_advertise
            dead.shutdown()

            # Drive the RPC through the SURVIVING FOLLOWER: it must
            # forward to the leader (rpc.go:178) before mutating.
            pool.call(alive.config.rpc_advertise,
                      "Operator.RaftRemovePeerByAddress",
                      {"Address": dead_addr})
            assert dead_addr not in leader.raft.peers
            assert set(leader.raft.peers) == {
                leader.config.rpc_advertise, alive.config.rpc_advertise}
            # The new configuration replicates to the survivor.
            assert wait_until(
                lambda: dead_addr not in alive.raft.peers, 10.0)

            # Removing an unknown peer errors instead of proposing.
            with pytest.raises(Exception):
                pool.call(leader.config.rpc_advertise,
                          "Operator.RaftRemovePeerByAddress",
                          {"Address": "10.0.0.9:4647"})
        finally:
            pool.close()
            for srv in servers:
                srv.shutdown()
