"""Multi-tenant serving plane tests (ISSUE 16).

Fast units for the tenancy primitives (TenantQueue/FairnessState,
QuotaLedger, TokenBucket/RateLimiter), the Namespace codec + binary
snapshot round-trips in BOTH persist formats (pre-tenancy snapshots and
legacy frames must restore with namespace="default"), the SDK's
jittered 429 retry, and the per-tenant broker admission front door —
all tier-1 under the ``tenancy`` marker.  The chaos leg (SIGKILL a
follower mid-quota-enforcement, assert no tenant exceeds its alloc
quota in committed state post-recovery) is additionally marked
``chaos``.
"""
import dataclasses

import pytest

from nomad_tpu import mock
from nomad_tpu.api.client import APIError, Jobs
from nomad_tpu.api.codec import from_wire, to_wire
from nomad_tpu.server.eval_broker import (BrokerLimitError, EvalBroker,
                                          _HeapEntry)
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import structs as s
from nomad_tpu.tenancy import (FairnessState, QuotaLedger, RateLimiter,
                               TenantQueue, TokenBucket)
from nomad_tpu.utils.backoff import Backoff

pytestmark = pytest.mark.tenancy


def entry(ns, priority=50, ci=0, seq=0):
    ev = s.Evaluation(
        id=s.generate_uuid(), priority=priority, type=s.JOB_TYPE_SERVICE,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=f"job-{ns}-{seq}",
        status=s.EVAL_STATUS_PENDING, namespace=ns, create_index=ci)
    return _HeapEntry(sort_key=(-priority, ci, seq), eval=ev)


def drain(q):
    out = []
    while q:
        out.append(q.pop())
    return out


# ---------------------------------------------------------------------------
# fairness: TenantQueue / FairnessState
# ---------------------------------------------------------------------------


class TestTenantQueue:
    def test_fifo_objective_reproduces_legacy_global_order(self):
        """fifo scores every tenant 0, so the selection falls through to
        the arrival tiebreak: pops come out in exact legacy
        (-priority, create_index, seq) order across tenants."""
        fs = FairnessState(objective=s.TENANCY_OBJECTIVE_FIFO)
        q = TenantQueue(fs)
        entries = [entry("a", 50, 1, 0), entry("b", 70, 2, 1),
                   entry("a", 50, 3, 2), entry("c", 70, 4, 3),
                   entry("b", 50, 5, 4)]
        for e in entries:
            q.push(e)
        got = [e.sort_key for e in drain(q)]
        assert got == sorted(e.sort_key for e in entries)

    def test_drf_drains_lowest_dominant_share_first(self):
        fs = FairnessState()  # default objective: drf
        fs.set_capacity((100_000, 200_000, 0, 0))
        fs.set_usage("hog", (50_000, 10_000, 0, 0))    # share 0.5
        fs.set_usage("mouse", (10_000, 10_000, 0, 0))  # share 0.1
        q = TenantQueue(fs)
        for i in range(4):
            q.push(entry("hog", 50, i, i))
            q.push(entry("mouse", 50, i, 100 + i))
        popped = [e.eval.namespace for e in drain(q)]
        # The idle tenant's whole backlog drains before the hog's.
        assert popped == ["mouse"] * 4 + ["hog"] * 4

    def test_dequeue_weight_divides_dominant_share(self):
        fs = FairnessState()
        fs.set_capacity((100_000, 0, 0, 0))
        fs.set_usage("heavy", (80_000, 0, 0, 0))  # share 0.8, weight 4
        fs.set_usage("light", (30_000, 0, 0, 0))  # share 0.3, weight 1
        fs.set_policy("heavy", 4.0, "")
        q = TenantQueue(fs)
        q.push(entry("light", 50, 1, 0))
        q.push(entry("heavy", 50, 2, 1))
        # 0.8/4 = 0.2 < 0.3/1: the weighted tenant wins.
        assert q.pop().eval.namespace == "heavy"

    def test_weighted_rr_honors_2_to_1_weights(self):
        fs = FairnessState(objective=s.TENANCY_OBJECTIVE_WRR)
        fs.set_policy("a", 2.0, "")
        q = TenantQueue(fs)
        for seq in range(12):  # interleaved arrivals a,b,a,b,...
            q.push(entry("a" if seq % 2 == 0 else "b", 50, seq, seq))
        first9 = [q.pop().eval.namespace for _ in range(9)]
        # weight 2 tenant is charged half the virtual time per dequeue,
        # so it drains exactly twice as often.
        assert first9.count("a") == 6 and first9.count("b") == 3
        drain(q)
        assert fs.vt["a"] == pytest.approx(3.0)  # 6 pops x 1/2
        assert fs.vt["b"] == pytest.approx(6.0)  # 6 pops x 1/1

    def test_priority_tiers_dominate_fairness(self):
        """A higher priority band always drains first, even when its
        tenant is the most over-share one — preemption/bypass semantics
        compose ABOVE the fairness plane."""
        fs = FairnessState()
        fs.set_capacity((1000, 0, 0, 0))
        fs.set_usage("hog", (900, 0, 0, 0))
        q = TenantQueue(fs)
        q.push(entry("idle", 50, 1, 0))
        q.push(entry("hog", 90, 2, 1))
        assert q.pop().eval.priority == 90
        assert q.pop().eval.namespace == "idle"

    def test_note_usage_changed_rescores_queued_tenants(self):
        fs = FairnessState()
        fs.set_capacity((1000, 0, 0, 0))
        fs.set_usage("a", (100, 0, 0, 0))
        fs.set_usage("b", (500, 0, 0, 0))
        q = TenantQueue(fs)
        for i in range(2):
            q.push(entry("a", 50, i, i))
            q.push(entry("b", 50, i, 10 + i))
        # Usage flips before anything dequeues; the O(changed) re-score
        # must win over the stale selection entries.
        fs.set_usage("a", (900, 0, 0, 0))
        fs.set_usage("b", (50, 0, 0, 0))
        q.note_usage_changed(("a", "b"))
        assert q.pop().eval.namespace == "b"

    def test_list_compatible_surface(self):
        fs = FairnessState()
        q = TenantQueue(fs)
        assert not q and len(q) == 0
        with pytest.raises(IndexError):
            q.pop()
        for i in range(3):
            q.push(entry("a", 50, i, i))
        q.push(entry("b", 70, 9, 9))
        assert q and len(q) == 4
        assert len(list(iter(q))) == 4
        assert q.peek_priority() == 70
        assert q.pending_by_tenant() == {"a": 3, "b": 1}
        drain(q)
        assert len(q) == 0 and q.peek_priority() is None


# ---------------------------------------------------------------------------
# quota: ledger + token buckets
# ---------------------------------------------------------------------------


class TestQuotaLedger:
    def test_admit_reject_and_zero_is_unlimited(self):
        led = QuotaLedger()
        assert led.check_and_reserve("t", "j1", 5, live=0, quota=10)
        assert led.check_and_reserve("t", "j2", 5, live=0, quota=10)
        assert not led.check_and_reserve("t", "j3", 1, live=0, quota=10)
        assert led.check_and_reserve("t", "j3", 1000, live=0, quota=0)
        assert led.reserved("t") == 1010

    def test_live_fold_counts_against_quota(self):
        led = QuotaLedger()
        assert led.check_and_reserve("t", "j1", 2, live=8, quota=10)
        assert not led.check_and_reserve("t", "j2", 1, live=8, quota=10)

    def test_reregister_replaces_reservation(self):
        """Steady-state resubmits of the same job must not ratchet the
        reserved sum — the check subtracts the job's prior hold."""
        led = QuotaLedger()
        assert led.check_and_reserve("t", "j1", 5, live=0, quota=6)
        assert led.check_and_reserve("t", "j1", 5, live=0, quota=6)
        assert led.reserved("t") == 5
        assert led.check_and_reserve("t", "j1", 3, live=0, quota=6)
        assert led.reserved("t") == 3

    def test_release_frees_and_is_idempotent(self):
        led = QuotaLedger()
        led.check_and_reserve("t", "j1", 4, live=0, quota=4)
        assert not led.check_and_reserve("t", "j2", 1, live=0, quota=4)
        led.release("j1")
        assert led.reserved("t") == 0
        led.release("j1")  # unknown/double release: no-op
        led.release("never-seen")
        assert led.check_and_reserve("t", "j2", 4, live=0, quota=4)

    def test_rebuild_reseeds_from_scratch(self):
        led = QuotaLedger()
        led.check_and_reserve("old", "j1", 9, live=0, quota=0)
        led.rebuild([("j2", "a", 3), ("j3", "b", 2), ("j4", "a", 1)])
        assert led.reserved("old") == 0
        assert led.reserved("a") == 4
        assert led.reserved("b") == 2


class TestTokenBucket:
    def test_burst_then_retry_after_then_refill(self):
        tb = TokenBucket(rate=1.0, burst=2.0)
        assert tb.take(100.0) == 0.0
        assert tb.take(100.0) == 0.0
        # Drained: the hint is the seconds until one token exists.
        assert tb.take(100.0) == pytest.approx(1.0)
        # 1.1s later a token has accrued.
        assert tb.take(101.1) == 0.0

    def test_default_burst_derivation(self):
        tb = TokenBucket(rate=5.0, burst=0.0)
        assert tb.burst == 10.0

    def test_rate_limiter_unconfigured_never_throttles(self):
        rl = RateLimiter()
        assert rl.check("default", now=1.0) == 0.0
        assert rl.check("anything", now=1.0) == 0.0

    def test_rate_limiter_configure_throttle_and_drop(self):
        rl = RateLimiter()
        rl.configure("t", rate=1.0, burst=1.0)
        assert rl.check("t", now=10.0) == 0.0
        assert rl.check("t", now=10.0) > 0.0
        # Re-applying the SAME config must not reset the bucket (the
        # server re-pushes policy on every namespace upsert).
        rl.configure("t", rate=1.0, burst=1.0)
        assert rl.check("t", now=10.0) > 0.0
        # A CHANGED config installs a fresh bucket.
        rl.configure("t", rate=5.0, burst=5.0)
        assert rl.check("t", now=10.0) == 0.0
        rl.drop("t")
        assert rl.check("t", now=10.0) == 0.0
        # rate <= 0 unconfigures too.
        rl.configure("u", rate=1.0, burst=1.0)
        rl.configure("u", rate=0.0)
        assert rl.check("u", now=10.0) == 0.0


# ---------------------------------------------------------------------------
# namespace codec + snapshot round-trips
# ---------------------------------------------------------------------------


def sample_ns():
    return s.Namespace(
        name="team-a", description="prod tenant", quota_node_units=1.5,
        max_live_allocs=10, max_pending_evals=4, api_rate=5.0, api_burst=8,
        dequeue_weight=2.0, objective=s.TENANCY_OBJECTIVE_WRR)


class TestNamespaceCodec:
    def test_wire_round_trip_and_casing(self):
        ns = sample_ns()
        ns.create_index, ns.modify_index = 3, 7
        w = to_wire(ns)
        # Go-style initialisms do NOT apply here: api_rate is ApiRate.
        assert w["ApiRate"] == 5.0 and w["ApiBurst"] == 8
        assert "APIRate" not in w
        assert w["MaxLiveAllocs"] == 10 and w["MaxPendingEvals"] == 4
        assert w["QuotaNodeUnits"] == 1.5
        assert w["DequeueWeight"] == 2.0
        assert w["Objective"] == s.TENANCY_OBJECTIVE_WRR
        assert from_wire(s.Namespace, w) == ns

    def test_pre_tenancy_frames_decode_as_default_namespace(self):
        """Wire frames from a pre-tenancy peer carry no Namespace key;
        every stamped struct must decode as the implicit default."""
        for obj in (mock.job(), mock.alloc(),
                    s.Evaluation(id=s.generate_uuid())):
            w = to_wire(obj)
            w.pop("Namespace", None)
            assert from_wire(type(obj), w).namespace == "default"

    def test_validate_rejects_bad_rows(self):
        assert s.Namespace(name="ok").validate() == []
        assert s.Namespace(name="").validate()
        assert s.Namespace(name="x", dequeue_weight=0.0).validate()
        assert s.Namespace(name="x", objective="lifo").validate()
        assert s.Namespace(name="x", max_live_allocs=-1).validate()


class TestNamespaceSnapshotRoundTrip:
    def _seed(self):
        st = StateStore()
        st.upsert_namespace(10, sample_ns())
        st.upsert_namespace(11, s.Namespace(name="team-b",
                                            objective="fifo"))
        st.upsert_namespace(12, dataclasses.replace(sample_ns(),
                                                    max_live_allocs=99))
        return st

    def _check(self, st2):
        rows = {n.name: n for n in st2.namespaces(None)}
        assert set(rows) == {"team-a", "team-b"}
        a = rows["team-a"]
        assert a.max_live_allocs == 99          # the upsert won
        assert a.api_rate == 5.0 and a.api_burst == 8
        assert a.dequeue_weight == 2.0
        assert a.objective == s.TENANCY_OBJECTIVE_WRR
        assert (a.create_index, a.modify_index) == (10, 12)
        assert rows["team-b"].objective == "fifo"
        assert st2.namespace_by_name(None, "team-a") is not None

    def test_v2_binary_snapshot_round_trip(self):
        st = self._seed()
        blob = st.persist()
        assert blob[:len(StateStore.SNAP2_MAGIC)] == StateStore.SNAP2_MAGIC
        self._check(StateStore.restore(blob))

    def test_legacy_msgpack_snapshot_round_trip(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "0")
        st = self._seed()
        blob = st.persist()
        assert blob[:len(StateStore.SNAP2_MAGIC)] != StateStore.SNAP2_MAGIC
        self._check(StateStore.restore(blob))

    def test_cross_format_restore(self, monkeypatch):
        """v2 blob restored under the legacy knob (and vice versa): the
        sniff is on the blob, not the environment."""
        st = self._seed()
        blob_v2 = st.persist()
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "0")
        blob_legacy = st.persist()
        self._check(StateStore.restore(blob_v2))
        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "1")
        self._check(StateStore.restore(blob_legacy))

    def test_pre_tenancy_snapshot_restores_cleanly(self, monkeypatch):
        """A snapshot written BEFORE the namespaces table existed (no
        "namespaces" key at all) must restore to an empty table, not
        crash — rolling upgrades restore old snapshots."""
        from nomad_tpu.server.log_codec import decode_payload, encode_payload

        monkeypatch.setenv("NOMAD_TPU_COLUMNAR", "0")
        st = self._seed()
        payload = decode_payload(st.persist(), subsystem="snapshot")
        del payload["namespaces"]
        st2 = StateStore.restore(
            encode_payload(payload, subsystem="snapshot"))
        assert st2.namespaces(None) == []
        assert st2.namespace_usage() == {}
        # And the restored store keeps working as a tenancy-aware one.
        st2.upsert_namespace(20, s.Namespace(name="late"))
        assert st2.namespace_by_name(None, "late").create_index == 20


# ---------------------------------------------------------------------------
# broker admission front door (per-tenant pending-eval quota)
# ---------------------------------------------------------------------------


class TestBrokerTenantAdmission:
    def test_per_tenant_pending_cap_raises_429_with_namespace(self):
        b = EvalBroker(nack_timeout=0)
        b.set_enabled(True)
        for i in range(3):
            b.enqueue(entry("team-a", 50, i, i).eval)
        # team-a is at its resolved quota; team-b is untouched.
        with pytest.raises(BrokerLimitError) as ei:
            b.check_admission(priority=50, namespace="team-a",
                              ns_max_pending=3)
        assert ei.value.namespace == "team-a"
        assert ei.value.retry_after > 0
        assert ei.value.limit == 3
        b.check_admission(priority=50, namespace="team-b", ns_max_pending=3)
        # 0 = unlimited (pre-tenancy behavior).
        b.check_admission(priority=50, namespace="team-a", ns_max_pending=0)
        pending, _deq, _shed, rejects = b.tenant_counters()["team-a"]
        assert pending == 3 and rejects == 1


# ---------------------------------------------------------------------------
# node-units quota (quota_node_units admission enforcement, ISSUE 17)
# ---------------------------------------------------------------------------


class _GaugeRecorder:
    """Telemetry stand-in: records set_gauge, swallows everything else."""

    def __init__(self):
        self.gauges = {}

    def set_gauge(self, key, value):
        self.gauges[key] = value

    def __getattr__(self, name):
        return lambda *a, **k: None


class TestNodeUnitsQuota:
    def _server(self):
        from nomad_tpu.server.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0))
        srv.start()
        for _ in range(2):
            srv.node_register(mock.node())
        return srv

    def _job(self, ns, count):
        j = mock.job()
        j.namespace = ns
        j.task_groups[0].count = count
        return j

    def test_over_quota_ask_rejected_with_429(self):
        """2 mock nodes = (8000 cpu, 16384 mb); a 10-count web job asks
        5000 cpu → dominant share 0.625 → 1.25 nodes-worth, over a
        1.0-unit quota.  A 4-count job (0.5 units) fits; reservations
        accumulate until a third submission would breach."""
        srv = self._server()
        try:
            srv.namespace_upsert(s.Namespace(name="units",
                                             quota_node_units=1.0))
            with pytest.raises(BrokerLimitError) as ei:
                srv.job_register(self._job("units", 10))
            assert ei.value.namespace == "units"
            assert ei.value.retry_after > 0
            # The rejected registration must not leak reservations in
            # EITHER ledger.
            assert srv.node_units_ledger.reserved("units") == 0
            assert srv.quota_ledger.reserved("units") == 0

            srv.job_register(self._job("units", 4))   # 0.5 units
            held = self._job("units", 4)
            srv.job_register(held)                    # 1.0 units total
            assert srv.node_units_ledger.reserved("units") == \
                pytest.approx(1.0)
            with pytest.raises(BrokerLimitError):
                srv.job_register(self._job("units", 4))
            # Deregister frees its node-units reservation, making room.
            srv.job_deregister(held.id)
            assert srv.node_units_ledger.reserved("units") == \
                pytest.approx(0.5)
            srv.job_register(self._job("units", 4))
            # Another tenant with no node-units quota is untouched.
            srv.job_register(self._job("other", 10))
        finally:
            srv.shutdown()

    def test_node_units_gauge_emitted(self):
        srv = self._server()
        try:
            srv.namespace_upsert(s.Namespace(name="units",
                                             quota_node_units=5.0))
            srv.job_register(self._job("units", 4))
            rec = _GaugeRecorder()
            srv.metrics = rec
            srv._feed_tenancy(tenant_top=5)
            assert "tenant.node_units.units" in rec.gauges
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# SDK: jittered retry honoring Retry-After
# ---------------------------------------------------------------------------


class _FakeConn:
    """Stands in for NomadAPI: fails the first N puts with an APIError,
    then succeeds."""

    def __init__(self, fail_codes):
        self.fail_codes = list(fail_codes)
        self.calls = 0

    def put(self, path, body=None, q=None):
        self.calls += 1
        if self.fail_codes:
            code, ra = self.fail_codes.pop(0)
            raise APIError(code, "nope", retry_after=ra)
        return {"EvalID": "e1"}, None


class TestRegisterWithRetry:
    def test_retries_429_and_honors_retry_after(self):
        conn = _FakeConn([(429, 2.0), (429, 2.0)])
        delays = []
        out, _meta = Jobs(conn).register_with_retry(
            mock.job(), retries=5, sleep=delays.append,
            backoff=Backoff(base=0.001, max_delay=0.002))
        assert out == {"EvalID": "e1"}
        assert conn.calls == 3 and len(delays) == 2
        for d in delays:
            # Jittered 0.5x-1.5x of the server hint — never a verbatim
            # synchronized re-burst, never less than half the hint.
            assert 1.0 <= d <= 3.0

    def test_non_429_raises_immediately(self):
        conn = _FakeConn([(500, 0.0)])
        delays = []
        with pytest.raises(APIError) as ei:
            Jobs(conn).register_with_retry(mock.job(), retries=5,
                                           sleep=delays.append)
        assert ei.value.code == 500
        assert conn.calls == 1 and delays == []

    def test_exhausted_retries_reraise_the_429(self):
        conn = _FakeConn([(429, 0.25)] * 10)
        delays = []
        with pytest.raises(APIError) as ei:
            Jobs(conn).register_with_retry(
                mock.job(), retries=2, sleep=delays.append,
                backoff=Backoff(base=0.001, max_delay=0.002))
        assert ei.value.code == 429
        assert conn.calls == 3 and len(delays) == 2


# ---------------------------------------------------------------------------
# chaos: SIGKILL a follower mid-quota-enforcement
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestChaosQuotaEnforcement:
    def test_follower_kill_mid_quota_enforcement(self):
        """chaos_smoke reshaped for tenancy: one abusive tenant soaks
        half the offered load and saturates its live-alloc quota early
        (its traffic is actively 429ing when the seeded scheduler
        SIGKILLs the follower).  Post-recovery the bar is: zero auditor
        violations, zero accepted-but-lost evals, and NO tenant above
        its alloc quota in committed state."""
        from nomad_tpu.loadgen.harness import run_scenario
        from nomad_tpu.loadgen.scenario import get_scenario

        sc = dataclasses.replace(
            get_scenario("chaos_smoke"),
            name="chaos_quota",
            # Load must span well past the fault: recovery is judged
            # against sustained placed/s, so load ending inside the
            # bound would leave the kill unrecoverable by definition.
            # Drain must outlive eval_nack_timeout (60s): deliveries
            # outstanding at the follower's 2 workers when it dies are
            # only redelivered after the nack deadline, and both must
            # complete inside the drain or they read as lost.
            max_submissions=800, measure_s=20.0, drain_s=60.0,
            # No client-side retry sleeps: with only 2 submitter
            # threads, sleeping ~0.5s per abuser 429 at ~20 rejects/s
            # would strangle the shared open-loop arrival and the
            # recovery check would starve for reasons unrelated to the
            # fault.  Drop on first 429; the retry path is unit-tested.
            submit_retries=0,
            # 1 abuser + 9 uniform compliant tenants: ONLY the abuser
            # saturates its quota (~3s in, well before the kill), so
            # the placed/s rate the recovery check compares against
            # stays steady through the fault.
            num_tenants=10, tenant_zipf=0.0,
            abusive_tenants=1, abusive_share=0.5,
            tenant_max_live_allocs=60, tenant_max_pending_evals=0,
            chaos={"seed": 11, "kills": 1, "partitions": 0,
                   "restart_delay_s": 0.5, "start_offset_s": 5.0,
                   "spacing_s": 6.0, "recovery_bound_s": 25.0},
            seed=23)
        rep = run_scenario(sc)

        aud = rep.get("auditor") or {}
        assert aud.get("violation_count") == 0, aud.get("violations")
        chaos = rep.get("chaos") or {}
        events = chaos.get("events") or []
        assert [ev["kind"] for ev in events] == ["kill"]
        assert not any(ev.get("error") for ev in events), events
        assert chaos.get("unrecovered") == 0, events

        ten = rep["tenancy"]
        # Quota enforcement was ACTIVE across the fault...
        assert ten["rejects_429"]["abuser"] > 0
        # ...and conservative: rejected tenants were told to back off,
        # never silently stripped of accepted work.
        assert ten["lost_accepted"] == {"abuser": 0, "compliant": 0}
        # The committed-state invariant, swept live by the auditor AND
        # re-checked in the final integrity pass.
        assert ten["quota_violations"] == 0, ten.get(
            "quota_violation_detail")
        assert rep["integrity"]["tenant_quota_violations"] == 0
        assert rep["sustained"]["stragglers_after_drain"] == 0
