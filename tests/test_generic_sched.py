"""Oracle scheduler tests (reference: scheduler/generic_sched_test.go)."""
import random

from nomad_tpu import mock
from nomad_tpu.scheduler import (
    Harness,
    RejectPlan,
    new_batch_scheduler,
    new_service_scheduler,
)
from nomad_tpu.scheduler.generic import GenericScheduler
from nomad_tpu.structs import structs as s


def make_harness(num_nodes=10):
    h = Harness()
    nodes = []
    for _ in range(num_nodes):
        n = mock.node()
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
    return h, nodes


def register_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(),
        priority=job.priority,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
        status=s.EVAL_STATUS_PENDING,
        type=job.type,
    )


def test_service_register_places_all():
    h, _ = make_harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process(new_service_scheduler, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 10
    # every alloc carries task resources + shared disk
    for a in placed:
        assert a.task_resources["web"].cpu == 500
        assert a.shared_resources.disk_mb == 150
        assert a.metrics is not None
    # allocs landed in state
    out = h.state.allocs_by_job(None, job.id, True)
    assert len(out) == 10
    h.assert_eval_status(s.EVAL_STATUS_COMPLETE)
    assert h.evals[0].queued_allocations == {"web": 0}


def test_service_register_no_nodes_blocked():
    h = Harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process(new_service_scheduler, ev)

    # no plan submitted, blocked eval created with failed TG metrics
    assert h.plans == []
    assert len(h.create_evals) == 1
    blocked = h.create_evals[0]
    assert blocked.status == s.EVAL_STATUS_BLOCKED
    assert blocked.previous_eval == ev.id
    update = h.evals[0]
    assert update.status == s.EVAL_STATUS_COMPLETE
    assert "web" in update.failed_tg_allocs
    assert update.failed_tg_allocs["web"].nodes_evaluated == 0
    assert update.blocked_eval == blocked.id


def test_service_register_infeasible_constraint_class_filtered():
    h, _ = make_harness(3)
    job = mock.job()
    job.constraints = [s.Constraint("${attr.kernel.name}", "windows", "=")]
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process(new_service_scheduler, ev)
    update = h.evals[0]
    metric = update.failed_tg_allocs["web"]
    # 3 nodes evaluated but only 1 full check thanks to computed-class cache
    assert metric.nodes_filtered == 3
    assert metric.coalesced_failures == 9
    blocked = h.create_evals[0]
    assert not blocked.escaped_computed_class
    assert blocked.class_eligibility  # classes recorded as ineligible
    assert all(v is False for v in blocked.class_eligibility.values())


def test_register_existing_allocs_ignored():
    h, _ = make_harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process(new_service_scheduler, ev)
    assert len(h.plans) == 1

    # Second eval for the same job version: everything ignored, no-op
    h2 = Harness(h.state)
    ev2 = register_eval(job)
    h2.process(new_service_scheduler, ev2)
    assert h2.plans == []
    h2.assert_eval_status(s.EVAL_STATUS_COMPLETE)


def test_job_update_destructive_evicts_and_places():
    h, _ = make_harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))

    # register new version with a changed task config (destructive)
    job2 = h.state.job_by_id(None, job.id).copy()
    job2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
    h.state.upsert_job(h.next_index(), job2)

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_service_scheduler, register_eval(job2))
    assert len(h2.plans) == 1
    plan = h2.plans[0]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(stopped) == 10
    assert len(placed) == 10
    for a in stopped:
        assert a.desired_status == s.ALLOC_DESIRED_STATUS_STOP


def test_job_update_inplace_when_tasks_unchanged():
    h, _ = make_harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))

    # bump priority only — in-place update
    job2 = h.state.job_by_id(None, job.id).copy()
    job2.priority = 80
    h.state.upsert_job(h.next_index(), job2)

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_service_scheduler, register_eval(job2))
    assert len(h2.plans) == 1
    plan = h2.plans[0]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert stopped == []          # nothing evicted
    assert len(placed) == 10      # all updated in place
    # in-place updates keep their node and previous ID
    originals = {a.id: a for a in h.state.allocs_by_job(None, job.id, True)}
    for a in placed:
        assert a.id in originals
        assert a.node_id == originals[a.id].node_id


def test_rolling_update_limit():
    h, _ = make_harness()
    job = mock.job()
    job.update = s.UpdateStrategy(stagger=30.0, max_parallel=3)
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))

    job2 = h.state.job_by_id(None, job.id).copy()
    job2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
    job2.update = s.UpdateStrategy(stagger=30.0, max_parallel=3)
    h.state.upsert_job(h.next_index(), job2)

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_service_scheduler, register_eval(job2))
    plan = h2.plans[0]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert len(stopped) == 3  # rolling limit
    # follow-up rolling eval created
    rolling = [e for e in h2.create_evals
               if e.triggered_by == s.EVAL_TRIGGER_ROLLING_UPDATE]
    assert len(rolling) == 1
    assert rolling[0].wait == 30.0


def test_node_down_marks_lost_and_replaces():
    h, nodes = make_harness(2)
    job = mock.job()
    job.task_groups[0].count = 2
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))

    # take one node down
    victim_allocs = [a for a in h.state.allocs_by_job(None, job.id, True)]
    victim_node = victim_allocs[0].node_id
    h.state.update_node_status(h.next_index(), victim_node, s.NODE_STATUS_DOWN)

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    ev = register_eval(job)
    ev.triggered_by = s.EVAL_TRIGGER_NODE_UPDATE
    h2.process(new_service_scheduler, ev)
    plan = h2.plans[0]
    lost = [a for allocs in plan.node_update.values() for a in allocs]
    assert lost, "expected lost allocs"
    for a in lost:
        assert a.client_status == s.ALLOC_CLIENT_STATUS_LOST
        assert a.desired_status == s.ALLOC_DESIRED_STATUS_STOP


def test_node_drain_migrates():
    h, nodes = make_harness(3)
    job = mock.job()
    job.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))

    allocs = h.state.allocs_by_job(None, job.id, True)
    drain_node = allocs[0].node_id
    h.state.update_node_drain(h.next_index(), drain_node, True)

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    ev = register_eval(job)
    ev.triggered_by = s.EVAL_TRIGGER_NODE_UPDATE
    h2.process(new_service_scheduler, ev)
    plan = h2.plans[0]
    stopped = [a for allocs_ in plan.node_update.values() for a in allocs_]
    n_on_drained = len([a for a in allocs if a.node_id == drain_node])
    assert len(stopped) == n_on_drained
    # migrated placements must avoid the draining node
    placed = [a for allocs_ in plan.node_allocation.values() for a in allocs_]
    assert len(placed) == n_on_drained
    for a in placed:
        assert a.node_id != drain_node


def test_job_deregister_stops_all():
    h, _ = make_harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))

    stopped_job = h.state.job_by_id(None, job.id).copy()
    stopped_job.stop = True
    h.state.upsert_job(h.next_index(), stopped_job)

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    ev = register_eval(job)
    ev.triggered_by = s.EVAL_TRIGGER_JOB_DEREGISTER
    h2.process(new_service_scheduler, ev)
    plan = h2.plans[0]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert len(stopped) == 10
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert placed == []


def test_distinct_hosts_limits_one_per_node():
    h, _ = make_harness(5)
    job = mock.job()
    job.constraints.append(s.Constraint(operand=s.CONSTRAINT_DISTINCT_HOSTS))
    job.task_groups[0].count = 5
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))
    plan = h.plans[0]
    placed_nodes = [nid for nid, allocs in plan.node_allocation.items() for _ in allocs]
    assert len(placed_nodes) == 5
    assert len(set(placed_nodes)) == 5  # all on distinct hosts


def test_distinct_hosts_infeasible_when_count_exceeds_nodes():
    h, _ = make_harness(3)
    job = mock.job()
    job.constraints.append(s.Constraint(operand=s.CONSTRAINT_DISTINCT_HOSTS))
    job.task_groups[0].count = 5
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))
    placed = [a for allocs in h.plans[0].node_allocation.values() for a in allocs]
    assert len(placed) == 3
    update = h.evals[0]
    assert update.failed_tg_allocs["web"].coalesced_failures == 1  # 2 failures coalesced


def test_reject_plan_creates_blocked_max_plans():
    h, _ = make_harness(2)
    h.planner = RejectPlan(h)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process(new_service_scheduler, ev)

    # all attempts rejected → failed status + blocked eval with max-plans
    blocked = [e for e in h.create_evals if e.triggered_by == s.EVAL_TRIGGER_MAX_PLANS]
    assert len(blocked) == 1
    update = h.evals[-1]
    assert update.status == s.EVAL_STATUS_FAILED


def test_batch_ignores_successful_terminal():
    h, _ = make_harness(2)
    job = mock.batch_job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    h.process(new_batch_scheduler, register_eval(job))
    allocs = h.state.allocs_by_job(None, job.id, True)
    assert len(allocs) == 1

    # mark it complete + successful
    done = allocs[0].copy()
    done.client_status = s.ALLOC_CLIENT_STATUS_COMPLETE
    done.task_states = {
        "web": s.TaskState(state=s.TASK_STATE_DEAD, events=[
            s.TaskEvent(type=s.TASK_TERMINATED, exit_code=0)])
    }
    h.state.update_allocs_from_client(h.next_index(), [done])

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_batch_scheduler, register_eval(job))
    # completed batch alloc must NOT be replaced
    assert h2.plans == []


def test_batch_failed_is_replaced():
    h, _ = make_harness(2)
    job = mock.batch_job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    h.process(new_batch_scheduler, register_eval(job))
    allocs = h.state.allocs_by_job(None, job.id, True)

    failed = allocs[0].copy()
    failed.client_status = s.ALLOC_CLIENT_STATUS_FAILED
    h.state.update_allocs_from_client(h.next_index(), [failed])

    h2 = Harness(h.state)
    h2._next_index = h._next_index
    h2.process(new_batch_scheduler, register_eval(job))
    placed = [a for allocs_ in h2.plans[0].node_allocation.values() for a in allocs_]
    assert len(placed) == 1
    assert placed[0].previous_allocation == failed.id


def test_anti_affinity_spreads_allocs():
    h, _ = make_harness(10)
    job = mock.job()
    job.task_groups[0].count = 10
    h.state.upsert_job(h.next_index(), job)
    h.process(new_service_scheduler, register_eval(job))
    placed_per_node = {nid: len(allocs)
                      for nid, allocs in h.plans[0].node_allocation.items()}
    # with anti-affinity and 10 nodes x 10 allocs, no node should be heavily
    # stacked (each collision costs 20 points vs binpack's max 18)
    assert max(placed_per_node.values()) <= 3


def test_plan_annotations():
    h, _ = make_harness(2)
    job = mock.job()
    job.task_groups[0].count = 2
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    ev.annotate_plan = True
    h.process(new_service_scheduler, ev)
    plan = h.plans[0]
    assert plan.annotations is not None
    desired = plan.annotations.desired_tg_updates["web"]
    assert desired.place == 2
