"""End-to-end data-plane integration: a real Client against an
in-process Server — register, schedule, run via mock driver, sync
status back, node failure handling
(reference: client/client_test.go against TestServer, SURVEY.md §4
item 4)."""
import time

import pytest

from nomad_tpu.structs import structs as s
from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server.server import Server, ServerConfig

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def server():
    srv = Server(ServerConfig(num_schedulers=1))
    srv.start()
    yield srv
    srv.shutdown()


@pytest.fixture
def client(server, tmp_path):
    cfg = ClientConfig(alloc_dir=str(tmp_path / "allocs"),
                       state_dir=str(tmp_path / "state"))
    c = Client(cfg, rpc=server)
    c.start()
    yield c
    c.shutdown()


def mock_driver_job(run_for="30s", count=1, job_type=s.JOB_TYPE_SERVICE,
                    **config):
    job = mock.job()
    job.type = job_type
    tg = job.task_groups[0]
    tg.count = count
    tg.restart_policy = s.RestartPolicy(attempts=0,
                                        mode=s.RESTART_POLICY_MODE_FAIL)
    for t in tg.tasks:
        t.driver = "mock_driver"
        t.config = {"run_for": run_for, **config}
        t.resources.networks = []
        t.services = []
    return job


class TestClientRegistration:
    def test_node_registers_and_heartbeats(self, server, client):
        assert wait_until(lambda: server.node_get(client.node.id) is not None)
        node = server.node_get(client.node.id)
        assert node.status in (s.NODE_STATUS_INIT, s.NODE_STATUS_READY)
        assert wait_until(
            lambda: server.node_get(client.node.id).status == s.NODE_STATUS_READY)
        # fingerprinted facts made it to the server
        assert node.attributes.get("cpu.arch")
        assert node.attributes.get("driver.mock_driver") == "1"
        assert node.resources.cpu > 0

    def test_client_stats(self, server, client):
        stats = client.stats()
        assert stats["node_id"] == client.node.id
        assert "host_stats" in stats


class TestEndToEndPlacement:
    def test_job_runs_on_client(self, server, client):
        wait_until(lambda: server.node_get(client.node.id) is not None and
                   server.node_get(client.node.id).status == s.NODE_STATUS_READY)
        job = mock_driver_job(run_for="30s")
        server.job_register(job)

        # scheduler places onto our node; client picks it up and runs it
        assert wait_until(
            lambda: any(a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
                        for a in server.job_allocations(job.id)))
        allocs = server.job_allocations(job.id)
        assert allocs[0].node_id == client.node.id
        assert client.num_allocs() == 1

        # task states synced upstream
        a = server.job_allocations(job.id)[0]
        assert a.task_states and all(
            ts.state == s.TASK_STATE_RUNNING for ts in a.task_states.values())

    def test_batch_job_completes(self, server, client):
        wait_until(lambda: server.node_get(client.node.id) is not None and
                   server.node_get(client.node.id).status == s.NODE_STATUS_READY)
        job = mock_driver_job(run_for="100ms", job_type=s.JOB_TYPE_BATCH)
        server.job_register(job)
        assert wait_until(
            lambda: any(a.client_status == s.ALLOC_CLIENT_STATUS_COMPLETE
                        for a in server.job_allocations(job.id)))

    def test_job_stop_kills_alloc(self, server, client):
        wait_until(lambda: server.node_get(client.node.id) is not None and
                   server.node_get(client.node.id).status == s.NODE_STATUS_READY)
        job = mock_driver_job(run_for="60s")
        server.job_register(job)
        assert wait_until(
            lambda: any(a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
                        for a in server.job_allocations(job.id)))

        server.job_deregister(job.id, purge=False)
        assert wait_until(
            lambda: all(a.client_terminal_status()
                        for a in server.job_allocations(job.id)))

    def test_failed_alloc_reported(self, server, client):
        wait_until(lambda: server.node_get(client.node.id) is not None and
                   server.node_get(client.node.id).status == s.NODE_STATUS_READY)
        job = mock_driver_job(run_for="10ms", job_type=s.JOB_TYPE_BATCH,
                              exit_code=1)
        server.job_register(job)
        assert wait_until(
            lambda: any(a.client_status == s.ALLOC_CLIENT_STATUS_FAILED
                        for a in server.job_allocations(job.id)))


class TestClientRestore:
    def test_state_restored_after_restart(self, server, tmp_path):
        cfg = ClientConfig(alloc_dir=str(tmp_path / "allocs"),
                           state_dir=str(tmp_path / "state"))
        c1 = Client(cfg, rpc=server)
        c1.start()
        try:
            wait_until(lambda: server.node_get(c1.node.id) is not None and
                       server.node_get(c1.node.id).status == s.NODE_STATUS_READY)
            job = mock_driver_job(run_for="60s")
            server.job_register(job)
            assert wait_until(lambda: c1.num_allocs() == 1)
            assert wait_until(
                lambda: any(a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
                            for a in server.job_allocations(job.id)))
        finally:
            c1.shutdown()

        # New client instance with same state dir restores the alloc runner
        c2 = Client(cfg, rpc=server)
        try:
            assert c2.num_allocs() == 1
        finally:
            c2.shutdown()
