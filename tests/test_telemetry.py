"""Telemetry tests (reference: armon/go-metrics usage; metric names per
website/source/docs/agent/telemetry.html.md)."""
import time

import conftest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import structs as s
from nomad_tpu.utils.telemetry import InmemSink, Telemetry


def wait_until(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestSink:
    def test_gauge_counter_sample_aggregation(self):
        sink = InmemSink(interval=60.0)
        t = Telemetry(sink)
        t.set_gauge("broker.total_ready", 3)
        t.incr_counter("rpc.query")
        t.incr_counter("rpc.query")
        t.add_sample("plan.evaluate", 12.5)
        t.add_sample("plan.evaluate", 7.5)
        latest = sink.latest()
        assert latest["Gauges"]["nomad.broker.total_ready"] == 3
        assert latest["Counters"]["nomad.rpc.query"]["count"] == 2
        samp = latest["Samples"]["nomad.plan.evaluate"]
        assert samp["count"] == 2 and samp["mean"] == 10.0
        assert samp["min"] == 7.5 and samp["max"] == 12.5

    def test_measure_records_milliseconds(self):
        sink = InmemSink(interval=60.0)
        t = Telemetry(sink)
        with t.measure("worker.invoke_scheduler.service"):
            time.sleep(0.02)
        samp = sink.latest()["Samples"]["nomad.worker.invoke_scheduler.service"]
        assert samp["count"] == 1 and samp["min"] >= 15.0

    def test_interval_ring_rolls(self):
        sink = InmemSink(interval=0.05, retain=3)
        for i in range(5):
            sink.set_gauge("g", i)
            time.sleep(0.06)
        data = sink.data()
        assert len(data) <= 3


class TestServerEmitters:
    def test_hot_path_metrics_emitted(self):
        srv = Server(ServerConfig(num_schedulers=1))
        srv.start()
        try:
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            srv.node_register(node)
            job = mock.job()
            job.task_groups[0].count = 2
            for t in job.task_groups[0].tasks:
                t.resources.networks = []
            srv.job_register(job)
            assert wait_until(lambda: len(
                srv.state.allocs_by_job(None, job.id, True)) == 2)

            def emitted():
                latest = srv.metrics.sink.latest()
                g, samp = latest["Gauges"], latest["Samples"]
                return ("nomad.broker.total_ready" in g
                        and "nomad.plan.queue_depth" in g
                        and "nomad.heartbeat.active" in g
                        and any(k.startswith("nomad.worker.invoke_scheduler")
                                for k in samp)
                        and "nomad.plan.evaluate" in samp
                        and "nomad.plan.apply" in samp)

            assert wait_until(emitted, 10.0), \
                srv.metrics.sink.latest()
            stats = srv.stats()
            assert "metrics_gauges" in stats and "metrics_samples" in stats
        finally:
            srv.shutdown()

    def test_metrics_http_endpoint(self, tmp_path):
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig
        import json
        import urllib.request

        cfg = conftest.dev_test_config()
        cfg.client.enabled = False
        agent = Agent(cfg)
        agent.start()
        try:
            assert wait_until(lambda: bool(
                agent.server.metrics.sink.latest()["Gauges"]))
            with urllib.request.urlopen(
                    agent.http.address + "/v1/metrics") as resp:
                data = json.loads(resp.read())
            assert data and "Gauges" in data[-1]
            assert "nomad.broker.total_ready" in data[-1]["Gauges"]
        finally:
            agent.shutdown()
