"""Telemetry tests (reference: armon/go-metrics usage; metric names per
website/source/docs/agent/telemetry.html.md)."""
import time

import conftest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import structs as s
from nomad_tpu.utils.telemetry import (EXACT_WINDOW, InmemSink, Telemetry,
                                       _Histogram, render_prometheus)


def wait_until(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestSink:
    def test_gauge_counter_sample_aggregation(self):
        sink = InmemSink(interval=60.0)
        t = Telemetry(sink)
        t.set_gauge("broker.total_ready", 3)
        t.incr_counter("rpc.query")
        t.incr_counter("rpc.query")
        t.add_sample("plan.evaluate", 12.5)
        t.add_sample("plan.evaluate", 7.5)
        latest = sink.latest()
        assert latest["Gauges"]["nomad.broker.total_ready"] == 3
        assert latest["Counters"]["nomad.rpc.query"]["count"] == 2
        samp = latest["Samples"]["nomad.plan.evaluate"]
        assert samp["count"] == 2 and samp["mean"] == 10.0
        assert samp["min"] == 7.5 and samp["max"] == 12.5

    def test_measure_records_milliseconds(self):
        sink = InmemSink(interval=60.0)
        t = Telemetry(sink)
        with t.measure("worker.invoke_scheduler.service"):
            time.sleep(0.02)
        samp = sink.latest()["Samples"]["nomad.worker.invoke_scheduler.service"]
        assert samp["count"] == 1 and samp["min"] >= 15.0

    def test_interval_ring_rolls(self):
        sink = InmemSink(interval=0.05, retain=3)
        for i in range(5):
            sink.set_gauge("g", i)
            time.sleep(0.06)
        data = sink.data()
        assert len(data) <= 3


class TestHistogramPercentiles:
    def test_small_n_quantiles_are_exact(self):
        h = _Histogram()
        for v in range(1, 101):  # 1..100, well inside the exact window
            h.add(float(v))
        assert h.percentile(0.50) == 51.0
        assert h.percentile(0.95) == 96.0
        assert h.percentile(0.99) == 100.0

    def test_large_n_quantiles_bounded_by_bucket_width(self):
        h = _Histogram()
        n = EXACT_WINDOW * 8  # force the bucketed estimator
        for i in range(n):
            h.add(100.0 * (i + 1) / n)  # uniform on (0, 100]
        # true p50/p95 are 50/95; the containing buckets are (25, 50]
        # and (50, 100], so the estimate may be off by a bucket width
        # but must stay inside the containing bucket's bounds.
        assert 25.0 <= h.percentile(0.50) <= 50.0
        assert 50.0 <= h.percentile(0.95) <= 100.0
        # quantiles never escape the observed range
        assert h.min <= h.percentile(0.01) <= h.percentile(0.99) <= h.max

    def test_summary_carries_quantiles_through_sink(self):
        sink = InmemSink(interval=60.0)
        t = Telemetry(sink)
        for v in (1.0, 2.0, 3.0, 4.0, 100.0):
            t.add_sample("plan.evaluate", v)
        samp = sink.latest()["Samples"]["nomad.plan.evaluate"]
        for q in ("p50", "p95", "p99"):
            assert q in samp
        assert samp["p50"] == 3.0
        assert samp["p99"] == 100.0

    def test_empty_histogram_percentiles(self):
        h = _Histogram()
        assert h.percentile(0.5) == 0.0


def parse_prometheus(text):
    """Parse exposition text into {name: value} + {name: type}; quantile
    series keep their label in the key (`name{quantile="0.5"}`)."""
    values, types = {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split()
            types[name] = typ
            continue
        assert not line.startswith("#"), line
        key, val = line.rsplit(" ", 1)
        values[key] = float(val)
    return values, types


class TestPrometheusRendering:
    def test_render_gauges_counters_summaries(self):
        sink = InmemSink(interval=60.0)
        t = Telemetry(sink)
        t.set_gauge("broker.total_ready", 3)
        t.incr_counter("rpc.request", 2)
        t.incr_counter("rpc.request", 1)
        for v in (5.0, 10.0, 15.0):
            t.add_sample("plan.evaluate", v)
        values, types = parse_prometheus(render_prometheus(sink.latest()))

        assert values["nomad_broker_total_ready"] == 3.0
        assert types["nomad_broker_total_ready"] == "gauge"
        assert values["nomad_rpc_request_total"] == 3.0
        assert types["nomad_rpc_request_total"] == "counter"
        assert types["nomad_plan_evaluate"] == "summary"
        assert values['nomad_plan_evaluate{quantile="0.5"}'] == 10.0
        assert values["nomad_plan_evaluate_sum"] == 30.0
        assert values["nomad_plan_evaluate_count"] == 3.0

    def test_counters_and_sample_totals_monotonic_across_rolls(self):
        """Scrapers need monotonic series: counter totals and summary
        _sum/_count must accumulate across interval rolls even though
        the interval aggregates reset."""
        sink = InmemSink(interval=0.05, retain=2)
        t = Telemetry(sink)
        t.incr_counter("rpc.request", 5)
        t.add_sample("plan.evaluate", 10.0)
        time.sleep(0.07)  # force an interval roll
        t.incr_counter("rpc.request", 2)
        t.add_sample("plan.evaluate", 30.0)
        values, _ = parse_prometheus(render_prometheus(sink.latest()))
        assert values["nomad_rpc_request_total"] == 7.0
        assert values["nomad_plan_evaluate_count"] == 2.0
        assert values["nomad_plan_evaluate_sum"] == 40.0
        # the quantile estimate itself is interval-local (newest only)
        assert values['nomad_plan_evaluate{quantile="0.5"}'] == 30.0
        # a key whose interval rolled quiet keeps its _sum/_count series
        time.sleep(0.07)
        sink.set_gauge("g", 1)  # rolls the interval; no fresh samples
        values, _ = parse_prometheus(render_prometheus(sink.latest()))
        assert values["nomad_plan_evaluate_count"] == 2.0
        assert values["nomad_plan_evaluate_sum"] == 40.0
        assert 'nomad_plan_evaluate{quantile="0.5"}' not in values

    def test_metric_names_sanitized(self):
        sink = InmemSink(interval=60.0)
        sink.set_gauge("worker.invoke_scheduler._core", 1.0)
        values, _ = parse_prometheus(render_prometheus(sink.latest()))
        assert values["worker_invoke_scheduler__core"] == 1.0

    def test_http_prometheus_endpoint(self):
        """Acceptance: /v1/metrics?format=prometheus serves valid
        exposition including p50/p95/p99 for nomad.plan.evaluate and
        nomad.worker.invoke_scheduler, plus the broker gauges."""
        import urllib.request

        from nomad_tpu.agent.agent import Agent

        cfg = conftest.dev_test_config()
        cfg.client.enabled = False
        agent = Agent(cfg)
        agent.start()
        try:
            # Quantiles render from the newest sink interval only; stretch
            # it so a slow CI box can't roll the scheduling samples out of
            # the window before the scrape below.
            agent.server.metrics.sink.interval = 3600.0
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            agent.server.node_register(node)
            job = mock.job()
            for t in job.task_groups[0].tasks:
                t.resources.networks = []
            agent.server.job_register(job)
            assert wait_until(lambda: agent.server.state.allocs_by_job(
                None, job.id, True))
            assert wait_until(lambda: "nomad.broker.total_ready"
                              in agent.server.metrics.sink.latest()["Gauges"])

            with urllib.request.urlopen(
                    agent.http.address
                    + "/v1/metrics?format=prometheus") as resp:
                assert resp.headers["Content-Type"].startswith("text/plain")
                values, types = parse_prometheus(resp.read().decode())

            assert "nomad_broker_total_ready" in values
            for base in ("nomad_plan_evaluate",
                         "nomad_worker_invoke_scheduler"):
                assert types[base] == "summary"
                for q in ("0.5", "0.95", "0.99"):
                    assert f'{base}{{quantile="{q}"}}' in values, (base, q)
                assert values[f"{base}_count"] >= 1.0
        finally:
            agent.shutdown()


class TestServerEmitters:
    def test_hot_path_metrics_emitted(self):
        srv = Server(ServerConfig(num_schedulers=1))
        srv.start()
        try:
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            srv.node_register(node)
            job = mock.job()
            job.task_groups[0].count = 2
            for t in job.task_groups[0].tasks:
                t.resources.networks = []
            srv.job_register(job)
            assert wait_until(lambda: len(
                srv.state.allocs_by_job(None, job.id, True)) == 2)

            def emitted():
                latest = srv.metrics.sink.latest()
                g, samp = latest["Gauges"], latest["Samples"]
                return ("nomad.broker.total_ready" in g
                        and "nomad.plan.queue_depth" in g
                        and "nomad.heartbeat.active" in g
                        and any(k.startswith("nomad.worker.invoke_scheduler")
                                for k in samp)
                        and "nomad.plan.evaluate" in samp
                        and "nomad.plan.apply" in samp)

            assert wait_until(emitted, 10.0), \
                srv.metrics.sink.latest()
            stats = srv.stats()
            assert "metrics_gauges" in stats and "metrics_samples" in stats
        finally:
            srv.shutdown()

    def test_metrics_http_endpoint(self, tmp_path):
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig
        import json
        import urllib.request

        cfg = conftest.dev_test_config()
        cfg.client.enabled = False
        agent = Agent(cfg)
        agent.start()
        try:
            assert wait_until(lambda: bool(
                agent.server.metrics.sink.latest()["Gauges"]))
            with urllib.request.urlopen(
                    agent.http.address + "/v1/metrics") as resp:
                data = json.loads(resp.read())
            assert data and "Gauges" in data[-1]
            assert "nomad.broker.total_ready" in data[-1]["Gauges"]
        finally:
            agent.shutdown()
