"""Device-resident node-state cache tests (PR 5 tentpole).

Differential coverage: the resident usage mirror updated from the state
store's usage-delta feed (``allocs_since``) must stay BIT-IDENTICAL to a
full re-encode across randomized sequences of plan applies, evictions,
client terminations, node drains, and node registrations — asserted by
arming the built-in differential guard at every batch.  Plus the
staleness fence, the feed-gap fallback (with its NodeStateDelta event),
and the breaker trip on injected resident corruption (fault.py
``ops.resident_state``).
"""
import random

import numpy as np
import pytest

from nomad_tpu import fault, mock
from nomad_tpu.ops import resident
from nomad_tpu.ops.batch_sched import TPUBatchScheduler
from nomad_tpu.ops.breaker import KernelCircuitBreaker
from nomad_tpu.scheduler import Harness
from nomad_tpu.server import event_broker
from nomad_tpu.structs import structs as s


def make_node():
    node = mock.node()
    node.resources.networks = []
    node.reserved.networks = []
    node.compute_class()
    return node


def make_job(count, prio=50):
    job = mock.job()
    job.priority = prio
    job.task_groups[0].count = count
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.networks = []
    return job


def reg_eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def schedule(h, jobs, register=True, **sched_kwargs):
    if register:
        for j in jobs:
            h.state.upsert_job(h.next_index(), j)
    evals = [reg_eval(j) for j in jobs]
    sched = TPUBatchScheduler(h.logger, h.snapshot(), h, **sched_kwargs)
    return sched.schedule_batch(evals)


@pytest.fixture(autouse=True)
def _fresh_resident(monkeypatch):
    """Each test starts with an empty resident cache, residency forced
    on, and the differential guard armed at EVERY delta hit — the guard
    IS the bit-identity assertion."""
    monkeypatch.setenv("NOMAD_TPU_RESIDENT", "1")
    monkeypatch.setenv("NOMAD_TPU_RESIDENT_GUARD_EVERY", "1")
    resident.reset_counters()
    yield
    resident.reset_counters()


class TestDeltaFeed:
    """StateStore.allocs_since — the usage-delta log."""

    def test_upsert_update_evict_and_slab_deltas(self):
        h = Harness()
        st = h.state
        node = make_node()
        st.upsert_node(1, node)
        job = make_job(1)
        st.upsert_job(2, job)

        a = s.Allocation(id=s.generate_uuid(), job_id=job.id, job=job,
                         node_id=node.id, task_group="web",
                         resources=s.Resources(cpu=100, memory_mb=200))
        st.upsert_allocs(3, [a])
        assert st.allocs_since(2) == [(node.id, (100, 200, 0, 0))]
        assert st.allocs_since(3) == []

        # Client completion: live → terminal subtracts the usage.
        done = s._fast_copy(a)
        done.client_status = s.ALLOC_CLIENT_STATUS_COMPLETE
        st.update_allocs_from_client(4, [done])
        assert st.allocs_since(3) == [(node.id, (-100, -200, 0, 0))]

        # Slab insert expands lazily, one feed entry per node row.
        proto = s.Allocation(job_id=job.id, job=job, task_group="web",
                             resources=s.Resources(cpu=10, memory_mb=20))
        slab = s.AllocSlab(proto=proto, ids=[s.generate_uuid() for _ in range(3)],
                           names=["a", "b", "c"],
                           node_ids=[node.id, node.id, node.id])
        st.upsert_slabs(5, [slab])
        assert st.allocs_since(4) == [(node.id, (30, 60, 0, 0))]

        # Pre-floor queries answer None after a restore-style reset.
        st._alloc_log_floor = 10
        assert st.allocs_since(4) is None

    def test_snapshot_has_independent_feed(self):
        """The log is shared behind a length cursor: parent appends are
        invisible to the snapshot, a snapshot write (dry-run world)
        copies first and never leaks into the parent's feed, and a
        parent trim leaves the snapshot's view intact."""
        h = Harness()
        st = h.state
        node = make_node()
        st.upsert_node(1, node)
        snap = st.snapshot()
        a = s.Allocation(id=s.generate_uuid(), job_id="j", node_id=node.id,
                         task_group="web",
                         resources=s.Resources(cpu=5, memory_mb=5))
        st.upsert_allocs(2, [a])
        assert st.allocs_since(1) and snap.allocs_since(1) == []

        # Snapshot write: copy-on-write, nothing leaks to the parent.
        b = s.Allocation(id=s.generate_uuid(), job_id="j", node_id=node.id,
                         task_group="web",
                         resources=s.Resources(cpu=7, memory_mb=7))
        snap.upsert_allocs(3, [b])
        assert snap.allocs_since(1) == [(node.id, (7, 7, 0, 0))]
        assert st.allocs_since(2) == []

        # Parent trim replaces the list object; an older snapshot's
        # cursor into the pre-trim list stays valid.
        snap2 = st.snapshot()
        st._alloc_log_weight = 10 ** 9          # force next append to trim
        st.upsert_allocs(4, [s._fast_copy(a)])  # no-op delta, then a real one
        c = s.Allocation(id=s.generate_uuid(), job_id="j", node_id=node.id,
                         task_group="web",
                         resources=s.Resources(cpu=9, memory_mb=9))
        st.upsert_allocs(5, [c])
        assert snap2.allocs_since(1) == [(node.id, (5, 5, 0, 0))]


class TestResidentDifferential:
    def test_randomized_sequence_bit_identical(self):
        """Randomized plan applies / evictions / terminations / drains /
        node registrations: with the guard armed at every hit, any drift
        between the resident mirror and a full re-encode trips
        GUARD_MISMATCHES — which must stay zero."""
        rng = random.Random(7)
        h = Harness()
        for _ in range(24):
            h.state.upsert_node(h.next_index(), make_node())

        placed_jobs = []
        for round_no in range(12):
            op = rng.randrange(5)
            if op == 0 and placed_jobs:
                # Evict some of a job's allocs (plan-apply eviction twin).
                job = rng.choice(placed_jobs)
                victims = [a for a in
                           h.state.allocs_by_job(None, job.id, True)
                           if not a.terminal_status()][:2]
                updates = []
                for v in victims:
                    ev = s._fast_copy(v)
                    ev.desired_status = s.ALLOC_DESIRED_STATUS_EVICT
                    updates.append(ev)
                if updates:
                    h.state.upsert_allocs(h.next_index(), updates)
            elif op == 1 and placed_jobs:
                # Client-side termination frees capacity.
                job = rng.choice(placed_jobs)
                live = [a for a in
                        h.state.allocs_by_job(None, job.id, True)
                        if not a.terminal_status()][:3]
                updates = []
                for a in live:
                    u = s._fast_copy(a)
                    u.client_status = s.ALLOC_CLIENT_STATUS_COMPLETE
                    updates.append(u)
                if updates:
                    h.state.update_allocs_from_client(h.next_index(),
                                                      updates)
            elif op == 2:
                # Node registration: nodes-table index changes, so the
                # static key changes → full re-encode path.
                h.state.upsert_node(h.next_index(), make_node())
            elif op == 3:
                node = rng.choice(h.state.nodes(None))
                h.state.update_node_drain(h.next_index(), node.id,
                                          not node.drain)

            jobs = [make_job(rng.randrange(1, 4)) for _ in range(2)]
            stats = schedule(h, jobs)
            assert stats.num_evals == 2
            placed_jobs.extend(jobs)

        assert resident.GUARD_MISMATCHES == 0
        assert resident.GUARD_RUNS > 0
        assert resident.HITS > 0, "delta path never exercised"
        assert resident.FULL_REENCODES > 1, (
            "node churn should have forced key-change re-encodes")

    def test_staleness_fence_serves_old_snapshot_without_regressing(self):
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())
        schedule(h, [make_job(2)])      # cold install
        schedule(h, [make_job(2)])      # delta hit advances the mirror

        # A scheduler handed an OLD snapshot must full re-encode from it
        # (fence) and leave the newer resident mirror untouched.
        job = make_job(1)
        h.state.upsert_job(h.next_index(), job)
        stale = h.snapshot()            # knows the job
        # The mirror sits at each batch's PRE-batch allocs index, so two
        # more batches push it past ``stale``'s view.
        schedule(h, [make_job(2)])
        schedule(h, [make_job(2)])
        cached = resident._STATE.alloc_index

        sched = TPUBatchScheduler(h.logger, stale, h)
        stats = sched.schedule_batch([reg_eval(job)])
        assert stats.staleness_fences == 1
        assert stats.full_reencodes == 1
        assert stats.resident_hits == 0
        assert resident._STATE.alloc_index == cached
        assert len(h.state.allocs_by_job(None, job.id, True)) == 1

    def test_feed_gap_forces_full_reencode_and_event(self):
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())
        schedule(h, [make_job(2)])
        assert resident._STATE is not None

        broker = event_broker.EventBroker(
            index_source=lambda: h.state.latest_index())
        event_broker.register(broker)
        event_broker.clear_recent()
        try:
            # Simulate the log trimming past the cached index.
            h.state._alloc_log_floor = resident._STATE.alloc_index + 10
            h.state._alloc_log.clear()
            stats = schedule(h, [make_job(2)])
            assert stats.full_reencodes == 1 and stats.resident_hits == 0
            deltas = [e for e in event_broker.recent()
                      if e.type == "NodeStateDelta"]
            assert deltas and deltas[-1].payload["Reason"] == "feed_gap"
        finally:
            event_broker.unregister(broker)
            event_broker.clear_recent()

    def test_injected_corruption_trips_breaker(self):
        """fault.py ``ops.resident_state`` corrupt: the guard detects the
        perturbed row, feeds the breaker, invalidates, and the batch
        still places correctly from the fresh full encode."""
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=3600.0)
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())
        schedule(h, [make_job(2)], breaker=brk)   # cold install

        with fault.scenario({"seed": 3, "faults": [
                {"point": "ops.resident_state", "action": "corrupt",
                 "times": 1}]}):
            job = make_job(2)
            stats = schedule(h, [job], breaker=brk)

        assert resident.GUARD_MISMATCHES == 1
        assert resident._STATE is None or resident._STATE.hits == 0
        assert stats.full_reencodes == 1
        assert brk.state == "open", brk.state
        # Scheduling stayed correct: the batch ran on the fresh encode.
        assert len([a for a in h.state.allocs_by_job(None, job.id, True)
                    if not a.terminal_status()]) == 2

    def test_residency_off_env_disables_delta_path(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_RESIDENT", "0")
        h = Harness()
        for _ in range(8):
            h.state.upsert_node(h.next_index(), make_node())
        schedule(h, [make_job(2)])
        stats = schedule(h, [make_job(2)])
        assert stats.resident_hits == 0 and stats.delta_rows == 0
        assert resident.HITS == 0


class TestPipelinedStream:
    def test_stream_matches_serial_placements(self):
        """schedule_stream (double-buffered) places exactly what the
        serial per-batch path would: every job fully placed, usage mirror
        clean (guard at every hit)."""
        h = Harness()
        for _ in range(16):
            h.state.upsert_node(h.next_index(), make_node())
        batches, all_jobs = [], []
        for _ in range(5):
            jobs = [make_job(2) for _ in range(2)]
            for j in jobs:
                h.state.upsert_job(h.next_index(), j)
            all_jobs.extend(jobs)  # registered above; stream runs below
            batches.append([reg_eval(j) for j in jobs])
        sched = TPUBatchScheduler(h.logger, h.snapshot(), h)
        stats = sched.schedule_stream(batches,
                                      state_source=lambda: h.snapshot())
        assert len(stats) == 5
        for job in all_jobs:
            live = [a for a in h.state.allocs_by_job(None, job.id, True)
                    if not a.terminal_status()]
            assert len(live) == 2, (job.id, len(live))
        assert resident.GUARD_MISMATCHES == 0
        assert sum(st.resident_hits for st in stats) >= 4

    def test_pipelined_batch_worker_places(self, monkeypatch):
        """NOMAD_TPU_PIPELINE=1: the BatchWorker's split-phase drain
        places a stream of jobs end-to-end through a live server."""
        monkeypatch.setenv("NOMAD_TPU_PIPELINE", "1")
        import time

        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=1,
                                  use_tpu_batch_worker=True, batch_size=8))
        srv.start()
        try:
            for _ in range(12):
                srv.node_register(make_node())
            jobs = []
            for _ in range(9):
                job = make_job(2)
                srv.job_register(job)
                jobs.append(job)
            deadline = time.time() + 60
            while time.time() < deadline:
                if all(len(srv.state.allocs_by_job(None, j.id, True)) == 2
                       for j in jobs):
                    break
                time.sleep(0.05)
            for j in jobs:
                assert len(srv.state.allocs_by_job(None, j.id, True)) == 2
        finally:
            srv.shutdown()


class TestDonatedDeviceMirror:
    """ISSUE 13: the donated device-resident usage mirror.

    The mirror is loaned to the fused kernel as a donated jit argument
    and returned aliased; ops/resident.py catches it up in place with
    donated scatter-adds.  These tests pin (a) bit-identity of the
    mirror and of placements against the sparse-delta upload path after
    N donated applies, and (b) that the PR 5 differential guard +
    breaker still fire when the mirror is corrupted under the donated
    regime (fault point ``ops.resident_state``)."""

    def _build(self, n_nodes=8):
        h = Harness()
        for i in range(n_nodes):
            node = make_node()
            node.id = f"dev-node-{i:02d}"
            node.name = node.id
            h.state.upsert_node(h.next_index(), node)
        return h

    def _stream(self, h, batches, **sched_kwargs):
        placements = []
        for _ in range(batches):
            job = make_job(2)
            schedule(h, [job], **sched_kwargs)
            placements.append(sorted(
                a.node_id for a in h.state.allocs_by_job(None, job.id,
                                                         True)))
        return placements

    def test_donated_applies_bit_identical_to_delta_path(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_RNG_SEED", "424242")

        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "1")
        h_dev = self._build()
        pl_dev = self._stream(h_dev, 5)
        assert resident.DEV_INSTALLS == 1, (
            "the device mirror must install exactly once and then "
            "round-trip in place")
        assert resident.DEV_APPLIES >= 4
        st = resident._STATE
        assert st is not None and st.used_dev is not None
        np.testing.assert_array_equal(
            np.asarray(st.used_dev).astype(np.int64), st.used)
        host_mirror = st.used.copy()

        resident.reset_counters()
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "0")
        h_dl = self._build()
        pl_dl = self._stream(h_dl, 5)
        assert resident.DEV_INSTALLS == 0 and resident.DEV_APPLIES == 0
        assert pl_dev == pl_dl
        np.testing.assert_array_equal(resident._STATE.used, host_mirror)

    def test_take_give_loan_protocol(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "1")
        h = self._build()
        self._stream(h, 2)
        st = resident._STATE
        assert st is not None and st.used_dev is not None
        key, idx = st.key, st.alloc_index
        # A stale (older-index) taker gets nothing and must not steal
        # the mirror.
        assert resident.take_device_used(key, idx - 1, st.used) is None
        assert st.used_dev is not None
        # The matching taker gets the loan; the slot empties while out.
        dev = resident.take_device_used(key, idx, st.used)
        assert dev is not None and st.used_dev is None
        # Giving back under a moved-on index drops the handle.
        resident.give_device_used(key, idx - 1, dev)
        assert st.used_dev is None
        resident.give_device_used(key, idx, dev)
        assert st.used_dev is dev

    def test_corrupted_donated_mirror_trips_guard_and_breaker(
            self, monkeypatch):
        """The chaos fault perturbs host AND device mirrors identically
        (mirror drift); the differential guard catches it, feeds the
        breaker, and invalidates — dropping the donated buffer too."""
        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "1")
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=3600.0)
        h = self._build()
        schedule(h, [make_job(2)], breaker=brk)      # cold install
        schedule(h, [make_job(2)], breaker=brk)      # donated apply
        assert resident.DEV_APPLIES >= 1

        with fault.scenario({"seed": 5, "faults": [
                {"point": "ops.resident_state", "action": "corrupt",
                 "times": 1}]}):
            job = make_job(2)
            stats = schedule(h, [job], breaker=brk)

        assert resident.GUARD_MISMATCHES == 1
        assert brk.state == "open", brk.state
        assert resident._STATE is None or resident._STATE.used_dev is None
        assert stats.full_reencodes == 1
        assert len([a for a in h.state.allocs_by_job(None, job.id, True)
                    if not a.terminal_status()]) == 2

    def test_device_mirror_drift_guard(self, monkeypatch):
        """Drift in the DONATED buffer alone (host mirror clean — the
        aliasing-bug twin) is caught by the device-vs-host compare at
        guard cadence: breaker fed, donated buffer dropped, host mirror
        survives."""
        import jax.numpy as jnp

        monkeypatch.setenv("NOMAD_TPU_RESIDENT_DEVICE", "1")
        brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                                   cooldown=3600.0)
        h = self._build()
        schedule(h, [make_job(2)], breaker=brk)
        schedule(h, [make_job(2)], breaker=brk)
        st = resident._STATE
        assert st is not None and st.used_dev is not None
        # Perturb ONLY the device copy.
        st.used_dev = jnp.asarray(np.asarray(st.used_dev)
                                  + np.int32(7))
        job = make_job(2)
        schedule(h, [job], breaker=brk)
        assert resident.DEV_GUARD_MISMATCHES == 1
        assert brk.agreement() < 1.0
        st = resident._STATE
        assert st is None or st.used_dev is None or \
            np.array_equal(np.asarray(st.used_dev).astype(np.int64),
                           st.used)
