"""Job diff + plan annotation tests (reference: nomad/structs/diff_test.go,
scheduler/annotate_test.go)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig


@pytest.fixture()
def server():
    srv = Server(ServerConfig(num_schedulers=1))
    srv.start()
    yield srv
    srv.shutdown()
from nomad_tpu.scheduler.annotate import (
    ANNOTATION_FORCES_CREATE, ANNOTATION_FORCES_DESTROY,
    ANNOTATION_FORCES_DESTRUCTIVE_UPDATE, ANNOTATION_FORCES_INPLACE_UPDATE,
    UPDATE_TYPE_CREATE, UPDATE_TYPE_DESTROY, annotate)
from nomad_tpu.structs import structs as s
from nomad_tpu.structs.diff import (DIFF_TYPE_ADDED, DIFF_TYPE_DELETED,
                                    DIFF_TYPE_EDITED, DIFF_TYPE_NONE,
                                    go_name, job_diff, task_diff,
                                    task_group_diff)


def test_go_name():
    assert go_name("kill_timeout") == "KillTimeout"
    assert go_name("count") == "Count"
    assert go_name("memory_mb") == "MemoryMB"
    assert go_name("cpu") == "CPU"


def test_identical_jobs_no_diff():
    job = mock.job()
    d = job_diff(job, job.copy())
    assert d.type == DIFF_TYPE_NONE
    assert not d.fields
    assert not d.task_groups


def test_job_added_and_deleted():
    job = mock.job()
    assert job_diff(None, job).type == DIFF_TYPE_ADDED
    assert job_diff(job, None).type == DIFF_TYPE_DELETED


def test_job_different_ids_error():
    a, b = mock.job(), mock.job()
    try:
        job_diff(a, b)
        assert False, "expected ValueError"
    except ValueError:
        pass


def test_primitive_field_edit():
    old = mock.job()
    new = old.copy()
    new.priority = old.priority + 10
    d = job_diff(old, new)
    assert d.type == DIFF_TYPE_EDITED
    f = next(f for f in d.fields if f.name == "Priority")
    assert f.type == DIFF_TYPE_EDITED
    assert f.old == str(old.priority)
    assert f.new == str(new.priority)


def test_datacenters_set_diff():
    old = mock.job()
    old.datacenters = ["dc1", "dc2"]
    new = old.copy()
    new.datacenters = ["dc1", "dc3"]
    d = job_diff(old, new)
    dcs = [f for f in d.fields if f.name == "Datacenters"]
    types = sorted(f.type for f in dcs)
    assert types == [DIFF_TYPE_ADDED, DIFF_TYPE_DELETED]


def test_constraint_added():
    old = mock.job()
    new = old.copy()
    new.constraints = list(new.constraints) + [
        s.Constraint(ltarget="${attr.kernel.name}", rtarget="linux",
                     operand="=")]
    d = job_diff(old, new)
    cons = [o for o in d.objects if o.name == "Constraint"]
    assert any(o.type == DIFF_TYPE_ADDED for o in cons)


def test_task_group_count_change():
    old = mock.job()
    new = old.copy()
    new.task_groups[0].count = old.task_groups[0].count + 2
    d = job_diff(old, new)
    assert len(d.task_groups) == 1
    tg = d.task_groups[0]
    assert tg.type == DIFF_TYPE_EDITED
    f = next(f for f in tg.fields if f.name == "Count")
    assert f.type == DIFF_TYPE_EDITED


def test_task_group_added_removed():
    old = mock.job()
    new = old.copy()
    extra = old.task_groups[0].copy()
    extra.name = "extra"
    new.task_groups.append(extra)
    d = job_diff(old, new)
    assert any(tg.type == DIFF_TYPE_ADDED and tg.name == "extra"
               for tg in d.task_groups)
    d2 = job_diff(new, old)
    assert any(tg.type == DIFF_TYPE_DELETED and tg.name == "extra"
               for tg in d2.task_groups)


def test_task_env_and_config_diff():
    old = mock.job()
    new = old.copy()
    t = new.task_groups[0].tasks[0]
    t.env = dict(t.env)
    t.env["NEW_VAR"] = "x"
    t.config = dict(t.config)
    t.config["command"] = "/bin/other"
    d = job_diff(old, new)
    td = d.task_groups[0].tasks[0]
    assert td.type == DIFF_TYPE_EDITED
    assert any(f.name == "Env[NEW_VAR]" and f.type == DIFF_TYPE_ADDED
               for f in td.fields)
    cfg = next(o for o in td.objects if o.name == "Config")
    assert any(f.name == "Config[command]" for f in cfg.fields)


def test_task_resources_diff():
    old = mock.job()
    new = old.copy()
    new.task_groups[0].tasks[0].resources = \
        old.task_groups[0].tasks[0].resources.copy()
    new.task_groups[0].tasks[0].resources.cpu += 100
    d = job_diff(old, new)
    td = d.task_groups[0].tasks[0]
    res = next(o for o in td.objects if o.name == "Resources")
    assert res.type == DIFF_TYPE_EDITED
    assert any(f.name == "CPU" for f in res.fields)


# -- annotate ---------------------------------------------------------------


def test_annotate_count_change():
    old = mock.job()
    new = old.copy()
    new.task_groups[0].count = old.task_groups[0].count + 3
    d = job_diff(old, new)
    annotate(d, None)
    f = next(f for f in d.task_groups[0].fields if f.name == "Count")
    assert ANNOTATION_FORCES_CREATE in f.annotations

    d2 = job_diff(new, old)
    annotate(d2, None)
    f2 = next(f for f in d2.task_groups[0].fields if f.name == "Count")
    assert ANNOTATION_FORCES_DESTROY in f2.annotations


def test_annotate_updates_map():
    old = mock.job()
    new = old.copy()
    new.task_groups[0].count += 1
    d = job_diff(old, new)
    ann = s.PlanAnnotations(desired_tg_updates={
        new.task_groups[0].name: s.DesiredUpdates(place=1, ignore=2, stop=3)})
    annotate(d, ann)
    tg = d.task_groups[0]
    assert tg.updates[UPDATE_TYPE_CREATE] == 1
    assert tg.updates[UPDATE_TYPE_DESTROY] == 3


def test_annotate_task_destructive_vs_inplace():
    old = mock.job()
    new = old.copy()
    new.task_groups[0].tasks[0].driver = "raw_exec"
    d = job_diff(old, new)
    annotate(d, None)
    td = d.task_groups[0].tasks[0]
    assert ANNOTATION_FORCES_DESTRUCTIVE_UPDATE in td.annotations

    # KillTimeout-only change is in-place
    new2 = old.copy()
    new2.task_groups[0].tasks[0].kill_timeout = 99.0
    d2 = job_diff(old, new2)
    annotate(d2, None)
    td2 = d2.task_groups[0].tasks[0]
    assert ANNOTATION_FORCES_INPLACE_UPDATE in td2.annotations


def test_annotate_new_task_in_new_group():
    old = mock.job()
    new = old.copy()
    extra = old.task_groups[0].copy()
    extra.name = "extra"
    new.task_groups.append(extra)
    d = job_diff(old, new)
    annotate(d, None)
    tg = next(t for t in d.task_groups if t.name == "extra")
    for td in tg.tasks:
        assert ANNOTATION_FORCES_CREATE in td.annotations


# -- server.job_plan end-to-end --------------------------------------------


def test_job_plan_dry_run(server):
    node = mock.node()
    server.node_register(node)
    job = mock.job()
    resp = server.job_plan(job)
    assert resp.diff is not None
    assert resp.diff.type == DIFF_TYPE_ADDED
    assert resp.annotations is not None
    tg = job.task_groups[0].name
    assert resp.annotations.desired_tg_updates[tg].place == job.task_groups[0].count
    # dry run must not mutate state
    assert server.state.job_by_id(None, job.id) is None


def test_job_plan_reports_failed_placements(server):
    # No nodes registered: every placement must fail, and the dry-run
    # response must surface the per-TG AllocMetric forensics.
    job = mock.job()
    resp = server.job_plan(job)
    tg = job.task_groups[0].name
    assert tg in resp.failed_tg_allocs
    assert resp.failed_tg_allocs[tg].nodes_evaluated == 0


def test_job_plan_update_diff(server):
    node = mock.node()
    server.node_register(node)
    job = mock.job()
    server.job_register(job)
    import time
    deadline = time.time() + 5
    while time.time() < deadline:
        allocs = server.job_allocations(job.id)
        if len(allocs) == job.task_groups[0].count:
            break
        time.sleep(0.05)
    new = job.copy()
    new.task_groups[0].count += 1
    resp = server.job_plan(new)
    assert resp.diff.type == DIFF_TYPE_EDITED
    assert resp.job_modify_index > 0
    f = next(f for f in resp.diff.task_groups[0].fields if f.name == "Count")
    assert ANNOTATION_FORCES_CREATE in f.annotations
