"""Eval-lifecycle tracing plane (nomad_tpu/utils/tracing.py): span
mechanics, the end-to-end trace of an eval through the TPU batch
pipeline, the HTTP query surface, and the chaos-correlation contract
(nack-redelivered evals show per-attempt spans with the nack reason)."""
import json
import time
import urllib.error
import urllib.request

import pytest

import conftest

from nomad_tpu import fault, mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import structs as s
from nomad_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Every test gets its own armed store; nothing leaks into tier-1."""
    tracing.enable()
    yield
    tracing.disable()
    fault.disarm()


def wait_until(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def make_node():
    n = mock.node()
    n.resources.networks = []
    n.reserved.networks = []
    return n


def make_job(count=2):
    j = mock.job()
    j.task_groups[0].count = count
    for t in j.task_groups[0].tasks:
        t.resources.networks = []
    return j


class TestTracerMechanics:
    def test_disabled_is_inert(self):
        tracing.disable()
        assert not tracing.enabled()
        with tracing.span("anything", eval_id="e1") as sp:
            sp.set(k="v")  # the no-op singleton tolerates attrs
        tracing.event("thing", eval_id="e1")
        tracing.record("thing", 0.0, 1.0, eval_id="e1")
        assert tracing.recent(10) == []
        assert tracing.trace_for_eval("e1") == []

    def test_nesting_parents_and_eval_inheritance(self):
        with tracing.span("outer", eval_id="e1") as outer:
            with tracing.span("inner") as inner:
                pass
            tracing.event("marker")
        spans = tracing.trace_for_eval("e1")
        by_name = {sp["Name"]: sp for sp in spans}
        # children inherit the eval id and parent pointer
        assert set(by_name) == {"outer", "inner", "marker"}
        assert by_name["inner"]["ParentID"] == by_name["outer"]["SpanID"]
        assert by_name["marker"]["ParentID"] == by_name["outer"]["SpanID"]
        assert by_name["outer"]["ParentID"] == 0
        for sp in spans:
            assert sp["End"] >= sp["Start"]

    def test_batch_eval_ids_index_under_every_member(self):
        with tracing.span("batch", eval_ids=["a", "b"]):
            pass
        assert [sp["Name"] for sp in tracing.trace_for_eval("a")] == ["batch"]
        assert [sp["Name"] for sp in tracing.trace_for_eval("b")] == ["batch"]

    def test_eval_ids_capped_per_span(self):
        ids = [f"e{i}" for i in range(200)]
        with tracing.span("batch", eval_ids=ids):
            pass
        (sp,) = tracing.trace_for_eval("e0")
        assert len(sp["Attrs"]["eval_ids"]) == tracing.MAX_EVAL_IDS_PER_SPAN
        assert sp["Attrs"]["eval_ids_elided"] == 200 - \
            tracing.MAX_EVAL_IDS_PER_SPAN
        # ids past the cap are not indexed; ids within it are
        assert tracing.trace_for_eval("e199") == []
        assert tracing.trace_for_eval(
            f"e{tracing.MAX_EVAL_IDS_PER_SPAN - 1}")

    def test_exception_recorded_on_span(self):
        with pytest.raises(ValueError):
            with tracing.span("boom", eval_id="e2"):
                raise ValueError("kapow")
        (sp,) = tracing.trace_for_eval("e2")
        assert sp["Attrs"]["error"] == "ValueError"
        assert "kapow" in sp["Attrs"]["error_detail"]

    def test_store_is_bounded(self):
        tr = tracing.enable(capacity=32, max_evals=4)
        for i in range(100):
            tr.event("tick", eval_id=f"e{i}")
        assert len(tr.recent(1000)) <= 32
        # LRU eval index: only the newest ids are retained
        assert tracing.trace_for_eval("e0") == []
        assert tracing.trace_for_eval("e99")

    def test_fault_fire_correlation(self):
        with fault.scenario({"seed": 3, "faults": [
                {"point": "heartbeat.deliver", "action": "drop",
                 "times": 1}]}):
            with tracing.span("lifecycle", eval_id="e3"):
                fault.faultpoint("heartbeat.deliver", node_id="n1")
        spans = tracing.trace_for_eval("e3")
        fires = [sp for sp in spans if sp["Name"] == "fault.fire"]
        assert len(fires) == 1
        assert fires[0]["Attrs"] == {"point": "heartbeat.deliver",
                                     "rule": 0, "action": "drop",
                                     "eval_id": "e3"}


class TestEvalLifecycleTrace:
    def test_single_eval_batch_pipeline_trace(self):
        """Acceptance: one eval through TPUBatchScheduler yields a
        queryable trace covering enqueue → dequeue → batch phases →
        plan-submit → apply, with monotonic timestamps."""
        srv = Server(ServerConfig(num_schedulers=1,
                                  use_tpu_batch_worker=True,
                                  batch_size=8))
        srv.start()
        try:
            for _ in range(3):
                srv.node_register(make_node())
            job = make_job(2)
            _, eval_id = srv.job_register(job)
            assert wait_until(
                lambda: srv.state.eval_by_id(None, eval_id) is not None
                and srv.state.eval_by_id(None, eval_id).status
                == s.EVAL_STATUS_COMPLETE, timeout=30.0)
            assert wait_until(
                lambda: len(srv.state.allocs_by_job(None, job.id, True))
                == 2, timeout=30.0)
            # the ack event lands just after the status write — wait for it
            assert wait_until(
                lambda: any(sp["Name"] == "broker.ack"
                            for sp in tracing.trace_for_eval(eval_id)),
                timeout=10.0)

            spans = tracing.trace_for_eval(eval_id)
            names = [sp["Name"] for sp in spans]
            for expected in ("broker.enqueue", "broker.dequeue",
                             "batch.schedule", "batch.phase1",
                             "batch.finalize", "worker.submit_plan",
                             "plan.evaluate", "plan.apply", "broker.ack"):
                assert expected in names, (expected, names)
            by_name = {sp["Name"]: sp for sp in spans}
            # timestamps are monotonic along the lifecycle ordering
            order = ["broker.enqueue", "broker.dequeue", "batch.schedule",
                     "worker.submit_plan", "plan.evaluate", "plan.apply"]
            starts = [by_name[n]["Start"] for n in order]
            assert starts == sorted(starts), list(zip(order, starts))
            for sp in spans:
                assert sp["End"] >= sp["Start"]
            # phases are parented under the batch.schedule root
            root = by_name["batch.schedule"]["SpanID"]
            assert by_name["batch.phase1"]["ParentID"] == root
            assert by_name["batch.finalize"]["ParentID"] == root
        finally:
            srv.shutdown()


class TestTraceHTTP:
    def test_trace_endpoints(self):
        from nomad_tpu.agent.agent import Agent

        cfg = conftest.dev_test_config()
        cfg.client.enabled = False
        agent = Agent(cfg)
        agent.start()
        try:
            agent.server.node_register(make_node())
            job = make_job(1)
            _, eval_id = agent.server.job_register(job)
            assert wait_until(
                lambda: agent.server.state.allocs_by_job(None, job.id,
                                                         True), timeout=30.0)
            assert wait_until(
                lambda: tracing.trace_for_eval(eval_id), timeout=10.0)

            with urllib.request.urlopen(
                    agent.http.address + f"/v1/trace/eval/{eval_id}") as r:
                body = json.loads(r.read())
            assert body["EvalID"] == eval_id
            assert any(sp["Name"] == "broker.enqueue"
                       for sp in body["Spans"])
            assert all("DurationMs" in sp for sp in body["Spans"])

            with urllib.request.urlopen(
                    agent.http.address + "/v1/traces?recent=5") as r:
                body = json.loads(r.read())
            assert body["Enabled"] is True
            assert 0 < len(body["Spans"]) <= 5

            # unknown eval → 404
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    agent.http.address + "/v1/trace/eval/nope")
            assert exc.value.code == 404
        finally:
            agent.shutdown()

    def test_traces_endpoint_reports_disabled(self):
        from nomad_tpu.agent.agent import Agent

        tracing.disable()
        cfg = conftest.dev_test_config()
        cfg.client.enabled = False
        agent = Agent(cfg)
        agent.start()
        try:
            with urllib.request.urlopen(
                    agent.http.address + "/v1/traces") as r:
                body = json.loads(r.read())
            assert body == {"Enabled": False, "Spans": []}
        finally:
            agent.shutdown()


@pytest.mark.chaos
class TestChaosTraceCorrelation:
    def test_nack_redelivery_shows_two_attempts_with_reason(self):
        """A plan-apply crash burns delivery attempt 1; the broker
        redelivers and attempt 2 completes.  The eval's trace must show
        BOTH worker attempt spans, the first carrying the nack reason."""
        srv = Server(ServerConfig(num_schedulers=1))
        srv.eval_broker.initial_nack_delay = 0.1
        srv.start()
        try:
            for _ in range(3):
                srv.node_register(make_node())
            fault.arm({"seed": 21, "faults": [
                {"point": "plan.apply", "action": "crash", "times": 1}]})
            job = make_job(2)
            _, eval_id = srv.job_register(job)
            assert wait_until(
                lambda: srv.state.eval_by_id(None, eval_id).status
                == s.EVAL_STATUS_COMPLETE, timeout=30.0)
            assert fault.trace() == [("plan.apply", 0, "crash")]
            # attempt spans finish just after the status write
            assert wait_until(
                lambda: sum(sp["Name"] == "worker.attempt"
                            for sp in tracing.trace_for_eval(eval_id))
                >= 2, timeout=10.0)

            spans = tracing.trace_for_eval(eval_id)
            attempts = [sp for sp in spans
                        if sp["Name"] == "worker.attempt"]
            assert len(attempts) == 2, [sp["Name"] for sp in spans]
            attempts.sort(key=lambda sp: sp["Start"])
            assert attempts[0]["Attrs"]["attempt"] == 1
            assert attempts[1]["Attrs"]["attempt"] == 2
            assert "InjectedFault" in attempts[0]["Attrs"]["nack_reason"]
            assert "nack_reason" not in attempts[1]["Attrs"]
            # the broker recorded the redelivery decision too
            nacks = [sp for sp in spans if sp["Name"] == "broker.nack"]
            assert len(nacks) == 1
            assert nacks[0]["Attrs"]["outcome"] == "requeue"
            # and the injected fault itself is correlated into the trace
            fires = [sp for sp in spans if sp["Name"] == "fault.fire"]
            assert len(fires) == 1
            assert fires[0]["Attrs"]["point"] == "plan.apply"
        finally:
            srv.shutdown()
