"""Invariant analysis plane tests (ISSUE 15).

- Seeded known-bad fixture snippets asserting each rule family fires,
  including regression fixtures reproducing the PR 9 fsync-under-lock
  and PR 10 drain-under-lock shapes.
- The tree itself ships green: ``run_checks()`` returns zero
  unsuppressed violations (the acceptance gate bench --check enforces).
- Runtime lockcheck units: a seeded inversion is caught with a witness
  cycle, the Condition protocol tracks manual release windows, and the
  disarmed state costs one module-global load (nothing patched).
- The sanitized native corpus leg (slow tier).
"""
from __future__ import annotations

import ast
import os
import threading
import time

import pytest

from nomad_tpu.analysis import (SourceFile, Allowlist, iter_source_files,
                                repo_root, run_checks)
from nomad_tpu.analysis import guardrules, jaxrules, knobrules, lockrules
from nomad_tpu.utils import knobs, lockcheck

pytestmark = pytest.mark.analysis

ROOT = repo_root()


def _sf(path: str, source: str) -> SourceFile:
    return SourceFile(path=path, abspath=os.path.join("/fake", path),
                      source=source, tree=ast.parse(source))


def _rules(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# rule family 1: lock discipline
# ---------------------------------------------------------------------------


class TestLockRules:
    def test_pr9_fsync_under_lock_fires(self):
        # The PR 9 regression shape: the WAL append fsyncs while the
        # raft log lock is held — group commit structurally impossible.
        src = (
            "import os\n"
            "import threading\n"
            "class RaftLog:\n"
            "    def __init__(self):\n"
            "        self._l = threading.Lock()\n"
            "    def apply(self, entry):\n"
            "        with self._l:\n"
            "            self._fh.write(entry)\n"
            "            os.fsync(self._fh.fileno())\n"
        )
        out = lockrules.check(ROOT, [_sf("nomad_tpu/server/fake_raft.py",
                                         src)])
        assert any(v.rule == "lock-blocking" and "fsync" in v.detail
                   for v in out), out

    def test_pr10_drain_under_lock_fires(self):
        # The PR 10 regression shape: the snapshot path drains the
        # apply sequencer (a sleep-poll loop) while the log lock is
        # held — flagged through the one-level helper propagation.
        src = (
            "import threading\n"
            "import time\n"
            "class FileLog:\n"
            "    def __init__(self):\n"
            "        self._l = threading.RLock()\n"
            "    def _drain_appliers(self):\n"
            "        while self._inflight:\n"
            "            time.sleep(0.01)\n"
            "    def snapshot(self):\n"
            "        with self._l:\n"
            "            self._drain_appliers()\n"
        )
        out = lockrules.check(ROOT, [_sf("nomad_tpu/server/fake_log.py",
                                         src)])
        assert any(v.rule == "lock-blocking"
                   and "_drain_appliers" in v.detail for v in out), out

    def test_lock_order_cycle_fires_with_witness(self):
        src = (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def rev(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        )
        out = lockrules.check(ROOT, [_sf("nomad_tpu/server/fake_cyc.py",
                                         src)])
        cyc = [v for v in out if v.rule == "lock-order"]
        assert cyc and "_a" in cyc[0].message and "_b" in cyc[0].message

    def test_condition_wait_not_blocking(self):
        src = (
            "import threading\n"
            "class Broker:\n"
            "    def __init__(self):\n"
            "        self._l = threading.RLock()\n"
            "        self._cond = threading.Condition(self._l)\n"
            "    def dequeue(self):\n"
            "        with self._l:\n"
            "            while not self._ready:\n"
            "                self._cond.wait(1.0)\n"
        )
        out = lockrules.check(ROOT, [_sf("nomad_tpu/server/fake_bk.py",
                                         src)])
        assert not [v for v in out if v.rule == "lock-blocking"], out

    def test_clean_region_silent(self):
        src = (
            "import os\n"
            "import threading\n"
            "class Log:\n"
            "    def __init__(self):\n"
            "        self._l = threading.Lock()\n"
            "    def apply(self, entry):\n"
            "        with self._l:\n"
            "            seq = self._wal.write(entry)\n"
            "        os.fsync(self._fh.fileno())\n"
        )
        out = lockrules.check(ROOT, [_sf("nomad_tpu/server/fake_ok.py",
                                         src)])
        assert out == []


# ---------------------------------------------------------------------------
# rule family 2: jax discipline
# ---------------------------------------------------------------------------


class TestJaxRules:
    def test_donated_reuse_fires(self):
        src = (
            "import jax\n"
            "_apply = jax.jit(_impl, donate_argnums=(0,))\n"
            "def step(buf, delta):\n"
            "    out = _apply(buf, delta)\n"
            "    return buf.sum()\n"  # use-after-donation
        )
        out = jaxrules.check(ROOT, [_sf("nomad_tpu/ops/fake_don.py",
                                        src)])
        assert any(v.rule == "jax-donated-reuse" for v in out), out

    def test_donated_rebind_ok_and_args_not_reuse(self):
        src = (
            "import jax\n"
            "_apply = jax.jit(_impl, donate_argnums=(0,))\n"
            "def step(buf, delta):\n"
            "    buf = _apply(buf, delta)\n"
            "    return buf.sum()\n"  # rebound: the aliased result
        )
        out = jaxrules.check(ROOT, [_sf("nomad_tpu/ops/fake_ok.py",
                                        src)])
        assert not [v for v in out if v.rule == "jax-donated-reuse"], out

    def test_host_sync_fires_in_hot_path_only(self):
        src = (
            "import jax\n"
            "def fetch(buf):\n"
            "    return jax.device_get(buf)\n"
        )
        hot = jaxrules.check(ROOT, [_sf("nomad_tpu/ops/fake_sync.py",
                                        src)])
        assert any(v.rule == "jax-host-sync" for v in hot)
        cold = jaxrules.check(ROOT, [_sf("nomad_tpu/server/fake.py",
                                         src)])
        assert cold == []

    def test_note_signature_escape_fires(self):
        src = (
            "import jax\n"
            "_fn = jax.jit(_impl, static_argnames=('n',))\n"
        )
        out = jaxrules.check(ROOT, [_sf("nomad_tpu/ops/fake_jit.py",
                                        src)])
        assert any(v.rule == "jax-note-signature" for v in out), out
        src_ok = src + (
            "def run(x):\n"
            "    note_signature('fake', (1,))\n"
            "    return _fn(x)\n"
        )
        out = jaxrules.check(ROOT, [_sf("nomad_tpu/ops/fake_jit2.py",
                                        src_ok)])
        assert not [v for v in out if v.rule == "jax-note-signature"]


# ---------------------------------------------------------------------------
# rule families 3+4 against the real tree, plus seeded negatives
# ---------------------------------------------------------------------------


class TestGuardAndKnobRules:
    def test_real_tree_guard_coverage_clean(self):
        from nomad_tpu.analysis import load_tree

        files = load_tree(ROOT)
        assert guardrules.check(ROOT, files) == []

    def test_unclaimed_native_source_fires(self, tmp_path):
        # A fake root with one .cc and an empty registry.
        (tmp_path / "nomad_tpu" / "native").mkdir(parents=True)
        (tmp_path / "nomad_tpu" / "ops").mkdir(parents=True)
        (tmp_path / "nomad_tpu" / "utils").mkdir(parents=True)
        (tmp_path / "nomad_tpu" / "native" / "rogue.cc").write_text(
            "// unguarded native code\n")
        (tmp_path / "nomad_tpu" / "ops" / "guards.py").write_text(
            "REGISTRY = []\n"
            "def native_sources():\n"
            "    return []\n")
        knobs_src = open(os.path.join(
            ROOT, "nomad_tpu/utils/knobs.py")).read()
        (tmp_path / "nomad_tpu" / "utils" / "knobs.py").write_text(
            knobs_src)
        out = guardrules.check(str(tmp_path), [])
        assert any("unclaimed-native-source" in v.detail for v in out)

    def test_adhoc_env_read_fires(self):
        src = (
            "import os\n"
            "def enabled():\n"
            "    return os.environ.get('NOMAD_TPU_FUSED') == '1'\n"
        )
        out = knobrules.check(ROOT, [_sf("nomad_tpu/fake_knob.py", src)])
        mine = [v for v in out if v.path == "nomad_tpu/fake_knob.py"]
        assert any(v.rule == "knob-env-read" for v in mine), out

    def test_env_read_through_module_constant_fires(self):
        src = (
            "import os\n"
            "CHILD = 'NOMAD_TPU_BENCH_CHILD'\n"
            "def is_child():\n"
            "    return os.environ.get(CHILD) == '1'\n"
        )
        out = knobrules.check(ROOT, [_sf("nomad_tpu/fake_knob2.py",
                                         src)])
        mine = [v for v in out if v.path == "nomad_tpu/fake_knob2.py"]
        assert any(v.rule == "knob-env-read" for v in mine), out

    def test_unregistered_knob_token_fires(self):
        src = "FLAG = 'NOMAD_TPU_TOTALLY_NEW_KNOB'\n"
        out = knobrules.check(ROOT, [_sf("nomad_tpu/fake_knob3.py",
                                         src)])
        mine = [v for v in out if v.path == "nomad_tpu/fake_knob3.py"]
        assert any(v.rule == "knob-unregistered" for v in mine), out

    def test_env_write_is_legal(self):
        src = (
            "import os\n"
            "def arm():\n"
            "    os.environ['NOMAD_TPU_FUSED'] = '0'\n"
            "    os.environ.pop('NOMAD_TPU_QUANT', None)\n"
        )
        out = knobrules.check(ROOT, [_sf("nomad_tpu/fake_knob4.py",
                                         src)])
        mine = [v for v in out
                if v.path == "nomad_tpu/fake_knob4.py"
                and v.rule == "knob-env-read"]
        assert mine == []

    def test_knob_accessors(self, monkeypatch):
        with pytest.raises(knobs.UnknownKnobError):
            knobs.get_bool("NOMAD_TPU_NOT_A_KNOB")
        monkeypatch.setenv("NOMAD_TPU_FUSED", "off")
        assert knobs.get_bool("NOMAD_TPU_FUSED") is False
        monkeypatch.setenv("NOMAD_TPU_FUSED", "")
        assert knobs.get_bool("NOMAD_TPU_FUSED") is True  # default
        monkeypatch.setenv("NOMAD_TPU_PLAN_PIPELINE", "garbage")
        assert knobs.get_int("NOMAD_TPU_PLAN_PIPELINE") == 8  # default
        monkeypatch.setenv("NOMAD_TPU_RNG_SEED", "123")
        assert knobs.get_int("NOMAD_TPU_RNG_SEED") == 123
        monkeypatch.delenv("NOMAD_TPU_RNG_SEED")
        assert knobs.get_int("NOMAD_TPU_RNG_SEED") is None
        assert knobs.raw("NOMAD_TPU_RNG_SEED") is None

    def test_readme_table_in_sync(self):
        text = open(os.path.join(ROOT, "README.md")).read()
        start = text.index(knobs.TABLE_BEGIN)
        stop = text.index(knobs.TABLE_END) + len(knobs.TABLE_END)
        assert text[start:stop] == knobs.render_readme_table()


# ---------------------------------------------------------------------------
# the allowlist mechanism
# ---------------------------------------------------------------------------


class TestAllowlist:
    def test_stale_entry_fails(self, tmp_path):
        allow = tmp_path / "allow.txt"
        allow.write_text("lock-blocking nomad_tpu/nope.py::f::x  "
                         "# covers nothing\n")
        active, _sup = run_checks(ROOT, allowlist_path=str(allow))
        assert any(v.rule == "allowlist" and "stale" in v.detail
                   for v in active)

    def test_entry_without_reason_fails(self, tmp_path):
        allow = tmp_path / "allow.txt"
        allow.write_text("lock-blocking nomad_tpu/x.py::f::y\n")
        active, _sup = run_checks(ROOT, allowlist_path=str(allow))
        assert any(v.rule == "allowlist" and "malformed" in v.detail
                   for v in active)


# ---------------------------------------------------------------------------
# the acceptance gate: the tree ships green
# ---------------------------------------------------------------------------


class TestTreeShipsGreen:
    def test_whole_tree_zero_unsuppressed_violations(self):
        active, suppressed = run_checks(ROOT)
        assert active == [], "\n".join(v.render() for v in active)
        # The allowlist is genuinely exercised (the justified shapes).
        assert len(suppressed) >= 10

    def test_every_source_file_scanned(self):
        paths = iter_source_files(ROOT)
        assert "nomad_tpu/server/raft.py" in paths
        assert "bench.py" in paths
        assert not any(p.startswith("tests/") for p in paths)


# ---------------------------------------------------------------------------
# runtime lockcheck
# ---------------------------------------------------------------------------


class TestLockcheck:
    def setup_method(self):
        assert not lockcheck.armed()

    def teardown_method(self):
        lockcheck.disarm()

    def test_seeded_inversion_caught_with_witness(self):
        lockcheck.arm()
        a = lockcheck.make_tracked("t:a")
        b = lockcheck.make_tracked("t:b")
        with a:
            with b:
                pass
        assert lockcheck.find_cycle() is None
        done = []

        def invert():
            with b:
                with a:
                    done.append(True)

        t = threading.Thread(target=invert)
        t.start()
        t.join(5)
        assert done
        with pytest.raises(lockcheck.LockOrderError) as exc:
            lockcheck.assert_acyclic()
        msg = str(exc.value)
        assert "t:a" in msg and "t:b" in msg

    def test_disarmed_is_unpatched_and_one_load(self):
        # Disarmed: the real primitives are in place...
        assert threading.Lock is lockcheck._REAL_LOCK
        assert threading.RLock is lockcheck._REAL_RLOCK
        assert time.sleep is lockcheck._REAL_SLEEP
        assert os.fsync is lockcheck._REAL_FSYNC
        # ...and a live wrapper's entire disarmed cost is the single
        # module-global load (_STATE is None short-circuits before any
        # tracking structure is touched).
        lk = lockcheck.make_tracked("t:disarmed")
        assert lockcheck._STATE is None
        with lk:
            assert lockcheck.held_tracked() == []
        lockcheck.arm()
        assert threading.Lock is not lockcheck._REAL_LOCK
        with lk:
            assert lockcheck.held_tracked() == ["t:disarmed"]
        lockcheck.disarm()
        assert threading.Lock is lockcheck._REAL_LOCK

    def test_armed_wraps_nomad_locks_only(self):
        lockcheck.arm()
        # A lock created from a nomad_tpu frame is wrapped: fake the
        # creation site by compiling with a nomad_tpu filename.
        fake = os.path.join(ROOT, "nomad_tpu", "_lockfixture.py")
        ns = {"threading": threading}
        exec(compile("def mk():\n    return threading.Lock()\n",
                     fake, "exec"), ns)
        assert isinstance(ns["mk"](), lockcheck.TrackedLock)
        # A lock created from foreign code (this test file) is real.
        assert not isinstance(threading.Lock(), lockcheck.TrackedLock)

    def test_rlock_reentry_no_self_edge(self):
        lockcheck.arm()
        r = lockcheck.make_tracked("t:r", rlock=True)
        with r:
            with r:
                pass
        assert lockcheck.edges() == {}
        assert lockcheck.held_tracked() == []

    def test_condition_wait_releases_held(self):
        lockcheck.arm()
        r = lockcheck.make_tracked("t:cv", rlock=True)
        cond = threading.Condition(r)
        observed = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                observed.append("woke")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        # The waiter released t:cv inside wait(): we can take it.
        got = r.acquire(timeout=2)
        assert got
        cond.notify_all()
        r.release()
        t.join(5)
        assert observed == ["woke"]

    def test_blocking_call_under_lock_recorded(self):
        lockcheck.arm()
        lk = lockcheck.make_tracked("t:hold")
        with lk:
            time.sleep(0)
        rec = lockcheck.blocking_calls()
        assert any(name == "t:hold" and kind == "time.sleep"
                   for name, kind, _site in rec), rec

    def test_maybe_arm_from_env(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_LOCKCHECK", "1")
        assert lockcheck.maybe_arm_from_env() is True
        assert lockcheck.armed()
        lockcheck.disarm()
        monkeypatch.setenv("NOMAD_TPU_LOCKCHECK", "0")
        assert lockcheck.maybe_arm_from_env() is False
        assert not lockcheck.armed()


# ---------------------------------------------------------------------------
# sanitized native corpus (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSanitizedCorpus:
    def test_asan_corpus_clean(self):
        from nomad_tpu.native.__main__ import run_sanitized

        verdict = run_sanitized(seed=0, log=lambda *a: None)
        assert verdict in ("ok", "skip"), verdict
